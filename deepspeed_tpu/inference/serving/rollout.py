"""Zero-downtime weight rollout: live checkpoint hot-swap with canary,
shadow traffic, and automatic rollback.

ROADMAP item 5, and the composition this repo's serving tier has been
building toward: checkpoint commit records (PR 1), the warm-spare
autoscaler pool (PR 14), router surgery (PR 12), and the SIGTERM drain
path, driven by one supervisor-side state machine::

       idle ──new committed tag──▶ staging ──canaries attached──▶ canary
        ▲                            │                              │
        │                    boot/verify failed               soak gates met
        │                            ▼                              ▼
        │◀──fleet recovered── rolling_back ◀──regression──── promoting
        │                                                           │
        └───────────────◀─── committed ◀──incumbents drained────────┘

- **idle -> staging**: a :class:`TagWatcher` poll observes a newly
  committed manifest tag (the atomically-written commit record, so a
  torn checkpoint is invisible by construction). The tag directory is
  re-verified against its manifest before any process boots on it; a
  corrupt tag is blacklisted and the rollout never starts.
- **staging -> canary**: ``canary_replicas`` workers boot on the new
  weights — warm spares from the autoscaler pool when one is wired in,
  cold spawns otherwise — and attach to the router tagged with the new
  generation. The router then routes a deterministic
  ``canary_fraction`` slice of NEW requests onto them, chosen by the
  same prompt-prefix hash the affinity policy uses, so cache locality
  survives the split and a given prefix sticks to one side of it.
- **canary soak**: live requests completed by the incumbent are sampled
  at ``shadow_sample_rate`` and replayed against the canary over the
  replica wire protocol; outputs are diffed bitwise (greedy decode is
  deterministic per generation) and latency is tracked per request
  class. The canary must hold ``canary_hold_s``, carry
  ``min_canary_requests`` live attempts, and survive
  ``min_shadow_compared`` shadow compares before promotion.
- **promoting -> committed**: the remaining new-generation capacity
  attaches, then each incumbent leaves through the existing drain path
  (``remove_endpoint`` + SIGTERM): in-flight work finishes where it is,
  retries stay generation-pinned, and the idempotency-key oracle proves
  no request was dropped or double-completed across the swap.
- **any regression -> rolling_back**: a firing SLO alert, a shadow diff
  rate above ``shadow_diff_threshold``, or a canary crash-loop tears
  the canary down the same drain path, blacklists the tag, and the
  machine waits for the fleet to probe healthy on the incumbent
  generation — bounded by ``recovery_bound_s`` (asserted by the chaos
  harness).

Clock-injectable and single-steppable (``step(now)``) like the
autoscaler, so tests and the chaos harness drive it deterministically;
``start()`` runs the same step on a background thread. Stdlib-only: the
supervisor process never imports jax.
"""

import os
import random
import socket
import sys
import threading
import time
import uuid
from collections import deque

from deepspeed_tpu.inference.serving.config import RolloutConfig
from deepspeed_tpu.inference.serving.metrics import RolloutMetrics
from deepspeed_tpu.inference.serving.router import (
    PROTOCOL_VERSION,
    _http_json,
    read_line,
    send_line,
)
from deepspeed_tpu.runtime.checkpoint.manifest import (
    CheckpointCorruptionError,
    TagWatcher,
    verify_tag_dir,
)


class RolloutController:
    """Supervisor-side weight-rollout state machine over one Router.

    Parameters
    ----------
    router : Router
        The live routing front-door. The controller attaches/removes
        endpoints, sets the canary slice, and installs a completion tap
        for shadow sampling.
    spawner : ProcessReplicaSpawner (or compatible)
        Boots replicas on a weight generation (``spawn(name=...,
        generation=tag)``) and owns the SIGTERM drain (``drain``).
    watch : TagWatcher | str
        A manifest watcher, or a checkpoint save-dir root to build one
        over. New committed tags observed here trigger rollouts.
    replicas : iterable of handles
        The ALREADY-ROUTED incumbent handles (name-matched to the
        router's endpoints), so promotion can drain the processes it
        detaches — same contract as the autoscaler.
    autoscaler : Autoscaler, optional
        When wired in, canaries come from its warm-spare pool
        (``take_spares``) and its pool is retargeted on commit/rollback
        (``set_weight_tag``) so refills track the serving generation.
    alerts : optional
        SLO pressure signal for the rollback trigger: an ``/alerts``
        URL, an object with ``alerts_doc()``, or a callable returning a
        bool/doc. Unreadable = not firing (an unreachable alerts
        endpoint must not tear down a healthy canary).
    incumbent_tag : str
        Weight generation the current fleet serves (must match the
        routed endpoints' ``generation``).
    """

    def __init__(self, router, spawner, watch, config=None, replicas=(),
                 autoscaler=None, alerts=None, metrics=None, registry=None,
                 clock=time.monotonic, incumbent_tag="0", verify_deep=False,
                 rng=None):
        self.router = router
        self.spawner = spawner
        self.watcher = watch if isinstance(watch, TagWatcher) \
            else TagWatcher(str(watch))
        self.config = config or RolloutConfig(enabled=True)
        self.autoscaler = autoscaler
        self._alerts = alerts
        self.metrics = metrics or RolloutMetrics()
        self._clock = clock
        self._rng = rng or random.Random()
        self.verify_deep = bool(verify_deep)
        self.current_tag = str(incumbent_tag)
        self._lock = threading.Lock()
        self._incumbents = {h.name: h for h in replicas}
        self._canaries = {}             # name -> handle, this rollout
        self._dead_canaries = set()     # names already counted as crashed
        self._bad_tags = set()          # blacklisted (corrupt / rolled back)
        self.phase = "idle"
        self._target_tag = None
        self._canary_since = None
        self._canary_routed_base = 0
        self._boot_seq = 0
        self._rollback_started = None
        self._shadow_pending = deque(
            maxlen=max(1, self.config.shadow_max_pending))
        self._thread = None
        self._stop = threading.Event()
        if registry is not None:
            self.export_gauges(registry)

    # -- observability ----------------------------------------------------
    def status(self):
        with self._lock:
            canaries = list(self._canaries)
        return {
            "phase": self.phase,
            "current_tag": self.current_tag,
            "target_tag": self._target_tag,
            "canaries": canaries,
            "bad_tags": sorted(self._bad_tags),
            "canary_routed": self._canary_routed_delta(),
            "shadow_compared": self.metrics.shadow_compared_total,
            "shadow_diffs": self.metrics.shadow_diff_total,
            "rollbacks_total": self.metrics.rollbacks_total,
            "commits_total": self.metrics.commits_total,
        }

    def export_gauges(self, registry):
        self.metrics.export_to(registry)
        return registry

    def _set_phase(self, phase):
        self.phase = phase
        self.metrics.set_phase(phase)
        self._note("rollout/phase", phase=phase, tag=self._target_tag)

    # -- the pressure signal (same shapes the autoscaler accepts) ---------
    def _alert_firing(self):
        src = self._alerts
        if src is None:
            return False
        try:
            if isinstance(src, str):
                url = src if src.endswith("/alerts") \
                    else src.rstrip("/") + "/alerts"
                doc = _http_json(url, 2.0)
            elif hasattr(src, "alerts_doc"):
                doc = src.alerts_doc()[1]
            else:
                doc = src()
        except Exception:
            return False        # unreadable must not tear down a canary
        if isinstance(doc, bool):
            return doc
        if isinstance(doc, dict):
            return bool(doc.get("firing", 0)) \
                or doc.get("status") == "alerting"
        return bool(doc)

    # -- one control tick -------------------------------------------------
    def step(self, now=None):
        """One deterministic tick; returns the transition taken (e.g.
        "staged", "canary", "promoted", "committed", "rolled_back",
        "rejected_tag") or None when the machine held its state."""
        now = self._clock() if now is None else now
        handler = {
            "idle": self._step_idle,
            "staging": self._step_staging,
            "canary": self._step_canary,
            "promoting": self._step_promoting,
            "rolling_back": self._step_rolling_back,
            "committed": self._step_committed,
        }[self.phase]
        return handler(now)

    def _step_idle(self, now):
        observed = self.watcher.poll()
        if observed is None:
            return None
        tag, _seq = observed
        if tag == self.current_tag or tag in self._bad_tags:
            return None
        tag_dir = os.path.join(self.watcher.root, tag)
        try:
            verify_tag_dir(tag_dir, deep=self.verify_deep)
        except CheckpointCorruptionError as e:
            # never boot a replica on a tag that fails its own manifest
            self._bad_tags.add(tag)
            self._note("rollout/corrupt_tag", tag=tag, error=str(e))
            return "rejected_tag"
        self._target_tag = tag
        self._dead_canaries.clear()
        self._shadow_pending.clear()
        self.metrics.begin_rollout(tag)
        self.phase = "staging"          # begin_rollout set the gauge
        self._note("rollout/begin", tag=tag)
        return "staged"

    def _boot_canaries(self, tag, n):
        handles = []
        if self.autoscaler is not None:
            handles = self.autoscaler.take_spares(tag, n)
        while len(handles) < n:
            # names must stay unique across the staging AND promoting
            # boots of one rollout (the router refuses duplicates)
            self._boot_seq += 1
            try:
                handles.append(self.spawner.spawn(
                    name=f"canary-{tag}-{self._boot_seq}", generation=tag))
            except Exception as e:
                self._note("rollout/spawn_failed", tag=tag, error=str(e))
                break
        return handles

    def _step_staging(self, now):
        tag = self._target_tag
        handles = self._boot_canaries(tag, max(1, self.config.canary_replicas))
        if not handles:
            self._bad_tags.add(tag)
            self._target_tag = None
            self._set_phase("idle")
            self._note("rollout/abort", tag=tag, reason="canary_boot_failed")
            return "rejected_tag"
        with self._lock:
            for h in handles:
                self._canaries[h.name] = h
        for h in handles:
            self.router.add_endpoint(h.endpoint(), generation=tag)
        self._canary_routed_base = \
            self.router.counters().get("canary_routed", 0)
        self.router.set_canary(tag, self.config.canary_fraction)
        if self.config.shadow_sample_rate > 0:
            self.router.set_completion_tap(self._on_completion)
        self._canary_since = now
        self._set_phase("canary")
        return "canary"

    def _canary_routed_delta(self):
        if self._target_tag is None:
            return 0
        routed = self.router.counters().get("canary_routed", 0)
        return max(0, routed - self._canary_routed_base)

    def _regression(self):
        """First firing rollback trigger, or None."""
        cfg = self.config
        crashed = 0
        with self._lock:
            canaries = list(self._canaries.values())
        for h in canaries:
            if h.name in self._dead_canaries:
                crashed += 1
                continue
            if not h.alive():
                self._dead_canaries.add(h.name)
                self.metrics.record_canary_crash()
                crashed += 1
        if "canary_crash" in cfg.rollback_on \
                and crashed >= max(1, cfg.max_canary_crashes):
            return "canary_crash"
        if "slo_alert" in cfg.rollback_on and self._alert_firing():
            return "slo_alert"
        if ("shadow_diff" in cfg.rollback_on
                and self.metrics.shadow_compared_total
                >= max(1, cfg.min_shadow_compared)
                and self.metrics.shadow_diff_rate()
                > cfg.shadow_diff_threshold):
            return "shadow_diff"
        return None

    def _step_canary(self, now):
        self._process_shadow()
        reason = self._regression()
        if reason is not None:
            return self._begin_rollback(reason, now)
        cfg = self.config
        if now - self._canary_since < cfg.canary_hold_s:
            return None
        if self._canary_routed_delta() < cfg.min_canary_requests:
            return None
        if (cfg.shadow_sample_rate > 0
                and self.metrics.shadow_compared_total
                < cfg.min_shadow_compared):
            return None
        self._set_phase("promoting")
        return "promoting"

    def _step_promoting(self, now):
        reason = self._regression()
        if reason is not None:
            return self._begin_rollback(reason, now)
        tag = self._target_tag
        with self._lock:
            incumbents = dict(self._incumbents)
            live_canaries = sum(1 for h in self._canaries.values()
                                if h.name not in self._dead_canaries)
        # widen the slice first: every unpinned request now prefers the
        # new generation while the incumbents drain out under it
        self.router.set_canary(tag, 1.0)
        shortfall = max(0, len(incumbents) - live_canaries)
        extra = self._boot_canaries(tag, shortfall) if shortfall else []
        with self._lock:
            for h in extra:
                self._canaries[h.name] = h
        for h in extra:
            self.router.add_endpoint(h.endpoint(), generation=tag)
        # one-at-a-time handoff down the drain path: detach (nothing new
        # lands, retries are generation-pinned), then SIGTERM (finish
        # in-flight, exit EXIT_PREEMPTED)
        for name, handle in incumbents.items():
            try:
                self.router.remove_endpoint(name)
            except ValueError:
                pass            # already detached (breaker/operator)
            self.spawner.drain(handle)
            with self._lock:
                self._incumbents.pop(name, None)
        self.router.clear_canary()
        self.router.set_completion_tap(None)
        with self._lock:
            promoted, self._canaries = self._canaries, {}
            self._incumbents.update(
                (n, h) for n, h in promoted.items()
                if n not in self._dead_canaries)
        self.current_tag = tag
        self._target_tag = None
        if self.autoscaler is not None:
            self.autoscaler.set_weight_tag(tag)
        self.metrics.record_commit()
        self._set_phase("committed")
        self._note("rollout/commit", tag=tag)
        return "committed"

    def _begin_rollback(self, reason, now):
        tag = self._target_tag
        # slice off first: every NEW request routes to the incumbent
        # generation from this instant
        self.router.clear_canary()
        self.router.set_completion_tap(None)
        with self._lock:
            canaries, self._canaries = self._canaries, {}
        for name, handle in canaries.items():
            try:
                self.router.remove_endpoint(name)
            except ValueError:
                pass
            # the same SIGTERM drain path scale-down uses: in-flight
            # canary work finishes where it is, nothing is dropped
            self.spawner.drain(handle)
        self._bad_tags.add(tag)
        self._shadow_pending.clear()
        self.metrics.record_rollback(reason)
        self._rollback_started = now
        if self.autoscaler is not None:
            self.autoscaler.set_weight_tag(self.current_tag)
        self._set_phase("rolling_back")
        self._note("rollout/rollback", tag=tag, reason=reason)
        return "rolled_back"

    def _step_rolling_back(self, now):
        eps = self.router.probe_all(force=True)
        settled = all(ep.generation == self.current_tag for ep in eps) \
            and any(ep.healthy and not ep.draining for ep in eps)
        if not settled:
            return None
        self.metrics.last_recovery_s = max(0.0, now - self._rollback_started)
        self._target_tag = None
        self._set_phase("idle")
        self._note("rollout/recovered",
                   recovery_s=self.metrics.last_recovery_s)
        return "recovered"

    def _step_committed(self, now):
        self._set_phase("idle")
        return None

    # -- shadow traffic ---------------------------------------------------
    def _on_completion(self, info):
        """Router completion tap: sample incumbent answers for replay."""
        if self.phase != "canary":
            return
        if info.get("generation") != self.current_tag:
            return              # only incumbent answers are references
        if self._rng.random() >= self.config.shadow_sample_rate:
            return
        # deque(maxlen) drops the oldest sample when full: shadowing
        # never applies backpressure to live traffic
        self._shadow_pending.append(info)

    def _live_canary_endpoint(self):
        tag = self._target_tag
        for ep in self.router.endpoints():
            if ep.generation == tag and not ep.removed \
                    and ep.name not in self._dead_canaries:
                return ep
        return None

    def _process_shadow(self):
        while self._shadow_pending:
            ep = self._live_canary_endpoint()
            if ep is None:
                return
            sample = self._shadow_pending.popleft()
            replayed = self._shadow_replay(ep, sample)
            if replayed is None:
                continue        # rejection/failure: not a quality signal
            self.metrics.record_shadow(replayed == sample["tokens"])

    def _shadow_replay(self, ep, sample, timeout_s=30.0):
        """Replay one sampled request against a canary endpoint over the
        replica wire protocol. Returns the token list, or None when the
        replay was rejected or failed (crash detection owns that)."""
        want = len(sample["tokens"])
        tokens = []
        try:
            with socket.create_connection(
                    (ep.host, ep.port), timeout=timeout_s) as sock:
                sock.settimeout(timeout_s)
                send_line(sock, {
                    "op": "submit", "v": PROTOCOL_VERSION,
                    "key": "shadow-" + uuid.uuid4().hex,
                    "prompt": sample["prompt"],
                    # pin the length so a shorter/longer canary answer
                    # still diffs positionally against the reference
                    "max_new_tokens": sample["max_new_tokens"] or want,
                    "eos_token_id": sample["eos_token_id"],
                    "timeout_s": timeout_s, "from": 0})
                stream = sock.makefile("rb")
                while True:
                    doc = read_line(stream)
                    if doc is None:
                        return None
                    if "t" in doc:
                        tokens.append(int(doc["t"]))
                    elif doc.get("done"):
                        return tokens
                    elif "rejected" in doc or "error" in doc:
                        return None
        except (OSError, ValueError):
            return None

    # -- background loop --------------------------------------------------
    def start(self):
        """Run ``step()`` every ``poll_interval_s`` on a daemon thread."""
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="rollout", daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.step()
            except Exception:
                pass            # the control loop must not die
            self._stop.wait(self.config.poll_interval_s)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None

    def drive(self, until=("committed",), timeout_s=120.0, tick_s=0.02):
        """Step the machine inline until the phase lands in ``until``
        (phase names, checked AFTER each step) or the deadline passes.
        Returns the final phase. For tests and the bench — production
        uses ``start()``."""
        deadline = time.monotonic() + timeout_s
        until = set(until)
        while time.monotonic() < deadline:
            self.step()
            if self.phase in until:
                return self.phase
            time.sleep(tick_s)
        return self.phase

    def _note(self, name, **args):
        if "deepspeed_tpu.telemetry" not in sys.modules:
            return
        try:
            from deepspeed_tpu import telemetry
            telemetry.instant(name, cat="fleet", args=args)
        except Exception:
            pass
