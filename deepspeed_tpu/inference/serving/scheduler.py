"""Continuous-batching scheduler: admission queue, bucketing, retirement.

Pure host-side policy — no jax. The scheduler decides *which* requests
run; the engine (engine.py) owns *how* (prefill/decode programs and the
KV pool). Keeping the policy import-light makes it unit-testable without
a device and reusable by any future engine variant.

Three decisions live here:

- **admission**: a bounded FIFO queue with named backpressure
  (``QueueFullError``) — under overload the caller learns immediately
  instead of the queue growing without bound; requests join the batch
  whenever a KV slot frees (join-at-free-slot), not at epoch boundaries.
- **bucketing**: prompt lengths are rounded up to a fixed ladder of
  bucket lengths, so the number of distinct prefill programs XLA ever
  compiles is bounded by ``len(buckets)`` no matter what lengths traffic
  brings (the recompile pin in tests/unit/test_serving.py).
- **retirement**: a sequence leaves its slot on EOS, on reaching its
  ``max_new_tokens``, or on blowing its per-request deadline
  (``RequestTimeoutError`` delivered through the request's future).
"""

import itertools
import threading
import time
from collections import deque


class QueueFullError(RuntimeError):
    """Admission queue is at capacity — backpressure signal to callers.

    Deliberately raised from ``submit()`` (not parked/blocked): a serving
    front-end under overload must shed or retry with its own policy."""


class EngineDrainingError(RuntimeError):
    """The engine is draining for a planned restart and admits nothing
    new; in-flight requests keep running to completion. A router should
    take the replica out of rotation and re-route, not retry here."""


class RequestTimeoutError(TimeoutError):
    """A request exceeded its deadline (queued or mid-decode) and was
    retired; delivered via the request's future."""

    def __init__(self, request_id, timeout_s, phase, tokens_done=0):
        self.request_id = request_id
        self.timeout_s = timeout_s
        self.phase = phase          # "queued" | "prefill" | "decoding"
        self.tokens_done = tokens_done
        super().__init__(
            f"request {request_id} exceeded its {timeout_s}s deadline "
            f"while {phase} ({tokens_done} token(s) generated)")


def default_buckets(max_prompt_len, smallest=8):
    """Power-of-two ladder up to (and including a cover of)
    ``max_prompt_len`` — log2 many prefill programs bound the compile
    count for arbitrary traffic."""
    buckets = []
    b = smallest
    while b < max_prompt_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_prompt_len)
    return tuple(buckets)


def bucket_for(length, buckets):
    """Smallest bucket >= length (buckets are validated ascending)."""
    for b in buckets:
        if length <= b:
            return b
    raise ValueError(
        f"prompt length {length} exceeds the largest bucket {buckets[-1]}")


class ServingFuture:
    """Result handle returned by ``submit()``.

    ``tokens`` is the streaming view (tokens emitted so far);
    ``result()`` blocks until retirement and returns the full token list
    or raises the retirement error (e.g. ``RequestTimeoutError``)."""

    def __init__(self, request_id):
        self.request_id = request_id
        self._tokens = []
        self._event = threading.Event()
        self._exc = None

    @property
    def tokens(self):
        return list(self._tokens)

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not finished after {timeout}s "
                "(serving loop not running?)")
        if self._exc is not None:
            raise self._exc
        return list(self._tokens)

    # engine-side hooks
    def _append(self, token):
        self._tokens.append(token)

    def _finish(self, exc=None):
        self._exc = exc
        self._event.set()


class Request:
    """One generation request plus its in-flight state."""

    def __init__(self, request_id, prompt, max_new_tokens, eos_token_id=None,
                 timeout_s=None, stream_cb=None, submitted_at=None):
        self.id = request_id
        self.prompt = prompt                    # list[int]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.timeout_s = timeout_s              # None = no deadline
        self.stream_cb = stream_cb
        self.future = ServingFuture(request_id)
        # submitted_at (monotonic) backdates a request that already waited
        # elsewhere — a PoolExhaustedError requeue or a router re-route
        # must NOT reset the deadline clock or the TTFT percentiles.
        self.submit_time = (time.monotonic() if submitted_at is None
                            else float(submitted_at))
        self.first_token_time = None            # TTFT endpoint
        self.slot = None
        self.emitted = 0
        self.prefix_entry = None                # held prefix-cache ref
        self.attn_impl = "dense"                # set by engine at admission

    def deadline_exceeded(self, now):
        return (self.timeout_s is not None
                and now - self.submit_time > self.timeout_s)


class ContinuousBatchingScheduler:
    """Bounded admission queue + bucketing + retirement policy."""

    def __init__(self, max_queue, buckets, default_max_new_tokens=64,
                 request_timeout_s=0.0):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        buckets = tuple(int(b) for b in buckets)
        if not buckets or any(b < 1 for b in buckets):
            raise ValueError(f"buckets must be positive ints, got {buckets}")
        if list(buckets) != sorted(set(buckets)):
            raise ValueError(
                f"buckets must be strictly ascending, got {buckets}")
        self.max_queue = int(max_queue)
        self.buckets = buckets
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.request_timeout_s = float(request_timeout_s)
        self._queue = deque()
        self._lock = threading.Lock()
        self._ids = itertools.count()
        # retirement counters (metrics reads these)
        self.completed = 0
        self.timed_out = 0
        # admission backpressure: requeue_front() calls (pool/chunk-lane
        # filled between pop and placement; pool-exhaustion requeues only
        # happen AFTER the engine attempted memory-pressure relief)
        self.requeues = 0

    def queue_depth(self):
        with self._lock:
            return len(self._queue)

    def submit(self, prompt, max_new_tokens=None, eos_token_id=None,
               timeout_s=None, stream_cb=None, submitted_at=None):
        """Enqueue a request; QueueFullError when at capacity.

        ``submitted_at`` (monotonic seconds) backdates the enqueue
        timestamp for a request that already waited somewhere else —
        e.g. one bounced off ``PoolExhaustedError`` backpressure or
        re-routed from a dead replica — so its deadline and TTFT clock
        keep running instead of silently resetting on retry."""
        if max_new_tokens is None:
            max_new_tokens = self.default_max_new_tokens
        if timeout_s is None and self.request_timeout_s > 0:
            timeout_s = self.request_timeout_s
        req = Request(next(self._ids), list(prompt), max_new_tokens,
                      eos_token_id=eos_token_id, timeout_s=timeout_s,
                      stream_cb=stream_cb, submitted_at=submitted_at)
        with self._lock:
            if len(self._queue) >= self.max_queue:
                raise QueueFullError(
                    f"admission queue is full ({self.max_queue} waiting); "
                    f"request rejected — retry with backpressure")
            self._queue.append(req)
        return req

    def enqueue(self, req):
        """Enqueue an ``adopt()``-minted request whose flags were set
        before it became visible to the serving loop (submit() races:
        the loop may admit between the append and any attribute write)."""
        with self._lock:
            if len(self._queue) >= self.max_queue:
                raise QueueFullError(
                    f"admission queue is full ({self.max_queue} waiting); "
                    f"request rejected — retry with backpressure")
            self._queue.append(req)
        return req

    def adopt(self, prompt, max_new_tokens=None, eos_token_id=None,
              timeout_s=None, stream_cb=None, submitted_at=None):
        """Mint a Request WITHOUT enqueueing it — for requests that
        bypass admission because their KV state already exists (a
        disaggregated handoff resume installs prefill-produced pages
        directly, so there is no prefill to queue for). The caller is
        responsible for activating the request on a pool slot."""
        if max_new_tokens is None:
            max_new_tokens = self.default_max_new_tokens
        if timeout_s is None and self.request_timeout_s > 0:
            timeout_s = self.request_timeout_s
        return Request(next(self._ids), list(prompt), max_new_tokens,
                       eos_token_id=eos_token_id, timeout_s=timeout_s,
                       stream_cb=stream_cb, submitted_at=submitted_at)

    def pop_expired(self, now):
        """Remove and return queued requests whose deadline passed while
        waiting (they must not waste a prefill)."""
        expired = []
        with self._lock:
            keep = deque()
            for req in self._queue:
                (expired if req.deadline_exceeded(now) else keep).append(req)
            self._queue = keep
        return expired

    def pop_next(self):
        """Next request to admit (FIFO), or None."""
        with self._lock:
            return self._queue.popleft() if self._queue else None

    def pop_matching(self, pred, max_n):
        """Pop up to ``max_n`` queued requests satisfying ``pred``,
        preserving FIFO order among them; non-matching requests keep
        their queue positions. The engine's batched-per-bucket prefill
        admission uses this to group same-bucket prompts into one
        prefill call."""
        if max_n < 1:
            return []
        taken = []
        with self._lock:
            keep = deque()
            for req in self._queue:
                if len(taken) < max_n and pred(req):
                    taken.append(req)
                else:
                    keep.append(req)
            self._queue = keep
        return taken

    def requeue_front(self, req):
        """Put an admitted-but-unplaced request back at the head (e.g. the
        pool filled between pop and placement)."""
        with self._lock:
            self._queue.appendleft(req)
            self.requeues += 1

    # -- retirement policy ---------------------------------------------
    def should_retire(self, req, token, stuck=False):
        """Retirement verdict after ``token`` was emitted for ``req``:
        'eos', 'length', or None (keep decoding). ``stuck`` (fault
        injection) suppresses both natural retirements so only the
        deadline can reap the request."""
        if stuck:
            return None
        if req.eos_token_id is not None and token == req.eos_token_id:
            return "eos"
        if req.emitted >= req.max_new_tokens:
            return "length"
        return None
