"""Fault-tolerant KV-page handoff for disaggregated prefill/decode.

A prefill-role replica finishes a prompt, exports the lane's pages AS
STORED (``KVCachePool.export_lane`` — storage-dtype bytes + int8 scales,
so the transfer is bitwise), and ships them to a decode-role replica
over the fleet's existing line-JSON socket, with the page payloads as
length-prefixed binary frames (crc32 per frame, hard size cap, named
errors on oversize/corrupt). The decode replica installs them with
``install_raw`` and resumes the request exactly where prefill left off.

The robustness contract lives here and is a two-phase protocol::

    sender                                  receiver
    ------                                  --------
    {"op": "handoff", key, meta, frames} -> claim: allocate a slot
                                         <- {"claimed": true} | rejection
    N binary page frames                 -> verify crc/cap, install_raw
                                         <- {"acked": true} | error doc

- **per-attempt timeout + bounded retry**: every attempt runs under
  ``attempt_timeout_s``; failures retry up to ``retries`` times with
  exponential backoff + jitter. Exhaustion raises
  :class:`HandoffRetryError` (the prefill replica then tells the router,
  which re-routes from its ``delivered`` high-water mark).
- **idempotency keys**: the claim carries the router's per-attempt
  handoff key. A re-sent handoff whose key is already installed is
  re-acked WITHOUT touching the pool (``install_raw`` returns False);
  a retry of an unfinished claim reuses its slot.
- **orphan reaping on both sides**: the receiver's claims carry a TTL —
  a prefill worker that dies mid-transfer leaks nothing (the claimed
  slot is freed), and an acked handoff the router never resumes is
  returned to the pool. The sender frees its own lane the moment the
  pages are exported to host memory, so its side cannot leak either.

Stdlib + numpy only on the protocol path: the codec must be usable from
tests without building an engine.
"""

import random
import socket
import struct
import threading
import time
import zlib

from deepspeed_tpu.inference.serving.kv_pool import (
    PageStateError,
    PoolExhaustedError,
)
from deepspeed_tpu.inference.serving.router import (
    PROTOCOL_VERSION,
    read_line,
    send_line,
)

# frame header: payload length + crc32 of the payload, big-endian
_FRAME_HEADER = struct.Struct(">II")
DEFAULT_MAX_FRAME_BYTES = 8 << 20


class HandoffError(RuntimeError):
    """Base class for KV-handoff failures."""


class HandoffSizeError(HandoffError):
    """A page frame exceeds the configured size cap — refused before a
    single payload byte is read/sent, so a corrupt length prefix can
    never make the receiver allocate gigabytes."""


class HandoffFrameError(HandoffError):
    """A frame arrived torn: truncated header/payload or crc32 mismatch.
    The claim survives — the sender retries the transfer under the same
    idempotency key."""


class HandoffTimeoutError(HandoffError):
    """One claim/transfer/ack attempt exceeded ``attempt_timeout_s``."""


class HandoffRejectedError(HandoffError):
    """The receiver refused the claim (pool exhausted, unknown op,
    terminal error doc)."""


class HandoffRetryError(HandoffError):
    """The bounded retry budget is spent. Carries the attempt count and
    the last underlying failure."""

    def __init__(self, key, attempts, last_error):
        self.key = key
        self.attempts = int(attempts)
        self.last_error = str(last_error)
        super().__init__(
            f"handoff {key!r} failed after {attempts} attempt(s); "
            f"last error: {last_error}")


def write_frame(sock, payload, max_bytes=DEFAULT_MAX_FRAME_BYTES):
    """Send one length-prefixed, crc32-protected binary frame."""
    if len(payload) > max_bytes:
        raise HandoffSizeError(
            f"frame of {len(payload)} bytes exceeds the "
            f"{max_bytes}-byte cap")
    header = _FRAME_HEADER.pack(len(payload),
                                zlib.crc32(payload) & 0xFFFFFFFF)
    sock.sendall(header + payload)


def _read_exact(stream, n):
    chunks = []
    got = 0
    while got < n:
        chunk = stream.read(n - got)
        if not chunk:
            break
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def read_frame(stream, max_bytes=DEFAULT_MAX_FRAME_BYTES):
    """Read one binary frame; HandoffFrameError on truncation or crc
    mismatch, HandoffSizeError on an oversize length prefix (raised
    BEFORE reading the payload)."""
    header = _read_exact(stream, _FRAME_HEADER.size)
    if len(header) < _FRAME_HEADER.size:
        raise HandoffFrameError(
            f"truncated frame header ({len(header)} of "
            f"{_FRAME_HEADER.size} bytes)")
    length, crc = _FRAME_HEADER.unpack(header)
    if length > max_bytes:
        raise HandoffSizeError(
            f"frame of {length} bytes exceeds the {max_bytes}-byte cap")
    payload = _read_exact(stream, length)
    if len(payload) < length:
        raise HandoffFrameError(
            f"truncated frame payload ({len(payload)} of {length} bytes)")
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise HandoffFrameError(
            f"frame crc mismatch (expected {crc:#010x}, got "
            f"{zlib.crc32(payload) & 0xFFFFFFFF:#010x})")
    return payload


class HandoffSender:
    """Prefill-side claim→transfer→ack driver with bounded retry.

    ``injector`` (a ServingFaultInjector) lets the chaos harness corrupt
    a frame on the wire or kill the worker mid-transfer — both faults
    the protocol must survive."""

    def __init__(self, config=None, injector=None, rng=None):
        from deepspeed_tpu.inference.serving.config import HandoffConfig
        self.config = config or HandoffConfig()
        self.injector = injector
        self._rng = rng or random.Random()
        self.counters = {"attempts": 0, "retries": 0, "acked": 0,
                         "dup_acked": 0, "failed": 0, "frame_errors": 0}

    def send(self, host, port, key, meta, frames):
        """Run the full protocol against ``host:port``; returns the ack
        doc. Raises HandoffRetryError once the retry budget is spent."""
        cfg = self.config
        budget = max(1, int(cfg.retries))
        last = None
        for attempt in range(1, budget + 1):
            self.counters["attempts"] += 1
            try:
                ack = self._attempt(host, port, key, meta, frames)
                self.counters["acked"] += 1
                if ack.get("dup"):
                    self.counters["dup_acked"] += 1
                return ack
            except (HandoffError, OSError) as e:
                last = e
                if isinstance(e, HandoffFrameError):
                    self.counters["frame_errors"] += 1
                if attempt < budget:
                    self.counters["retries"] += 1
                    base = cfg.backoff_s * (2 ** (attempt - 1))
                    delay = min(base, cfg.backoff_max_s)
                    time.sleep(delay * (0.5 + self._rng.random()))
        self.counters["failed"] += 1
        raise HandoffRetryError(key, budget, last)

    def _attempt(self, host, port, key, meta, frames):
        cfg = self.config
        timeout = float(cfg.attempt_timeout_s) or None
        try:
            with socket.create_connection((host, int(port)),
                                          timeout=timeout) as sock:
                sock.settimeout(timeout)
                stream = sock.makefile("rb")
                send_line(sock, {"op": "handoff", "v": PROTOCOL_VERSION,
                                 "key": key, "meta": meta,
                                 "frames": len(frames)})
                reply = read_line(stream)
                if reply is None:
                    raise HandoffFrameError("EOF awaiting claim reply")
                if reply.get("acked"):
                    return reply            # idempotent duplicate
                if not reply.get("claimed"):
                    raise HandoffRejectedError(
                        f"claim refused: {reply!r}")
                for idx, payload in enumerate(frames):
                    self._write_frame(sock, payload)
                    if self.injector is not None:
                        self.injector.maybe_kill_mid_transfer(idx + 1)
                reply = read_line(stream)
                if reply is None:
                    raise HandoffFrameError("EOF awaiting ack")
                if reply.get("acked"):
                    return reply
                etype = reply.get("etype", "")
                if etype in ("HandoffFrameError", "HandoffSizeError"):
                    raise HandoffFrameError(
                        f"receiver refused a frame: {reply.get('error')}")
                raise HandoffRejectedError(f"no ack: {reply!r}")
        except socket.timeout as e:
            raise HandoffTimeoutError(
                f"handoff attempt to {host}:{port} exceeded "
                f"{cfg.attempt_timeout_s}s") from e

    def _write_frame(self, sock, payload):
        """write_frame, plus the corrupt_handoff_frame arm: the header's
        crc is computed BEFORE the flip (simulating wire corruption), so
        the receiver's crc check must catch it."""
        cap = int(self.config.max_frame_bytes)
        if len(payload) > cap:
            raise HandoffSizeError(
                f"frame of {len(payload)} bytes exceeds the {cap}-byte cap")
        header = _FRAME_HEADER.pack(len(payload),
                                    zlib.crc32(payload) & 0xFFFFFFFF)
        if (payload and self.injector is not None
                and self.injector.corrupt_handoff_frame()):
            payload = bytes([payload[0] ^ 0xFF]) + payload[1:]
        sock.sendall(header + payload)


class _Claim:
    __slots__ = ("key", "slot", "state", "meta", "born")

    def __init__(self, key, slot, meta, now):
        self.key = key
        self.slot = slot
        self.state = "claimed"          # -> "installed"
        self.meta = meta
        self.born = now


class HandoffReceiver:
    """Decode-side claim/install/ack state machine + orphan reaper.

    Pool access goes through three engine-provided callables (they run
    on the engine loop thread so claims never race admissions):
    ``allocate_fn(n_tokens) -> slot``, ``install_fn(slot, meta, frames,
    key) -> bool`` and ``free_fn(slot)``."""

    def __init__(self, config, allocate_fn, install_fn, free_fn,
                 clock=time.monotonic, on_event=None):
        from deepspeed_tpu.inference.serving.config import HandoffConfig
        self.config = config or HandoffConfig()
        self._allocate = allocate_fn
        self._install = install_fn
        self._free = free_fn
        self._clock = clock
        self._on_event = on_event       # on_event(name) -> None, optional
        self._claims = {}               # key -> _Claim
        self._lock = threading.Lock()
        self.counters = {"claims": 0, "installs": 0, "dup_acks": 0,
                         "frame_errors": 0, "reaped_claimed": 0,
                         "reaped_installed": 0, "resumed": 0,
                         "rejected": 0}

    def _event(self, name):
        if self._on_event is not None:
            try:
                self._on_event(name)
            except Exception:
                pass

    # -- the "handoff" socket op ----------------------------------------
    def handle(self, conn, stream, op, reply_fn):
        """Serve one handoff op on an open connection: claim, read the
        binary frames, install, ack. The claim survives a torn transfer
        (the sender retries under the same key); only the TTL reaper
        frees it."""
        self.reap()
        key = str(op.get("key") or "")
        meta = op.get("meta")
        nframes = int(op.get("frames", 0))
        if not key or not isinstance(meta, dict):
            reply_fn(conn, {"error": "handoff without key/meta",
                            "etype": "ValueError"})
            return
        with self._lock:
            claim = self._claims.get(key)
            if claim is not None and claim.state == "installed":
                self.counters["dup_acks"] += 1
                reply_fn(conn, {"acked": True, "key": key, "dup": True})
                return
        if claim is None:
            reserve = int(meta.get("reserve_tokens")
                          or meta.get("position") or 1)
            try:
                slot = self._allocate(reserve)
            except PoolExhaustedError as e:
                self.counters["rejected"] += 1
                reply_fn(conn, {"rejected": "pool_exhausted",
                                "detail": str(e)})
                return
            claim = _Claim(key, slot, meta, self._clock())
            with self._lock:
                self._claims[key] = claim
            self.counters["claims"] += 1
        reply_fn(conn, {"claimed": True, "key": key, "slot": claim.slot})
        cap = int(self.config.max_frame_bytes)
        try:
            frames = [read_frame(stream, cap) for _ in range(nframes)]
        except (HandoffFrameError, HandoffSizeError) as e:
            # claim kept: the sender retries the transfer under the same
            # key; a dead sender's claim falls to the TTL reaper
            self.counters["frame_errors"] += 1
            self._event("frame_error")
            reply_fn(conn, {"error": str(e), "etype": type(e).__name__,
                            "key": key})
            return
        except OSError:
            return                      # sender died mid-transfer
        try:
            fresh = self._install(claim.slot, meta, frames, key)
        except (PageStateError, ValueError) as e:
            reply_fn(conn, {"error": str(e), "etype": type(e).__name__,
                            "key": key})
            return
        claim.state = "installed"
        claim.born = self._clock()      # installed TTL starts now
        if fresh:
            self.counters["installs"] += 1
        else:
            self.counters["dup_acks"] += 1
        reply_fn(conn, {"acked": True, "key": key,
                        "pages": int(meta.get("pages", nframes)),
                        "dup": not fresh})

    # -- resume (the router's second hop claims the installed lane) -----
    def take(self, key):
        """Pop an INSTALLED claim for resumption; returns (slot, meta)
        or None (unknown key, or transfer never finished). Once taken,
        the slot belongs to the engine's resumed request — the reaper
        will not touch it."""
        with self._lock:
            claim = self._claims.get(key)
            if claim is None or claim.state != "installed":
                return None
            del self._claims[key]
        self.counters["resumed"] += 1
        return claim.slot, claim.meta

    def restore(self, key, slot, meta):
        """Undo a take() whose resume failed before the engine owned the
        slot, so the reaper can still free it."""
        with self._lock:
            self._claims[key] = _Claim(key, slot, meta, self._clock())
            self._claims[key].state = "installed"

    # -- the orphan reaper ----------------------------------------------
    def reap(self, now=None):
        """Free claims past their TTL: ``claim_ttl_s`` for transfers
        that never finished (prefill worker died mid-handoff),
        ``resume_ttl_s`` for installed lanes the router never resumed
        (it re-routed, or died). Returns the number of slots freed."""
        now = self._clock() if now is None else now
        expired = []
        with self._lock:
            for key, claim in list(self._claims.items()):
                ttl = (self.config.claim_ttl_s if claim.state == "claimed"
                       else self.config.resume_ttl_s)
                if now - claim.born > ttl:
                    expired.append(claim)
                    del self._claims[key]
        for claim in expired:
            if claim.state == "claimed":
                self.counters["reaped_claimed"] += 1
            else:
                self.counters["reaped_installed"] += 1
            self._event("reaped")
            try:
                self._free(claim.slot)
            except (PageStateError, ValueError):
                pass                    # already freed elsewhere
        return len(expired)

    def pending(self):
        with self._lock:
            return len(self._claims)
