"""Fleet routing front-door: health-aware load balancing over N replicas.

One :class:`ServingEngine` process answers one queue; this tier answers a
*fleet*. The :class:`Router` load-balances requests across replica
processes (each a ``replica.py`` worker under ``launcher/supervisor.py``)
speaking a line-delimited JSON protocol over local TCP sockets, and makes
the fleet survive exactly the faults the injectors can produce:

- **health-aware routing**: replicas are scored from their ``/healthz``
  + ``/snapshot`` telemetry endpoints (loop liveness, queue depth,
  draining flag — the PR 10 ``DSTPU_TELEMETRY_PORT`` contract), with a
  socket-level ``{"op": "health"}`` probe as the no-telemetry fallback.
  Prefix-affinity hashing sends prompts sharing their first N tokens to
  the same replica so ``Serving/PrefixHitRate`` survives scale-out;
  least-loaded wins whenever the affinity target is unhealthy, draining,
  or saturated.
- **failover with exactly-once completion**: every request carries an
  idempotency key and a ``delivered`` high-water mark. On replica death
  (supervisor restart, ``EXIT_POISONED``, socket EOF, per-attempt
  timeout) the request is re-routed with ``from=delivered``: the new
  replica recomputes the full greedy generation (deterministic — same
  seed, same params) and replays only the missing suffix, so a
  ``stream_cb`` never sees a token twice and the final output is
  bitwise-identical to single-engine ``generate()``. Failure retries
  burn a bounded budget with exponential backoff + jitter; exhausting it
  quarantines the request with :class:`RequestPoisonedError` instead of
  crash-looping the fleet. Rejections (queue-full / draining / injected)
  re-route immediately WITHOUT burning budget — the request did nothing
  wrong.
- **drain awareness**: a replica answering ``rejected: draining`` (or
  advertising ``draining`` via health) leaves the rotation at once; its
  in-flight requests finish where they are (see replica.py's SIGTERM
  sequence).
- **overload shedding**: an admission controller sheds with a structured
  :class:`FleetOverloadError` (retry-after hint) when a request class'
  token budget is exhausted or every routable replica is saturated —
  failing fast at the door beats timing out deep in a queue.

Stdlib-only on purpose (sockets + threads + json): the router process
must never pay a jax import, and the module is reusable from the
launcher. Wire protocol (one JSON object per line)::

    -> {"op": "submit", "key": K, "prompt": [...], "from": 0, ...}
    <- {"t": 17, "i": 0}            # token 0
    <- {"t": 4,  "i": 1}            # token 1
    <- {"done": true, "n": 2}       # terminal: success
    <- {"rejected": "queue_full" | "draining" | "injected"}
    <- {"error": msg, "etype": "RequestTimeoutError", "detail": {...}}
"""

import json
import random
import socket
import sys
import threading
import time
import uuid
import zlib
from urllib.error import HTTPError
from urllib.request import urlopen

from deepspeed_tpu.inference.serving.config import FleetConfig
from deepspeed_tpu.inference.serving.scheduler import (
    RequestTimeoutError,
    ServingFuture,
)

PROTOCOL_VERSION = 1

# replica roles for disaggregated prefill/decode serving. "mixed" runs
# both phases interleaved (the classic topology and the wire default: a
# health snapshot with no role field is treated as mixed so pre-role
# replicas keep routing unchanged). "prefill" workers run prompt
# processing and hand finished KV pages to a "decode" worker; "decode"
# workers only accept handoff installs + resumes, never fresh submits.
REPLICA_ROLES = ("prefill", "decode", "mixed")

# terminal error types a replica may report; anything else degrades to
# RuntimeError with the replica's message
_TERMINAL_ERRORS = {
    "RequestTimeoutError": None,     # reconstructed from detail below
    "ValueError": ValueError,
}


class FleetOverloadError(RuntimeError):
    """The router shed this request at admission: either its class'
    token budget is exhausted or every routable replica is saturated.
    ``retry_after_s`` is the client's backoff hint."""

    def __init__(self, reason, retry_after_s, request_class="default"):
        self.reason = reason            # "class_budget" | "saturated"
        self.retry_after_s = float(retry_after_s)
        self.request_class = request_class
        super().__init__(
            f"fleet overloaded ({reason}, class={request_class!r}); "
            f"retry after {retry_after_s:.2f}s")


class WrongRoleError(RuntimeError):
    """The fleet cannot serve this request kind at all: every attached
    endpoint is the wrong role (e.g. a plain submit against a fleet of
    pure decode workers). Structured — carries the request kind and the
    per-endpoint role map — so callers can tell a topology bug from a
    transient outage."""

    def __init__(self, request_kind, roles):
        self.request_kind = str(request_kind)
        self.roles = dict(roles)
        super().__init__(
            f"no endpoint can serve a {request_kind!r} request: "
            f"fleet roles are {self.roles}")


class RequestPoisonedError(RuntimeError):
    """A request failed on every retry and was quarantined: the retry
    budget is spent and the router will not crash-loop the fleet on it."""

    def __init__(self, key, attempts, last_error):
        self.key = key
        self.attempts = int(attempts)
        self.last_error = str(last_error)
        super().__init__(
            f"request {key} quarantined after {attempts} failed attempt(s); "
            f"last error: {last_error}")


def send_line(sock, doc):
    """One protocol frame: compact JSON + newline."""
    sock.sendall((json.dumps(doc, separators=(",", ":")) + "\n")
                 .encode("utf-8"))


def read_line(stream):
    """One frame off a socket file object; None at EOF."""
    line = stream.readline()
    if not line:
        return None
    return json.loads(line)


def _http_json(url, timeout_s):
    """GET a JSON doc; a 503 /healthz body still parses (unhealthy is an
    answer, not an outage)."""
    try:
        with urlopen(url, timeout=timeout_s) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except HTTPError as e:
        return json.loads(e.read().decode("utf-8"))


class ReplicaEndpoint:
    """One replica's addresses + the router's live view of it."""

    def __init__(self, name, host, port, health_url=None, generation="0",
                 role="mixed"):
        self.name = str(name)
        self.host = str(host)
        self.port = int(port)
        # disaggregation role ("prefill" | "decode" | "mixed"); refreshed
        # from health probes — a snapshot without a role field means a
        # pre-role replica and maps to "mixed"
        role = str(role or "mixed")
        if role not in REPLICA_ROLES:
            raise ValueError(
                f"unknown replica role {role!r} "
                f"(known: {', '.join(REPLICA_ROLES)})")
        self.role = role
        # telemetry endpoint ("http://127.0.0.1:9100"); None = probe the
        # serving socket with {"op": "health"} instead
        self.health_url = health_url.rstrip("/") if health_url else None
        # weight-version tag: which committed checkpoint generation this
        # replica serves. Greedy decoding is deterministic PER generation,
        # so exactly-once replay across replicas is only bitwise-safe
        # within one generation — retry selection pins on it.
        self.generation = str(generation if generation is not None else "0")
        # router-side view, refreshed by probes
        self.healthy = True
        self.draining = False
        self.removed = False        # detached via remove_endpoint()
        self.load_hint = 0          # queue_depth + active from last probe
        self.inflight = 0           # attempts the router has on this replica
        self.last_probe = 0.0
        # last SUCCESSFUL probe: a cached health snapshot older than
        # 2 x health_ttl_s is treated as unhealthy rather than routed on
        # (stale scores). Seeded to construction time: a fresh endpoint
        # gets one staleness window of benefit of the doubt.
        self.last_ok = time.monotonic()
        self.failures = 0           # consecutive probe/attempt failures

    @property
    def address(self):
        return (self.host, self.port)

    def __repr__(self):
        return (f"ReplicaEndpoint({self.name}, {self.host}:{self.port}, "
                f"gen={self.generation}, role={self.role}, "
                f"healthy={self.healthy}, draining={self.draining}, "
                f"load={self.load_hint}+{self.inflight})")


class _RoutedRequest:
    __slots__ = ("key", "prompt", "max_new_tokens", "eos_token_id",
                 "timeout_s", "stream_cb", "request_class", "cost",
                 "future", "delivered", "t0", "generation")

    def __init__(self, key, prompt, max_new_tokens, eos_token_id, timeout_s,
                 stream_cb, request_class, cost):
        self.key = key
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.eos_token_id = eos_token_id
        self.timeout_s = timeout_s
        self.stream_cb = stream_cb
        self.request_class = request_class
        self.cost = cost
        self.future = ServingFuture(key)
        self.delivered = 0          # exactly-once high-water mark
        self.t0 = time.monotonic()  # original submit time (age_s on retry)
        # weight generation that streamed the first token: once any token
        # is delivered, retries must stay on this generation (different
        # weights would replay a different suffix and break bitwise
        # exactly-once). None until then.
        self.generation = None


class Router:
    """Health-aware, failover-capable request router over a replica fleet."""

    def __init__(self, endpoints, config=None, registry=None,
                 probe_timeout_s=2.0, rng=None):
        self.config = config or FleetConfig(enabled=True)
        self.probe_timeout_s = float(probe_timeout_s)
        self._rng = rng or random.Random()
        self._endpoints = []
        for ep in endpoints:
            if not isinstance(ep, ReplicaEndpoint):
                ep = ReplicaEndpoint(*ep)
            self._endpoints.append(ep)
        if not self._endpoints:
            raise ValueError("router needs at least one replica endpoint")
        # stable order: the affinity hash must map a prefix to the same
        # replica in every router process
        self._endpoints.sort(key=lambda e: e.name)
        self._lock = threading.Lock()
        self._inflight_tokens = {}      # class -> tokens in flight
        self._inflight_requests = 0
        self._degrade_rung = 0          # rung 3 sheds classes at the door
        self._canary = None             # (generation, fraction) or None
        self._tap = None                # completion tap (shadow sampling)
        self._threads = set()
        self._closed = False
        self._counters = {
            "routed": 0,        # attempts dispatched to a replica
            "retried": 0,       # failure retries (budget-burning)
            "shed": 0,          # FleetOverloadError raised at admission
            "drained": 0,       # draining rejections observed
            "rejected": 0,      # queue_full / injected rejections observed
            "completed": 0,     # requests finished successfully
            "failed": 0,        # requests finished with a terminal error
            "poisoned": 0,      # requests quarantined
            "canary_routed": 0,  # attempts landed on the canary generation
            "handoff_routed": 0,     # two-hop prefill->decode routes tried
            "handoff_completed": 0,  # requests finished via the decode hop
            "handoff_failed": 0,     # page transfers that never acked
            "handoff_degraded": 0,   # edge-triggered falls to mixed mode
        }
        # edge state for the handoff-degraded instant: set when a decode
        # pool exists but cannot be routed to (requests fall back to the
        # interleaved plain path), cleared when a handoff routes again
        self._handoff_degraded_flag = False
        if registry is not None:
            self.export_gauges(registry)

    # -- metrics ---------------------------------------------------------
    def counters(self):
        with self._lock:
            out = dict(self._counters)
            out["inflight_requests"] = self._inflight_requests
            out["inflight_tokens"] = float(
                sum(self._inflight_tokens.values()))
        accepted = out["completed"] + out["failed"] + out["poisoned"] \
            + out["inflight_requests"]
        out["shed_rate"] = (out["shed"] / (out["shed"] + accepted)
                            if out["shed"] + accepted > 0 else 0.0)
        out["healthy_replicas"] = float(
            sum(1 for ep in self._endpoints
                if ep.healthy and not ep.draining))
        out["replicas"] = float(len(self._endpoints))
        out["degrade_rung"] = float(self._degrade_rung)
        return out

    def export_gauges(self, registry):
        """Pull gauges under ``Fleet/router/*`` (routed, retried, shed,
        drained, shed_rate, ...) so the PR 10 SLO engine and the fleet
        collector can alert on them. Idempotent."""
        registry.gauge_fn(
            "Fleet/router",
            lambda: {k: float(v) for k, v in self.counters().items()},
            help="fleet router counters (routed/retried/shed/drained)")
        return registry

    # -- health ----------------------------------------------------------
    def _probe(self, ep, now=None, force=False):
        now = time.monotonic() if now is None else now
        if not force and now - ep.last_probe < self.config.health_ttl_s:
            return
        ep.last_probe = now
        try:
            if ep.health_url is not None:
                doc = _http_json(ep.health_url + "/healthz",
                                 self.probe_timeout_s)
                loop = doc.get("serving_loop") or {}
                rep = doc.get("replica") or {}
                ep.draining = bool(loop.get("draining")
                                   or rep.get("draining"))
                ep.healthy = doc.get("status") == "ok"
                ep.load_hint = (int(loop.get("queue_depth", 0))
                                + int(loop.get("active_requests", 0)))
                # missing role = pre-role replica = mixed (wire compat)
                ep.role = str(rep.get("role") or "mixed")
            else:
                doc = self._socket_health(ep)
                ep.draining = bool(doc.get("draining"))
                ep.healthy = bool(doc.get("healthy", True))
                ep.load_hint = (int(doc.get("queue_depth", 0))
                                + int(doc.get("active_requests", 0)))
                ep.role = str(doc.get("role") or "mixed")
            ep.failures = 0
            ep.last_ok = now
        except (OSError, ValueError):
            ep.healthy = False
            ep.failures += 1

    def _socket_health(self, ep):
        with socket.create_connection(ep.address,
                                      timeout=self.probe_timeout_s) as sock:
            sock.settimeout(self.probe_timeout_s)
            send_line(sock, {"op": "health"})
            doc = read_line(sock.makefile("rb"))
        if doc is None:
            raise OSError("health probe: EOF")
        return doc

    def probe_all(self, force=True):
        """Refresh every endpoint's health view; returns the endpoints."""
        now = time.monotonic()
        eps = self._endpoints
        for ep in eps:
            self._probe(ep, now=now, force=force)
        return list(eps)

    # -- fleet membership (the autoscaler's contract) --------------------
    def endpoints(self):
        """Current endpoint list (a snapshot)."""
        return list(self._endpoints)

    def add_endpoint(self, ep, generation=None):
        """Attach a replica to the rotation (the autoscaler's scale-up:
        the process is already warm and listening, attach is O(1)).
        The list is re-sorted by name so the affinity hash stays stable
        across router processes. ``generation`` overrides the endpoint's
        weight-version tag (the rollout controller tags canaries here)."""
        if not isinstance(ep, ReplicaEndpoint):
            ep = ReplicaEndpoint(*ep)
        if generation is not None:
            ep.generation = str(generation)
        with self._lock:
            if any(e.name == ep.name for e in self._endpoints):
                raise ValueError(f"endpoint {ep.name!r} already routed")
            eps = self._endpoints + [ep]
            eps.sort(key=lambda e: e.name)
            self._endpoints = eps           # atomic swap: readers snapshot
        return ep

    def remove_endpoint(self, name):
        """Detach a replica from the rotation (the autoscaler's
        scale-down: the caller then SIGTERMs the process, which drains
        and exits ``EXIT_PREEMPTED``). In-flight attempts on it finish
        where they are; the endpoint is marked draining so nothing new
        lands during the handoff. Refuses to empty the fleet."""
        with self._lock:
            ep = next((e for e in self._endpoints if e.name == name), None)
            if ep is None:
                raise ValueError(f"no endpoint named {name!r}")
            if len(self._endpoints) == 1:
                raise ValueError("cannot remove the last endpoint")
            # flags first, THEN the list swap: a picker holding the old
            # list snapshot still sees removed/draining on the shared
            # endpoint object and skips it (the drain race fix — the swap
            # alone leaves a window where a mid-retry request re-selects
            # the detached replica from its stale snapshot)
            ep.removed = True
            ep.draining = True
            self._endpoints = [e for e in self._endpoints if e is not ep]
        return ep

    # -- canary slice (the rollout controller's contract) ----------------
    def set_canary(self, generation, fraction):
        """Route a deterministic ``fraction`` of NEW requests onto
        replicas tagged ``generation``. The slice is chosen by hashing
        the same prompt prefix the affinity hash uses, so a given prefix
        always lands in the same group and cache locality survives the
        split; within each group, prefix-affinity hashing applies
        unchanged. In-flight requests are never migrated."""
        fraction = float(fraction)
        if not 0.0 <= fraction <= 1.0:
            raise ValueError(f"canary fraction {fraction} not in [0, 1]")
        self._canary = (str(generation), fraction)
        return self._canary

    def clear_canary(self):
        """Drop the canary split: routing reverts to one undivided pool
        (rollback, or promotion once every replica is on the new tag)."""
        self._canary = None

    @property
    def canary(self):
        """(generation, fraction) of the active canary slice, or None."""
        return self._canary

    def _in_canary_slice(self, prompt, fraction):
        if fraction >= 1.0:
            return True
        if fraction <= 0.0:
            return False
        n = max(1, self.config.affinity_prefix_tokens)
        prefix = ",".join(str(int(t)) for t in prompt[:n]).encode("ascii")
        # salted so the slice decision decorrelates from the in-group
        # replica choice, but still a pure function of the prefix
        return (zlib.crc32(b"canary:" + prefix) % 10000) < fraction * 10000

    # -- completion tap (shadow traffic sampling) ------------------------
    def set_completion_tap(self, tap):
        """Install ``tap(info)`` called once per successfully completed
        request with ``{key, prompt, max_new_tokens, eos_token_id,
        request_class, tokens, generation, latency_s}``. The rollout
        controller samples these to replay as shadow traffic against the
        canary. Pass None to uninstall. Tap exceptions are swallowed —
        observation must not affect routing."""
        self._tap = tap

    # -- degraded-mode ladder (rung 3 lives here) ------------------------
    def set_degrade_rung(self, rung):
        """Fleet degrade rung as pushed by the autoscaler (or a test).
        The router acts on rung >= 3: per-class shedding at admission.
        Edge-triggered bookkeeping only — instants are the ladder
        owner's job."""
        self._degrade_rung = max(0, int(rung))
        return self._degrade_rung

    @property
    def degrade_rung(self):
        return self._degrade_rung

    def _routable(self, ep, now=None):
        if ep.removed:
            return False
        ttl = self.config.health_ttl_s
        if ttl > 0:
            now = time.monotonic() if now is None else now
            if now - ep.last_ok > 2.0 * ttl:
                # the health view went stale (probes failing or never
                # completing): don't route on old scores
                return False
        return ep.healthy and not ep.draining

    def _load(self, ep):
        return ep.load_hint + ep.inflight

    def _saturated(self, ep):
        return self._load(ep) >= max(1, self.config.saturation_queue_depth)

    # -- routing policy --------------------------------------------------
    def _affinity_target(self, prompt, eps=None):
        n = self.config.affinity_prefix_tokens
        eps = self._endpoints if eps is None else eps
        if n <= 0 or not eps:
            return None
        prefix = ",".join(str(int(t)) for t in prompt[:n]).encode("ascii")
        return eps[zlib.crc32(prefix) % len(eps)]

    def _pick(self, rr, avoid=None, eps=None, role="submit"):
        """Affinity target when healthy and unsaturated; else the
        least-loaded routable replica; None when nothing is routable.

        Role rules (disaggregated fleets): ``role="submit"`` — a plain
        interleaved request — never lands on a pure decode worker (those
        only accept handoff installs; routing one a fresh prompt is the
        wrong-role bug the replica would reject anyway).
        ``role="prefill"`` prefers strict prefill workers and falls back
        to mixed ones; ``role="decode"`` selects pure decode workers
        only. Mixed fleets (every role "mixed", the pre-disaggregation
        default) behave exactly as before.

        Generation rules: a request that has delivered tokens is pinned
        to the generation that produced them — a cross-generation replay
        would recompute a different suffix and break bitwise exactly-once
        — so candidates of other generations are never selected, even
        when that means returning None and backing off. An unpinned
        request under an active canary is assigned to the canary or
        incumbent slice by prefix hash; affinity then applies within the
        slice. ``eps`` exists for tests: pass a stale snapshot to prove
        removed endpoints are still skipped."""
        now = time.monotonic()
        eps = self._endpoints if eps is None else eps
        for ep in eps:
            self._probe(ep, now=now)
        candidates = [ep for ep in eps if self._routable(ep, now=now)]
        if role == "decode":
            candidates = [ep for ep in candidates if ep.role == "decode"]
        elif role == "prefill":
            strict = [ep for ep in candidates if ep.role == "prefill"]
            candidates = strict or [ep for ep in candidates
                                    if ep.role != "decode"]
        else:   # plain submit: anything that can run a full request
            candidates = [ep for ep in candidates if ep.role != "decode"]
        if avoid is not None and len(candidates) > 1:
            candidates = [ep for ep in candidates if ep is not avoid]
        if not candidates:
            return None
        pool = eps                   # affinity pool: stable across health
        if rr.generation is not None:
            same_gen = [ep for ep in candidates
                        if ep.generation == rr.generation]
            if not same_gen:
                return None
            candidates = same_gen
            pool = [ep for ep in eps if ep.generation == rr.generation]
        else:
            canary = self._canary
            if canary is not None:
                gen, frac = canary
                want = self._in_canary_slice(rr.prompt, frac)
                group = [ep for ep in candidates
                         if (ep.generation == gen) == want]
                if group:
                    candidates = group
                    pool = [ep for ep in eps
                            if (ep.generation == gen) == want]
                # an empty slice (canary crashed / not yet attached)
                # falls through to the full candidate set: traffic keeps
                # flowing on whatever is routable
        chosen = None
        target = self._affinity_target(rr.prompt, pool)
        if (target is not None and target in candidates
                and not self._saturated(target)):
            chosen = target
        else:
            chosen = min(candidates, key=self._load)
        # final re-validation: remove_endpoint() may have detached the
        # chosen replica after the candidate filter ran (flags are set on
        # the shared object before the list swap, so this check closes
        # the stale-snapshot window)
        if chosen.removed or chosen.draining:
            return None
        return chosen

    # -- admission control ----------------------------------------------
    def _class_budget(self, request_class):
        b = self.config.max_inflight_tokens
        if isinstance(b, dict):
            b = b.get(request_class, b.get("default", 0))
        return int(b or 0)

    def _shed_class(self, request_class):
        """Rung-3 (class_shed) verdict for one request class: the
        configured ``fleet.degrade.shed_classes``, or — with an empty
        list — every class EXCEPT the protected ``"default"``."""
        if self._degrade_rung < 3:
            return False
        classes = tuple(getattr(self.config.degrade, "shed_classes", ())
                        if getattr(self.config, "degrade", None) is not None
                        else ())
        if classes:
            return request_class in classes
        return request_class != "default"

    def _admit(self, rr):
        """Shed checks; reserves the class token budget on success."""
        if self._shed_class(rr.request_class):
            with self._lock:
                self._counters["shed"] += 1
            raise FleetOverloadError(
                "degraded", self.config.shed_retry_after_s,
                request_class=rr.request_class)
        budget = self._class_budget(rr.request_class)
        with self._lock:
            used = self._inflight_tokens.get(rr.request_class, 0)
            if budget > 0 and used + rr.cost > budget:
                self._counters["shed"] += 1
                raise FleetOverloadError(
                    "class_budget", self.config.shed_retry_after_s,
                    request_class=rr.request_class)
        routable = [ep for ep in self.probe_all(force=False)
                    if self._routable(ep)]
        if routable and all(self._saturated(ep) for ep in routable):
            with self._lock:
                self._counters["shed"] += 1
            raise FleetOverloadError(
                "saturated", self.config.shed_retry_after_s,
                request_class=rr.request_class)
        with self._lock:
            self._inflight_tokens[rr.request_class] = \
                self._inflight_tokens.get(rr.request_class, 0) + rr.cost
            self._inflight_requests += 1

    def _release(self, rr):
        with self._lock:
            left = self._inflight_tokens.get(rr.request_class, 0) - rr.cost
            self._inflight_tokens[rr.request_class] = max(0, left)
            self._inflight_requests -= 1

    # -- public API ------------------------------------------------------
    def submit(self, prompt, max_new_tokens=None, eos_token_id=None,
               timeout_s=None, stream_cb=None, request_class="default",
               key=None, shed_retries=0):
        """Route one request; returns a :class:`ServingFuture`.

        Raises :class:`FleetOverloadError` synchronously when shedding.
        ``shed_retries`` re-admits a shed request up to that many times,
        honoring the error's ``retry_after_s`` hint between attempts, so
        callers get load-aware backoff instead of a hot retry loop.
        Every other outcome — success, terminal error from the replica,
        :class:`RequestPoisonedError` after budget exhaustion — is
        delivered through the future."""
        if self._closed:
            raise RuntimeError("router is closed")
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompt must contain at least one token")
        cost = len(prompt) + int(max_new_tokens or 0)
        rr = _RoutedRequest(
            key or uuid.uuid4().hex, prompt,
            None if max_new_tokens is None else int(max_new_tokens),
            None if eos_token_id is None else int(eos_token_id),
            timeout_s, stream_cb, request_class, cost)
        attempts_left = max(0, int(shed_retries))
        while True:
            try:
                self._admit(rr)
                break
            except FleetOverloadError as exc:
                if attempts_left <= 0 or self._closed:
                    raise
                attempts_left -= 1
                time.sleep(max(0.0, float(exc.retry_after_s)))
        t = threading.Thread(target=self._run_request, args=(rr,),
                             name=f"router-{rr.key[:8]}", daemon=True)
        with self._lock:
            self._threads.add(t)
        t.start()
        return rr.future

    def close(self, timeout_s=5.0):
        self._closed = True
        with self._lock:
            threads = list(self._threads)
        deadline = time.monotonic() + timeout_s
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))

    # -- the per-request worker ------------------------------------------
    def _run_request(self, rr):
        try:
            self._drive(rr)
        finally:
            self._release(rr)
            with self._lock:
                self._threads.discard(threading.current_thread())

    def _drive(self, rr):
        cfg = self.config
        failures = 0
        reroutes = 0
        avoid = None
        while True:
            ep = None
            decode_ep = None
            if self._handoff_wanted(rr):
                decode_ep = self._pick(rr, avoid=avoid, role="decode")
                if decode_ep is not None:
                    pre = self._pick(rr, avoid=avoid, role="prefill")
                    # generation guard: both hops replay within ONE weight
                    # generation or the spliced output is not bitwise
                    if (pre is not None and pre is not decode_ep
                            and pre.generation == decode_ep.generation):
                        ep = pre
                if ep is None:
                    # decode pool configured but unroutable right now:
                    # fall back to interleaved mixed mode (edge-triggered
                    # instant; requests keep flowing, just slower TTFT)
                    decode_ep = None
                    self._handoff_degraded(True)
            if ep is None:
                ep = self._pick(rr, avoid=avoid)
            if ep is None:
                eps = [e for e in self._endpoints if not e.removed]
                if eps and all(e.role == "decode" for e in eps):
                    # topology bug, not a transient outage: nothing in
                    # the fleet can EVER take a fresh prompt
                    with self._lock:
                        self._counters["failed"] += 1
                    rr.future._finish(WrongRoleError(
                        "submit", {e.name: e.role for e in eps}))
                    return
                failures += 1
                if failures > cfg.retry_budget:
                    self._finish_poisoned(rr, failures,
                                          "no routable replica")
                    return
                with self._lock:
                    self._counters["retried"] += 1
                avoid = None
                self._backoff(failures)
                continue
            blame = ep
            if decode_ep is not None:
                self._handoff_degraded(False)
                outcome, detail, blame = self._attempt_handoff(
                    rr, ep, decode_ep)
                if outcome == "handoff_failed":
                    # the transfer never landed (or the installed claim
                    # was lost): the prefill hop already streamed token 0,
                    # so re-route plain from the delivered high-water mark.
                    # Like a rejection this burns no retry budget — the
                    # request did nothing wrong — but rides the same
                    # bounded carousel.
                    with self._lock:
                        self._counters["handoff_failed"] += 1
                    blame.healthy = False
                    blame.failures += 1
                    avoid = None
                    reroutes += 1
                    if reroutes > max(4, 2 * len(self._endpoints)):
                        reroutes = 0
                        failures += 1
                        if failures > cfg.retry_budget:
                            self._finish_poisoned(
                                rr, failures,
                                f"handoff failed everywhere ({detail})")
                            return
                        with self._lock:
                            self._counters["retried"] += 1
                        self._backoff(failures)
                    continue
            else:
                outcome, detail = self._attempt(rr, ep)
            if outcome == "done":
                with self._lock:
                    self._counters["completed"] += 1
                rr.future._finish()
                tap = self._tap
                if tap is not None:
                    try:
                        tap({"key": rr.key, "prompt": list(rr.prompt),
                             "max_new_tokens": rr.max_new_tokens,
                             "eos_token_id": rr.eos_token_id,
                             "request_class": rr.request_class,
                             "tokens": rr.future.tokens,
                             "generation": ep.generation,
                             "latency_s": max(
                                 0.0, time.monotonic() - rr.t0)})
                    except Exception:
                        pass    # observation must not affect routing
                return
            if outcome == "terminal":
                with self._lock:
                    self._counters["failed"] += 1
                rr.future._finish(self._terminal_exception(detail))
                return
            if outcome == "rejected":
                # the replica said no before doing work: re-route without
                # burning retry budget, but bound the carousel
                with self._lock:
                    self._counters[
                        "drained" if detail == "draining"
                        else "rejected"] += 1
                if detail == "draining":
                    blame.draining = True
                avoid = blame
                reroutes += 1
                if reroutes > max(4, 2 * len(self._endpoints)):
                    reroutes = 0
                    failures += 1
                    if failures > cfg.retry_budget:
                        self._finish_poisoned(
                            rr, failures, f"rejected everywhere ({detail})")
                        return
                    with self._lock:
                        self._counters["retried"] += 1
                    self._backoff(failures)
                continue
            # outcome == "failed": the replica died / wedged mid-attempt
            # (``blame`` is the hop that actually failed — the decode
            # worker on a post-ack death, not the innocent prefill)
            blame.healthy = False
            blame.failures += 1
            failures += 1
            if failures > cfg.retry_budget:
                self._finish_poisoned(rr, failures, detail)
                return
            with self._lock:
                self._counters["retried"] += 1
            avoid = blame
            self._backoff(failures)

    # -- disaggregated prefill/decode routing ----------------------------
    def _handoff_wanted(self, rr):
        """Plan a two-hop prefill->decode route? Only for FRESH requests
        (``delivered == 0`` — a retry with delivered tokens replays plain
        from its high-water mark), only when decoding will actually
        happen (``max_new_tokens > 1``; a 1-token request IS its prefill),
        and only when the fleet has a decode pool at all."""
        return (rr.delivered == 0
                and rr.max_new_tokens is not None
                and int(rr.max_new_tokens) > 1
                and any(e.role == "decode" and not e.removed
                        for e in self._endpoints))

    def _attempt_handoff(self, rr, pre_ep, decode_ep):
        """One two-hop attempt: prefill on ``pre_ep`` (which streams the
        first token, then ships the KV pages to ``decode_ep``), then
        resume on ``decode_ep``. Returns (outcome, detail, blame) where
        ``blame`` is the endpoint at fault for a non-done outcome.

        The handoff key is fresh per attempt — the replica-side
        idempotency (dup-ack on re-send, installed-claim takeover) keys
        on it, and reusing a key across logically different attempts
        would alias unrelated transfers."""
        hkey = f"{rr.key}:{uuid.uuid4().hex[:8]}"
        with self._lock:
            self._counters["handoff_routed"] += 1
        outcome, detail = self._attempt(rr, pre_ep, extra={
            "handoff": {"host": decode_ep.host, "port": decode_ep.port,
                        "key": hkey}})
        if outcome != "handoff_done":
            if outcome == "handoff_failed":
                why = (detail or {}).get("error", "page transfer failed")
                # the prefill worker exhausted its bounded retries against
                # the decode worker: the decode side is the suspect
                return "handoff_failed", why, decode_ep
            return outcome, detail, pre_ep
        # hop 2: resume on the decode worker from the installed pages
        outcome, detail = self._attempt(rr, decode_ep, extra={
            "handoff_key": hkey})
        if outcome == "rejected" and detail == "handoff_unknown":
            # acked but gone (reaped, or the decode worker restarted
            # between ack and resume): fall back to a plain replay
            return "handoff_failed", "installed claim lost", decode_ep
        if outcome == "done":
            with self._lock:
                self._counters["handoff_completed"] += 1
        return outcome, detail, decode_ep

    def _handoff_degraded(self, degraded, reason="decode pool unroutable"):
        """Edge-triggered degraded-mode bookkeeping: the first fall from
        disaggregated to interleaved routing bumps the counter and emits
        a ``fleet/handoff_degraded`` instant; recovery re-arms the edge
        (and emits the matching restore instant)."""
        if degraded and not self._handoff_degraded_flag:
            self._handoff_degraded_flag = True
            with self._lock:
                self._counters["handoff_degraded"] += 1
            self._note("fleet/handoff_degraded", reason=reason)
        elif not degraded and self._handoff_degraded_flag:
            self._handoff_degraded_flag = False
            self._note("fleet/handoff_restored")

    def _note(self, name, **args):
        """Emit a telemetry instant IF the telemetry subsystem is already
        imported (the router is stdlib-only by design — it must never be
        the first importer of anything heavy)."""
        if "deepspeed_tpu.telemetry" not in sys.modules:
            return
        try:
            from deepspeed_tpu import telemetry
            telemetry.instant(name, cat="fleet", args=args)
        except Exception:
            pass    # observation must not affect routing

    def _backoff(self, n):
        base = self.config.retry_backoff_s * (2 ** max(0, n - 1))
        delay = min(base, self.config.retry_backoff_max_s)
        time.sleep(delay * (0.5 + self._rng.random()))

    def _finish_poisoned(self, rr, attempts, last_error):
        with self._lock:
            self._counters["poisoned"] += 1
        rr.future._finish(
            RequestPoisonedError(rr.key, attempts, last_error))

    @staticmethod
    def _terminal_exception(doc):
        etype = doc.get("etype", "")
        detail = doc.get("detail") or {}
        if etype == "RequestTimeoutError":
            return RequestTimeoutError(
                detail.get("request_id", doc.get("key", "?")),
                detail.get("timeout_s"), detail.get("phase", "decoding"),
                tokens_done=detail.get("tokens_done", 0))
        exc_cls = _TERMINAL_ERRORS.get(etype) or RuntimeError
        return exc_cls(doc.get("error", "replica error"))

    def _attempt(self, rr, ep, extra=None):
        """One routed attempt. Returns (outcome, detail): "done",
        ("terminal", error-doc), ("rejected", reason), or
        ("failed", why) — only "failed" burns retry budget. With a
        handoff ``extra`` two more outcomes appear: ("handoff_done", doc)
        — the prefill hop streamed its token and the pages acked on the
        decode side, proceed to hop 2 — and ("handoff_failed", doc)."""
        timeout = self.config.attempt_timeout_s or None
        canary = self._canary
        with self._lock:
            self._counters["routed"] += 1
            if canary is not None and ep.generation == canary[0]:
                self._counters["canary_routed"] += 1
        ep.inflight += 1
        sock = None
        try:
            sock = socket.create_connection(ep.address, timeout=timeout)
            sock.settimeout(timeout)
            doc = {
                "op": "submit", "v": PROTOCOL_VERSION, "key": rr.key,
                "prompt": rr.prompt, "max_new_tokens": rr.max_new_tokens,
                "eos_token_id": rr.eos_token_id, "timeout_s": rr.timeout_s,
                "from": rr.delivered,
                "age_s": max(0.0, time.monotonic() - rr.t0)}
            if extra:
                doc.update(extra)
            send_line(sock, doc)
            stream = sock.makefile("rb")
            while True:
                doc = read_line(stream)
                if doc is None:
                    return "failed", "socket EOF (replica died?)"
                if "t" in doc:
                    i = int(doc.get("i", -1))
                    if i == rr.delivered:
                        self._deliver(rr, int(doc["t"]), ep)
                    elif i > rr.delivered:
                        return "failed", (
                            f"token gap: got index {i}, "
                            f"delivered {rr.delivered}")
                    # i < delivered: replayed duplicate — never re-emitted
                elif doc.get("done"):
                    n = int(doc.get("n", rr.delivered))
                    if n != rr.delivered:
                        return "failed", (
                            f"done at n={n} but delivered {rr.delivered}")
                    return "done", None
                elif doc.get("handoff_done"):
                    return "handoff_done", doc
                elif doc.get("handoff_failed"):
                    return "handoff_failed", doc
                elif "rejected" in doc:
                    if doc["rejected"] == "wrong_role" and doc.get("role"):
                        # the router's role view was stale — adopt the
                        # replica's own answer so the re-pick is informed
                        role = str(doc["role"])
                        if role in REPLICA_ROLES:
                            ep.role = role
                    return "rejected", str(doc["rejected"])
                elif "error" in doc:
                    return "terminal", doc
                else:
                    return "failed", f"unintelligible frame: {doc!r}"
        except (OSError, ValueError) as e:
            # connect refused, reset, per-attempt inactivity timeout,
            # or torn JSON from a dying replica — all the same verdict
            return "failed", f"{type(e).__name__}: {e}"
        finally:
            ep.inflight -= 1
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass

    def _deliver(self, rr, token, ep):
        if rr.generation is None:
            rr.generation = ep.generation   # pin: retries stay bitwise
        rr.future._append(token)
        rr.delivered += 1
        if rr.stream_cb is not None:
            try:
                rr.stream_cb(rr.key, token)
            except Exception:   # a broken callback must not kill routing
                pass
