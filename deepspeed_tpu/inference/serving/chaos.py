"""Randomized chaos harness for the serving fleet.

The fleet tier accumulated a lot of robustness machinery — failover with
exactly-once replay, drain-aware routing, retry budgets, overload
shedding, crash-loop breakers, the degrade ladder, the autoscaler — each
tested in isolation. This harness tests the COMPOSITION: a seeded
randomized schedule of fault episodes against a LIVE router + replica
fleet, with the paper's correctness bar asserted after every single
episode, not just at the end:

- **exactly-once, bitwise**: every request that completes must return
  tokens bitwise-identical to the single-engine ``generate()`` oracle
  (greedy decoding is deterministic, so any divergence means a replay
  bug, a duplicated token, or cross-replica state leakage).
- **no stuck requests**: every submitted request reaches a terminal
  state — tokens, a structured error, or a shed — within a deadline.
  A future that never resolves is the worst serving failure mode.
- **bounded recovery**: after each fault clears, the time until the
  fleet is healthy again (every routed endpoint probing healthy AND a
  canary request completing) is measured and bounded.
- **convergence**: after the full schedule the fleet must walk itself
  back to normal — degrade rung 0, no draining endpoints, all healthy.

Fault kinds composed by the schedule (all five can interleave across
episodes; seeds make any failure replayable):

===================  ====================================================
``kill_replica``     SIGKILL a routed replica mid-traffic (hard death —
                     no drain, no flush), then respawn and re-attach.
``drain_replica``    SIGTERM (the polite path): replica finishes
                     in-flight work, exits ``EXIT_PREEMPTED``; respawn.
``slow_replica``     arm the ``slow_replica`` fault point over the
                     socket ``inject`` op: every reply delayed.
``reject_admission`` arm ``reject_admission``: the replica bounces new
                     keys, forcing the router's free re-route path.
``overload``         submit a burst past the fleet's saturation budget;
                     shed requests must carry ``retry_after_s`` and
                     succeed on honored re-admission.
===================  ====================================================

The harness is transport-real (subprocess replicas over TCP via
:class:`ProcessReplicaSpawner`) but fleet-shape-agnostic: tests can also
hand it an in-process fake spawner. Stdlib-only, like everything else
on the router side of the fleet.
"""

import random
import statistics
import time

from deepspeed_tpu.inference.serving.autoscaler import replica_op
from deepspeed_tpu.inference.serving.router import (
    FleetOverloadError,
    RequestPoisonedError,
)

FAULT_KINDS = ("kill_replica", "drain_replica", "slow_replica",
               "reject_admission", "overload")


def default_make_prompt(rng, vocab=100):
    """Deterministic-from-seed prompt generator (token 0 avoided: some
    models reserve it)."""
    n = rng.randint(3, 10)
    return [rng.randint(1, vocab - 1) for _ in range(n)]


class ChaosReport(dict):
    """Schedule results: per-episode records + the rollup the bench
    gate consumes (``chaos_episodes`` is the artifact-kind marker)."""

    @property
    def ok(self):
        return (self["invariant_bitwise_ok"] and self["invariant_no_stuck"]
                and self["invariant_recovery_bounded"]
                and self["invariant_converged"])


class ChaosHarness:
    """Drive one seeded fault schedule against a live fleet.

    ``reference_fn(prompt, max_new_tokens) -> list[int]`` is the bitwise
    oracle (single-engine ``generate()`` precomputed in-process, or the
    stub token function in router unit tests). ``replicas`` maps the
    router's endpoint names to :class:`SpawnedReplica`-shaped handles so
    faults can kill/drain/respawn the actual processes."""

    def __init__(self, router, spawner, reference_fn, replicas,
                 seed=0, faults=FAULT_KINDS, make_prompt=None,
                 max_new_tokens=8, request_timeout_s=60.0,
                 recovery_timeout_s=60.0, vocab=100):
        self.router = router
        self.spawner = spawner
        self.reference_fn = reference_fn
        self._replicas = {h.name: h for h in replicas}
        self.seed = int(seed)
        self.rng = random.Random(self.seed)
        self.faults = tuple(faults)
        unknown = set(self.faults) - set(FAULT_KINDS)
        if unknown:
            raise ValueError(f"unknown fault kinds: {sorted(unknown)}")
        self.make_prompt = make_prompt or (
            lambda rng: default_make_prompt(rng, vocab))
        self.max_new_tokens = int(max_new_tokens)
        self.request_timeout_s = float(request_timeout_s)
        self.recovery_timeout_s = float(recovery_timeout_s)
        self.episodes = []
        self._respawn_seq = 0

    # -- request plumbing ------------------------------------------------
    def _submit_batch(self, count, shed_retries=0):
        """Submit ``count`` seeded requests; returns [(prompt, future)].
        A synchronous shed (overload episodes with retries exhausted)
        records as a None future — shed is a legal terminal state, not a
        stuck request."""
        out = []
        for _ in range(count):
            prompt = self.make_prompt(self.rng)
            try:
                fut = self.router.submit(
                    prompt, max_new_tokens=self.max_new_tokens,
                    shed_retries=shed_retries)
            except FleetOverloadError:
                fut = None
            out.append((prompt, fut))
        return out

    def _collect(self, batch, record):
        """Resolve every future; folds outcomes into the episode record.
        Completions are checked bitwise against the oracle; structured
        terminal errors (poisoned, shed) are legal; a TimeoutError from
        the future itself is a STUCK request — the invariant killer."""
        deadline = time.monotonic() + self.request_timeout_s
        for prompt, fut in batch:
            if fut is None:
                record["shed"] += 1
                continue
            try:
                tokens = fut.result(
                    timeout=max(0.1, deadline - time.monotonic()))
            except TimeoutError:
                record["stuck"] += 1
                continue
            except (RequestPoisonedError, FleetOverloadError):
                record["errors"] += 1
                continue
            except Exception:
                record["errors"] += 1
                continue
            record["completed"] += 1
            expect = self.reference_fn(prompt, self.max_new_tokens)
            if list(tokens) != list(expect):
                record["bitwise_mismatch"] += 1

    # -- fault application -----------------------------------------------
    def _routed_handles(self):
        names = {ep.name for ep in self.router.endpoints()}
        return [h for n, h in self._replicas.items() if n in names]

    def _respawn(self, old):
        """Replace a dead/drained replica: spawn a fresh process and
        attach it (the autoscaler's attach path, exercised under fire)."""
        self._respawn_seq += 1
        handle = self.spawner.spawn(f"{old.name}.r{self._respawn_seq}")
        self._replicas.pop(old.name, None)
        self._replicas[handle.name] = handle
        self.router.add_endpoint(handle.endpoint())
        return handle

    def _apply_fault(self, kind, record):
        """Arm/execute one fault; returns a ``clear()`` callable that
        undoes it (respawn, disarm) — recovery timing starts after."""
        handles = self._routed_handles()
        if kind in ("kill_replica", "drain_replica") and len(handles) > 1:
            victim = self.rng.choice(handles)
            record["victim"] = victim.name
            if kind == "kill_replica":
                self.spawner.kill(victim)
            else:
                self.spawner.drain(victim, wait_s=self.request_timeout_s)

            def clear(victim=victim):
                try:
                    self.router.remove_endpoint(victim.name)
                except ValueError:
                    pass
                self._respawn(victim)
            return clear
        if kind in ("slow_replica", "reject_admission") and handles:
            victim = self.rng.choice(handles)
            record["victim"] = victim.name
            args = {"op": "inject", "point": kind}
            if kind == "slow_replica":
                args["seconds"] = round(self.rng.uniform(0.05, 0.2), 3)
                args["times"] = self.rng.randint(2, 6)
            else:
                args["times"] = self.rng.randint(1, 4)
            try:
                replica_op(victim.host, victim.port, args)
            except OSError:
                record["inject_failed"] = True

            def clear(victim=victim):
                try:
                    replica_op(victim.host, victim.port,
                               {"op": "inject", "point": None})
                except OSError:
                    pass
            return clear
        # overload (or a degenerate fleet): the fault IS extra traffic
        record["victim"] = None
        burst = self._submit_batch(
            self.rng.randint(4, 8),
            shed_retries=3)             # honor retry_after_s on re-admission
        self._collect(burst, record)
        return lambda: None

    # -- recovery --------------------------------------------------------
    def _await_recovery(self, record):
        """Time from fault-clear until the fleet is demonstrably healthy:
        every routed endpoint probes healthy and non-draining, and one
        canary request completes bitwise-correct."""
        t0 = time.monotonic()
        deadline = t0 + self.recovery_timeout_s
        while time.monotonic() < deadline:
            eps = self.router.probe_all(force=True)
            if all(ep.healthy and not ep.draining for ep in eps):
                break
            time.sleep(0.02)
        else:
            record["recovered"] = False
            record["recovery_s"] = time.monotonic() - t0
            return
        canary = self.make_prompt(self.rng)
        try:
            tokens = self.router.submit(
                canary, max_new_tokens=self.max_new_tokens,
                shed_retries=5).result(
                    timeout=max(0.1, deadline - time.monotonic()))
            record["recovered"] = (
                list(tokens) == list(self.reference_fn(
                    canary, self.max_new_tokens)))
        except Exception:
            record["recovered"] = False
        record["recovery_s"] = time.monotonic() - t0

    # -- the schedule ----------------------------------------------------
    def run_episode(self, kind=None):
        """One episode: traffic before, fault during, traffic after,
        collect, clear, time recovery. Returns the episode record."""
        kind = kind or self.rng.choice(self.faults)
        record = {"kind": kind, "completed": 0, "shed": 0, "errors": 0,
                  "stuck": 0, "bitwise_mismatch": 0}
        before = self._submit_batch(self.rng.randint(1, 3))
        clear = self._apply_fault(kind, record)
        during = self._submit_batch(self.rng.randint(1, 3),
                                    shed_retries=3)
        self._collect(before, record)
        self._collect(during, record)
        clear()
        self._await_recovery(record)
        self.episodes.append(record)
        return record

    def run(self, episodes=20):
        """The full seeded schedule; returns a :class:`ChaosReport`."""
        for _ in range(int(episodes)):
            self.run_episode()
        return self.report()

    def report(self):
        eps = self.episodes
        recoveries = sorted(e["recovery_s"] for e in eps
                            if "recovery_s" in e)
        converged = self._converged()

        def pctl(p):
            if not recoveries:
                return 0.0
            return float(recoveries[min(len(recoveries) - 1,
                                        int(p * len(recoveries)))])

        return ChaosReport({
            "chaos_episodes": len(eps),
            "chaos_seed": self.seed,
            "completed_total": sum(e["completed"] for e in eps),
            "shed_total": sum(e["shed"] for e in eps),
            "errors_total": sum(e["errors"] for e in eps),
            "recovery_p50_s": round(
                statistics.median(recoveries), 4) if recoveries else 0.0,
            "recovery_p95_s": round(pctl(0.95), 4),
            "recovery_max_s": round(
                max(recoveries), 4) if recoveries else 0.0,
            "invariant_bitwise_ok": all(
                e["bitwise_mismatch"] == 0 for e in eps),
            "invariant_no_stuck": all(e["stuck"] == 0 for e in eps),
            "invariant_recovery_bounded": all(
                e.get("recovered", False) for e in eps),
            "invariant_converged": converged,
            "episodes": [dict(e) for e in eps],
        })

    def _converged(self):
        """Post-schedule convergence: healthy fleet, ladder back at 0."""
        eps = self.router.probe_all(force=True)
        healthy = all(ep.healthy and not ep.draining for ep in eps)
        return bool(healthy and self.router.degrade_rung == 0)


DISAGG_FAULT_KINDS = ("kill_prefill_mid_handoff", "kill_decode_post_ack",
                      "corrupt_handoff_frame")

# which serving fault point each disagg episode arms on its victim
_DISAGG_POINTS = {
    "kill_prefill_mid_handoff": "handoff_kill_mid_transfer",
    "kill_decode_post_ack": "handoff_kill_post_ack",
    "corrupt_handoff_frame": "handoff_corrupt_frame",
}


class DisaggChaosHarness(ChaosHarness):
    """Chaos arms for disaggregated prefill/decode serving, on top of
    the base invariants (bitwise exactly-once, no stuck, bounded
    recovery, convergence) plus one of its own — **zero orphaned KV
    pages**: after every episode each replica's pool occupancy returns
    to zero in-use and its handoff receiver holds no pending claims.

    ``kill_prefill_mid_handoff``
        Arm ``handoff_kill_mid_transfer`` on a prefill worker: it dies
        after writing one page frame of a transfer. The router sees the
        hop-1 EOF and re-routes plain; the decode side's half-fed claim
        must be TTL-reaped (run with short ``claim_ttl_s`` so the
        zero-orphan check can observe it).
    ``kill_decode_post_ack``
        Arm ``handoff_kill_post_ack`` on a decode worker: it dies right
        after acking a transfer. The prefill side reports
        ``handoff_done``, hop 2 fails to connect, and the router replays
        plain from its delivered high-water mark — bitwise.
    ``corrupt_handoff_frame``
        Arm ``handoff_corrupt_frame`` on a prefill worker: one page
        frame is bit-flipped after its crc was computed. The receiver's
        crc check rejects it, the claim survives, and the sender's
        bounded retry lands the transfer — nobody dies.

    Lethal episodes respawn the victim **role-preserving** (a decode
    worker comes back as a decode worker) so the fleet topology the
    router scaled for survives the schedule."""

    def __init__(self, router, spawner, reference_fn, replicas, seed=0,
                 faults=DISAGG_FAULT_KINDS, **kw):
        super().__init__(router, spawner, reference_fn, replicas,
                         seed=seed, faults=(), **kw)
        self.faults = tuple(faults)
        unknown = set(self.faults) - set(FAULT_KINDS + DISAGG_FAULT_KINDS)
        if unknown:
            raise ValueError(f"unknown fault kinds: {sorted(unknown)}")

    def _handles_by_role(self, role):
        return [h for h in self._routed_handles()
                if getattr(h, "role", "mixed") == role]

    def _respawn(self, old):
        """Role-preserving respawn: the replacement worker keeps the
        victim's disaggregation role."""
        self._respawn_seq += 1
        handle = self.spawner.spawn(
            f"{old.name}.r{self._respawn_seq}",
            role=getattr(old, "role", None))
        self._replicas.pop(old.name, None)
        self._replicas[handle.name] = handle
        self.router.add_endpoint(handle.endpoint())
        return handle

    def run_episode(self, kind=None):
        kind = kind or self.rng.choice(self.faults)
        if kind not in DISAGG_FAULT_KINDS:
            return super().run_episode(kind)
        record = {"kind": kind, "completed": 0, "shed": 0, "errors": 0,
                  "stuck": 0, "bitwise_mismatch": 0}
        role = "decode" if kind == "kill_decode_post_ack" else "prefill"
        victims = self._handles_by_role(role)
        if not victims:
            # a degenerate fleet (pool scaled to zero): the episode
            # degrades to pure traffic — still invariant-checked
            record["victim"] = None
            self._collect(self._submit_batch(self.rng.randint(2, 4),
                                             shed_retries=3), record)
            record["pages_clean"] = self._pages_clean()
            self.episodes.append(record)
            return record
        victim = self.rng.choice(victims)
        record["victim"] = victim.name
        args = {"op": "inject", "point": _DISAGG_POINTS[kind], "times": 1}
        if kind == "kill_prefill_mid_handoff":
            args["at_step"] = 1         # die after the first page frame
        try:
            replica_op(victim.host, victim.port, args)
        except OSError:
            record["inject_failed"] = True
        # traffic while the arm is live: some of these requests cross the
        # victim and trip the fault mid-handoff
        during = self._submit_batch(self.rng.randint(2, 4), shed_retries=3)
        lethal = kind != "corrupt_handoff_frame"
        if lethal:
            # the kill fires only when a handoff actually crosses the
            # victim; give it a window, then respawn role-preserving so
            # in-flight retries have somewhere to land
            deadline = time.monotonic() + self.request_timeout_s / 4.0
            while victim.alive() and time.monotonic() < deadline:
                time.sleep(0.02)
            record["fired"] = not victim.alive()
            if record["fired"]:
                try:
                    self.router.remove_endpoint(victim.name)
                except ValueError:
                    pass
                self._respawn(victim)
        if not lethal or not record.get("fired"):
            try:                        # disarm a survivor: a stale arm
                replica_op(victim.host, victim.port,   # must not leak into
                           {"op": "inject", "point": None})  # later episodes
            except OSError:
                pass
        self._collect(during, record)
        self._await_recovery(record)
        record["pages_clean"] = self._pages_clean()
        self.episodes.append(record)
        return record

    def _pages_clean(self, timeout_s=None):
        """The zero-orphan invariant: poll every routed replica's health
        until its KV pool shows zero lanes in use and its handoff
        receiver zero pending claims. Polling IS the reaper heartbeat
        (the receiver reaps on every health probe), so an orphaned claim
        clears as soon as its TTL expires."""
        deadline = time.monotonic() + (
            self.recovery_timeout_s if timeout_s is None else timeout_s)
        while time.monotonic() < deadline:
            clean = True
            for ep in self.router.endpoints():
                try:
                    doc = replica_op(ep.host, ep.port, {"op": "health"})
                except OSError:
                    clean = False
                    break
                pool = doc.get("kv_pool") or {}
                if int(pool.get("in_use", 0)) != 0 \
                        or int(doc.get("handoff_pending", 0)) != 0:
                    clean = False
                    break
            if clean:
                return True
            time.sleep(0.05)
        return False

    def report(self):
        rep = super().report()
        disagg = [e for e in self.episodes
                  if e["kind"] in DISAGG_FAULT_KINDS]
        rep["disagg_episodes"] = len(disagg)
        rep["handoff_faults_fired"] = sum(
            1 for e in disagg if e.get("fired"))
        rep["invariant_pages_clean"] = all(
            e.get("pages_clean", True) for e in self.episodes)
        return rep


MEMTIER_FAULT_KINDS = ("corrupt_spill_entry", "torn_spill_write",
                       "host_mem_pressure")


class MemtierChaosHarness(ChaosHarness):
    """Chaos arms for the prefix-cache memory tier (spill store +
    pressure guard), on top of the base invariants (bitwise
    exactly-once, no stuck, bounded recovery, convergence) plus one of
    its own — **spill faults are invisible**: a corrupt blob, a torn
    disk write, or a memory-pressure escalation may cost a re-prefill,
    but must never error, stall, or bitwise-perturb a single request.

    ``corrupt_spill_entry``
        Flip a byte in a spilled prefix blob on a live replica. The next
        promotion of that entry must fail its crc32, drop the record,
        and fall through to a normal suffix prefill.
    ``torn_spill_write``
        The victim's next spill-to-disk writes land truncated under
        their final names (a crash mid-write without the atomic rename
        discipline). The framed reload must reject them on promotion.
    ``host_mem_pressure``
        The victim's ``MemoryPressureGuard`` reads a fake
        over-watermark RSS for several checks, walking
        shed-spill -> pause-inserts -> degrade-rung under live traffic;
        with the arm exhausted the guard (and ladder) must recover.

    Traffic is steered through a small pool of SHARED prompt prefixes
    (``shared_prefix_frac``) so the prefix cache — and therefore its
    spill tier — actually carries state worth corrupting; the rest stays
    fully random like the base harness. All three arms are non-lethal:
    episodes arm over the socket ``inject`` op and disarm after."""

    def __init__(self, router, spawner, reference_fn, replicas, seed=0,
                 faults=MEMTIER_FAULT_KINDS, shared_prefix_len=6,
                 shared_prefix_frac=0.7, vocab=100, **kw):
        super().__init__(router, spawner, reference_fn, replicas,
                         seed=seed, faults=(), vocab=vocab, **kw)
        self.faults = tuple(faults)
        unknown = set(self.faults) - set(FAULT_KINDS + MEMTIER_FAULT_KINDS)
        if unknown:
            raise ValueError(f"unknown fault kinds: {sorted(unknown)}")
        self.shared_prefix_frac = float(shared_prefix_frac)
        # a couple of fixed bases, seeded: enough to force live-tier
        # eviction (hence demotion) without every prompt colliding
        self._bases = [[self.rng.randint(1, vocab - 1)
                        for _ in range(int(shared_prefix_len))]
                       for _ in range(3)]
        self.make_prompt = self._memtier_prompt

    def _memtier_prompt(self, rng):
        if rng.random() < self.shared_prefix_frac:
            base = rng.choice(self._bases)
            tail = [rng.randint(1, 99) for _ in range(rng.randint(1, 3))]
            return list(base) + tail
        return default_make_prompt(rng)

    def _victim_spill_stats(self, victim):
        """Cumulative spill counters from the victim's health doc, {}
        when unreachable or spill-less."""
        try:
            doc = replica_op(victim.host, victim.port, {"op": "health"})
        except OSError:
            return {}
        spill = (doc.get("prefix_cache") or {}).get("spill") or {}
        return {k: int(spill.get(k, 0))
                for k in ("demotions", "promotions", "corrupt_dropped")}

    def run_episode(self, kind=None):
        kind = kind or self.rng.choice(self.faults)
        if kind not in MEMTIER_FAULT_KINDS:
            return super().run_episode(kind)
        record = {"kind": kind, "completed": 0, "shed": 0, "errors": 0,
                  "stuck": 0, "bitwise_mismatch": 0}
        handles = self._routed_handles()
        if not handles:
            record["victim"] = None
            self._collect(self._submit_batch(self.rng.randint(2, 4),
                                             shed_retries=3), record)
            self.episodes.append(record)
            return record
        victim = self.rng.choice(handles)
        record["victim"] = victim.name
        spill_before = self._victim_spill_stats(victim)
        # warm traffic FIRST: the spill tier needs demoted state before
        # corrupting/tearing it means anything
        before = self._submit_batch(self.rng.randint(2, 4))
        self._collect(before, record)
        args = {"op": "inject", "point": kind}
        if kind == "host_mem_pressure":
            args["times"] = self.rng.randint(4, 8)  # pressured guard ticks
        else:
            args["times"] = self.rng.randint(1, 3)
        try:
            replica_op(victim.host, victim.port, args)
        except OSError:
            record["inject_failed"] = True
        during = self._submit_batch(self.rng.randint(2, 4), shed_retries=3)
        self._collect(during, record)
        try:                            # a stale arm must not leak into
            replica_op(victim.host, victim.port,     # later episodes
                       {"op": "inject", "point": None})
        except OSError:
            pass
        self._await_recovery(record)
        spill_after = self._victim_spill_stats(victim)
        record["spill_delta"] = {
            k: spill_after.get(k, 0) - spill_before.get(k, 0)
            for k in spill_after}
        self.episodes.append(record)
        return record

    def report(self):
        rep = super().report()
        mem = [e for e in self.episodes if e["kind"] in MEMTIER_FAULT_KINDS]
        rep["memtier_episodes"] = len(mem)
        rep["spill_corrupt_dropped_total"] = sum(
            e.get("spill_delta", {}).get("corrupt_dropped", 0) for e in mem)
        rep["spill_demotions_total"] = sum(
            e.get("spill_delta", {}).get("demotions", 0) for e in mem)
        # the tentpole's bar: a spill fault may cost a re-prefill, never
        # an errored or stuck request (bitwise is already asserted base)
        rep["invariant_spill_clean"] = all(
            e["errors"] == 0 and e["stuck"] == 0 for e in mem)
        return rep


ROLLOUT_FAULT_KINDS = ("kill_canary_mid_swap", "corrupt_new_tag")


class RolloutChaosHarness(ChaosHarness):
    """Chaos arms for the weight-rollout state machine
    (inference/serving/rollout.py), on top of the base harness's
    invariants (bitwise exactly-once, no stuck, bounded recovery,
    convergence):

    ``kill_canary_mid_swap``
        Commit a good tag, drive the controller into its canary phase
        under live traffic, then SIGKILL a canary replica. The
        controller must detect the crash-loop, roll back down the drain
        path, and the fleet must recover on the incumbent generation —
        with every completed request still bitwise-correct.
    ``corrupt_new_tag``
        Commit a tag that fails manifest verification. The controller
        must refuse it before any process boots on it: the machine never
        leaves idle for that tag, no endpoint ever carries its
        generation, and live traffic is untouched.

    ``commit_good_tag()`` / ``commit_corrupt_tag()`` are injected
    callables returning a fresh tag name — the test/bench owns the
    checkpoint root and how "corrupt" is produced (torn shard, bad
    digest). The controller must be constructed over the same root and
    is stepped inline (not on its background thread) so every episode
    is deterministic from the seed."""

    def __init__(self, router, spawner, reference_fn, replicas, controller,
                 commit_good_tag, commit_corrupt_tag, seed=0,
                 faults=ROLLOUT_FAULT_KINDS, **kw):
        super().__init__(router, spawner, reference_fn, replicas,
                         seed=seed, faults=(), **kw)
        self.controller = controller
        self.commit_good_tag = commit_good_tag
        self.commit_corrupt_tag = commit_corrupt_tag
        self.faults = tuple(faults)
        unknown = set(self.faults) - set(FAULT_KINDS + ROLLOUT_FAULT_KINDS)
        if unknown:
            raise ValueError(f"unknown fault kinds: {sorted(unknown)}")

    def _drive_controller(self, until, timeout_s=30.0):
        """Step the controller inline until its phase lands in ``until``
        (or the deadline passes); returns the final phase."""
        deadline = time.monotonic() + timeout_s
        until = set(until)
        while time.monotonic() < deadline:
            self.controller.step()
            if self.controller.phase in until:
                break
            time.sleep(0.01)
        return self.controller.phase

    def run_episode(self, kind=None):
        kind = kind or self.rng.choice(self.faults)
        if kind not in ROLLOUT_FAULT_KINDS:
            return super().run_episode(kind)
        record = {"kind": kind, "completed": 0, "shed": 0, "errors": 0,
                  "stuck": 0, "bitwise_mismatch": 0}
        if kind == "kill_canary_mid_swap":
            self._episode_kill_canary(record)
        else:
            self._episode_corrupt_tag(record)
        self._await_recovery(record)
        self.episodes.append(record)
        return record

    def _episode_kill_canary(self, record):
        c = self.controller
        tag = self.commit_good_tag()
        record["tag"] = tag
        before = self._submit_batch(self.rng.randint(1, 3))
        phase = self._drive_controller(("canary",),
                                       timeout_s=self.recovery_timeout_s)
        if phase != "canary":
            record["rollout_ok"] = False
            record["victim"] = None
            self._collect(before, record)
            return
        with c._lock:
            canaries = [h for h in c._canaries.values() if h.alive()]
        victim = self.rng.choice(canaries) if canaries else None
        record["victim"] = victim.name if victim else None
        if victim is not None:
            self.spawner.kill(victim)   # hard death mid-swap: no drain
        during = self._submit_batch(self.rng.randint(1, 3), shed_retries=3)
        # the controller must notice the crash-loop and walk the machine
        # back to idle through rolling_back
        phase = self._drive_controller(("idle",),
                                       timeout_s=self.recovery_timeout_s)
        self._collect(before, record)
        self._collect(during, record)
        eps = self.router.endpoints()
        record["rollout_ok"] = (
            phase == "idle"
            and c.metrics.last_rollback_reason == "canary_crash"
            and all(ep.generation == c.current_tag for ep in eps))

    def _episode_corrupt_tag(self, record):
        c = self.controller
        tag = self.commit_corrupt_tag()
        record["tag"] = tag
        record["victim"] = None
        before = self._submit_batch(self.rng.randint(1, 3))
        # give the watcher several polls: the tag must be rejected (valid
        # manifest, corrupt payload) or never observed (torn manifest)
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline and c.phase == "idle" \
                and tag not in c._bad_tags:
            c.step()
            time.sleep(0.01)
        during = self._submit_batch(self.rng.randint(1, 3), shed_retries=3)
        self._drive_controller(("idle",), timeout_s=self.recovery_timeout_s)
        self._collect(before, record)
        self._collect(during, record)
        eps = self.router.endpoints()
        record["rollout_ok"] = (
            c.current_tag != tag
            and all(ep.generation != tag for ep in eps))

    def report(self):
        rep = super().report()
        rep["invariant_rollout_ok"] = all(
            e.get("rollout_ok", True) for e in self.episodes)
        rep["rollbacks_total"] = self.controller.metrics.rollbacks_total
        return rep
