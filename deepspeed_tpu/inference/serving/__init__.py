"""Continuous-batching inference serving (beyond the v0.3.10 reference;
DeepSpeed grew this as DeepSpeed-Inference later).

The one-shot ``generate()`` path answers a fixed batch; this subsystem
answers *traffic*: a bounded admission queue feeds a slot-based KV-cache
pool, and a single compiled masked batched decode step serves every
in-flight request — new requests join whenever a slot frees, finished
ones retire per sequence, and none of that churn recompiles. Prompts are
prefilled in ONE single-pass batched causal forward per same-bucket
admission group (optionally chunked for long prompts, optionally seeded
from the prefix KV cache). Greedy outputs are bitwise identical to
per-request ``generate()`` regardless of arrival order (the oracle in
tests/unit/test_serving.py).

Layering: kv_pool (device state) <- engine (compiled prefill/step +
loop) <- scheduler (host policy: queue/buckets/retirement) <-
prefix_cache (host prompt-KV reuse) <- metrics (monitor). The fleet
tier sits above: replica (one engine behind a line-JSON socket) <-
router (health-aware front-door with failover/drain/shedding).
"""

from deepspeed_tpu.inference.serving.config import (  # noqa: F401
    FleetConfig,
    HandoffConfig,
    RolesConfig,
    RolloutConfig,
    ServingConfig,
)
from deepspeed_tpu.inference.serving.engine import ServingEngine  # noqa: F401
from deepspeed_tpu.inference.serving.fault_injection import (  # noqa: F401
    ServingFaultInjector,
)
from deepspeed_tpu.inference.serving.handoff import (  # noqa: F401
    HandoffError,
    HandoffFrameError,
    HandoffReceiver,
    HandoffRejectedError,
    HandoffRetryError,
    HandoffSender,
    HandoffSizeError,
    HandoffTimeoutError,
)
from deepspeed_tpu.inference.serving.kv_pool import (  # noqa: F401
    KVCachePool,
    PageStateError,
    PoolExhaustedError,
)
from deepspeed_tpu.inference.serving.metrics import (  # noqa: F401
    RolloutMetrics,
    ServingMetrics,
)
from deepspeed_tpu.inference.serving.prefix_cache import (  # noqa: F401
    MemoryPressureGuard,
    PrefixKVCache,
    SpillStore,
    decode_spill_blob,
    encode_spill_blob,
    read_host_rss_mb,
)
from deepspeed_tpu.inference.serving.replica import (  # noqa: F401
    ReplicaServer,
)
from deepspeed_tpu.inference.serving.rollout import (  # noqa: F401
    RolloutController,
)
from deepspeed_tpu.inference.serving.router import (  # noqa: F401
    REPLICA_ROLES,
    FleetOverloadError,
    ReplicaEndpoint,
    RequestPoisonedError,
    Router,
    WrongRoleError,
)
from deepspeed_tpu.inference.serving.scheduler import (  # noqa: F401
    ContinuousBatchingScheduler,
    EngineDrainingError,
    QueueFullError,
    RequestTimeoutError,
    ServingFuture,
    bucket_for,
    default_buckets,
)

__all__ = [
    "ServingEngine", "ServingConfig", "ServingMetrics", "ServingFuture",
    "KVCachePool", "PoolExhaustedError", "PrefixKVCache",
    "ContinuousBatchingScheduler", "QueueFullError", "RequestTimeoutError",
    "EngineDrainingError", "ServingFaultInjector", "bucket_for",
    "default_buckets", "FleetConfig", "Router", "ReplicaEndpoint",
    "ReplicaServer", "FleetOverloadError", "RequestPoisonedError",
    "RolloutConfig", "RolloutController", "RolloutMetrics",
    "RolesConfig", "HandoffConfig", "PageStateError", "REPLICA_ROLES",
    "WrongRoleError", "HandoffError", "HandoffSizeError",
    "HandoffFrameError", "HandoffTimeoutError", "HandoffRejectedError",
    "HandoffRetryError", "HandoffSender", "HandoffReceiver",
    "SpillStore", "MemoryPressureGuard", "encode_spill_blob",
    "decode_spill_blob", "read_host_rss_mb",
]
