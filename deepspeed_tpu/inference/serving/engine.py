"""Continuous-batching serving engine over the KV-cache decode path.

The decode loop is ONE jitted program for the life of the server: a
masked batched step over the pool's ``MaxSlots`` lanes, each lane
running the SAME per-token ``_step`` the one-shot ``generate()`` path
uses (vmapped with a per-lane position counter). ``MaxSlots`` is static,
the lane-active mask and positions are traced operands — so requests
joining, retiring, or swapping slots NEVER recompile.

Prefill is a SINGLE-PASS batched causal forward (``_forward_chunk`` —
the same core ``generate()``/``beam_search()`` prefill with): the
scheduler groups queued requests that share a prompt bucket and
prefills them as one ``[MaxSlots, Sb]`` call straight into their pool
slots, so a prompt of length S costs one whole-sequence forward instead
of S sequential batch-1 matmuls. The batch dimension is padded to the
static ``MaxSlots`` and per-lane starts/true-lengths are traced, so the
compile count stays bounded by the bucket ladder — never by how many
requests happen to arrive together. Long prompts can additionally be
split into fixed-size chunks (``serving.prefill_chunk_tokens``)
interleaved with decode steps, and previously-served prompt prefixes
can be seeded from the prefix KV cache (``serving.prefix_cache_mb``,
prefix_cache.py) instead of recomputed.

Correctness oracle (tests/unit/test_serving.py): continuous-batched
greedy output is BITWISE equal to per-request ``generate()`` output for
any arrival order. Why it holds:

- prefill pads the prompt up to its bucket but *selects* the logits at
  the true last prompt position; a valid query position only ever
  attends true prompt tokens (causal mask), so the selected logits
  match the unpadded forward;
- pad/stale cache beyond a lane's position is either overwritten before
  it is reachable (decode writes position p before attending to it) or
  hidden by the causal mask, whose -1e30 scores underflow to exactly 0
  probability — extra masked cache length is numerically invisible;
- lanes are vmapped, hence computed independently: a neighbor admitting,
  retiring, or holding garbage cannot perturb another lane's values
  (the batch-independence property test_generation.py already pins);
- a prefix-cache hit seeds bits a previous identical computation
  produced, so seeding and recomputing are the same bits.

Greedy only: serving argmax-decodes (temperature-0), the mode with a
bitwise oracle. Sampling needs per-request RNG streams and is future
work.
"""

import threading
import time
from contextlib import nullcontext
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.generation import _forward_chunk, _ln, _step
from deepspeed_tpu.profiling.sentinels import CompileSentinel, transfer_free
from deepspeed_tpu import telemetry
from deepspeed_tpu.inference.quantization import logits_table
from deepspeed_tpu.inference.serving.config import ServingConfig
from deepspeed_tpu.inference.serving.fault_injection import ServingFaultInjector
from deepspeed_tpu.inference.serving.kv_pool import KVCachePool
from deepspeed_tpu.inference.serving.metrics import ServingMetrics
from deepspeed_tpu.inference.serving.prefix_cache import PrefixKVCache
from deepspeed_tpu.inference.serving.scheduler import (
    ContinuousBatchingScheduler,
    RequestTimeoutError,
    bucket_for,
    default_buckets,
)


@partial(jax.jit, static_argnames=("n_heads",), donate_argnums=(1, 2))
def _prefill_batch_jit(params, init_k, init_v, padded_ids, starts, true_lens,
                       *, n_heads):
    """Single-pass batched prefill: ``padded_ids`` [B, Sb] (each lane's
    to-be-computed tokens, right-padded to the bucket) forwarded in ONE
    causal call into ``init_k``/``init_v`` ([L, B, nh, S_max, hd] —
    zeros, or prefix-cache KV for lanes resuming at ``starts[i] > 0``).
    Returns (k, v, first greedy token per lane).

    ``starts`` and ``true_lens`` are traced [B] vectors, so ONE compiled
    program per (B, Sb, S_max) serves every group composition: plain
    prompts, prefix-cache hits at any offset, and (at B=1, Sb=chunk)
    every chunk of a chunked prefill. The logits are *selected* at each
    lane's true last prompt position, which makes both pad tokens and
    dummy lanes invisible to the emitted token."""
    B, Sb = padded_ids.shape
    tr = params["params"]["transformer"]
    h, (k, v) = _forward_chunk(params, n_heads, (init_k, init_v),
                               padded_ids, starts)
    idx = jnp.clip(true_lens - 1 - starts, 0, Sb - 1)
    h_sel = jnp.take_along_axis(h, idx[:, None, None], axis=1)[:, 0]
    h_sel = _ln(h_sel, tr["ln_f"])
    logits = h_sel @ logits_table(tr["wte"], h_sel.dtype).T
    first = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return k, v, first


@partial(jax.jit, static_argnames=("n_heads",), donate_argnums=(1, 2, 3, 4))
def _decode_step_jit(params, pool_k, pool_v, tokens, positions, active, *,
                     n_heads):
    """One masked batched decode step over every pool lane.

    Each lane feeds its last token at its own position through the
    one-shot path's ``_step`` (vmapped as a B=1 lane). Inactive lanes
    compute garbage into their own (dead) lane and keep their token via
    the ``active`` mask; pool buffers, tokens and positions are donated —
    the step is an in-place update of device-resident serving state, and
    active lanes advance their position counter HERE, so steady-state
    decode needs no per-step host->device upload at all."""

    def lane(ck, cv, tok, pos):
        logits, (ck2, cv2) = _step(params, n_heads, (ck[:, None], cv[:, None]),
                                   tok[None], pos)
        return logits[0], ck2[:, 0], cv2[:, 0]

    logits, pool_k, pool_v = jax.vmap(
        lane, in_axes=(1, 1, 0, 0), out_axes=(0, 1, 1))(
        pool_k, pool_v, tokens, positions)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    tokens = jnp.where(active, nxt, tokens)
    positions = jnp.where(active, positions + 1, positions)
    return tokens, positions, pool_k, pool_v


class _ChunkedPrefill:
    """In-flight chunked prefill: the request, its private cache pair
    (carried across engine steps between chunk calls), how far it has
    prefilled, and the pool slot reserved for it at start."""

    __slots__ = ("req", "k", "v", "pos", "reuse", "slot", "prefill_s")

    def __init__(self, req, k, v, pos, reuse, slot):
        self.req = req
        self.k = k
        self.v = v
        self.pos = pos
        self.reuse = reuse
        self.slot = slot
        self.prefill_s = 0.0


class ServingEngine:
    """Request queue + KV pool + the single compiled decode loop.

    Drive it synchronously (``step()`` / ``drain()`` — deterministic, what
    the tests do) or as a background thread (``start()`` / ``stop()``)
    with ``submit()`` from any thread."""

    def __init__(self, params, model_config, serving_config=None,
                 monitor=None, injector=None, sentinel_config=None,
                 telemetry_config=None):
        cfg = serving_config or ServingConfig()
        self.params = params
        self.model_config = model_config
        self.config = cfg
        self.n_layers = model_config.num_hidden_layers
        self.n_heads = model_config.num_attention_heads
        self.head_dim = model_config.hidden_size // self.n_heads

        mpe = model_config.max_position_embeddings
        self.max_seq_len = cfg.max_seq_len or mpe
        if self.max_seq_len > mpe:
            raise ValueError(
                f"serving.max_seq_len={self.max_seq_len} exceeds "
                f"max_position_embeddings={mpe}")
        buckets = cfg.prompt_buckets or default_buckets(self.max_seq_len - 1)
        if buckets[-1] > self.max_seq_len - 1:
            raise ValueError(
                f"largest prompt bucket ({buckets[-1]}) must leave room for "
                f"one generated token (max_seq_len={self.max_seq_len})")
        if cfg.prefill_chunk_tokens < 0:
            raise ValueError(
                f"serving.prefill_chunk_tokens must be >= 0 "
                f"(0 disables chunked prefill), got {cfg.prefill_chunk_tokens}")
        if cfg.prefix_cache_mb < 0:
            raise ValueError(
                f"serving.prefix_cache_mb must be >= 0 "
                f"(0 disables the prefix cache), got {cfg.prefix_cache_mb}")

        tr = params["params"]["transformer"]
        emb_dtype = (jnp.float32 if "kernel_q" in tr["wte"]
                     else tr["wte"]["embedding"].dtype)
        dtype = jnp.result_type(emb_dtype, tr["wpe"]["embedding"].dtype)
        self.pool = KVCachePool(self.n_layers, cfg.max_slots, self.n_heads,
                                self.max_seq_len, self.head_dim, dtype=dtype)
        self.scheduler = ContinuousBatchingScheduler(
            max_queue=cfg.max_queue, buckets=buckets,
            default_max_new_tokens=cfg.default_max_new_tokens,
            request_timeout_s=cfg.request_timeout_s)
        self.metrics = ServingMetrics(monitor)
        self.prefix_cache = (
            PrefixKVCache(max(1, int(cfg.prefix_cache_mb * 2 ** 20)))
            if cfg.prefix_cache_mb > 0 else None)
        if injector is None and cfg.fault_injection:
            injector = ServingFaultInjector(cfg.fault_injection)
        self.injector = injector

        self._active = {}                                   # slot -> Request
        self._lane_tokens = np.zeros(cfg.max_slots, np.int32)
        self._lane_active = np.zeros(cfg.max_slots, bool)
        # device-resident decode operands: uploaded ONLY on lane churn
        # (_lane_dirty), advanced in-jit otherwise — steady-state decode
        # performs exactly one explicit transfer per step (the EOS read)
        self._dev_tokens = None
        self._dev_positions = None
        self._dev_active = None
        self._lane_dirty = True
        if sentinel_config is not None and sentinel_config.enabled:
            budget = sentinel_config.compile_budget
            self.decode_sentinel = CompileSentinel(
                _decode_step_jit, budget, name="serving decode step")
            self.prefill_sentinel = CompileSentinel(
                _prefill_batch_jit, budget, name="serving batched prefill")
            self._transfer_guard = bool(sentinel_config.transfer_guard)
        else:
            self.decode_sentinel = None
            self.prefill_sentinel = None
            self._transfer_guard = False
        # batched prefill always runs at the pool width: the batch dim is
        # STATIC, so any admission-group size shares one program per bucket
        self._prefill_batch = cfg.max_slots
        self._chunking = None               # at most one chunked prefill
        self._step_count = 0
        self._loop_thread = None
        self._stop = threading.Event()

        # telemetry: an explicit block arms the process-global tracer and
        # registry; an absent block leaves them untouched. Hot-path guard
        # is one attribute read (self._tracer.enabled).
        telemetry.configure_from_config(telemetry_config)
        self._tracer = telemetry.get_tracer()
        self._trace_file = None
        self.telemetry_server = None
        if telemetry_config is not None and telemetry_config.enabled:
            self._trace_file = telemetry_config.trace_file
            self.metrics.export_to(telemetry.get_registry())
            if telemetry_config.http_port is not None:
                self.telemetry_server = self._build_telemetry_server(
                    telemetry_config.http_port)

    def _build_telemetry_server(self, port):
        srv = telemetry.TelemetryServer(
            registry=telemetry.get_registry(), tracer=self._tracer, port=port)
        srv.add_snapshot_provider("serving", self.metrics.snapshot)
        srv.add_snapshot_provider("kv_pool", self.occupancy)
        srv.add_snapshot_provider("prefix_cache", self.prefix_stats)
        srv.add_health_provider("serving_loop", self._loop_health)
        return srv.start()

    def _loop_health(self):
        """Healthy unless a background loop was started and then died
        (synchronous step()/drain() driving is always healthy)."""
        t = self._loop_thread
        return {"healthy": t is None or t.is_alive(),
                "background_loop": t is not None,
                "steps": self._step_count,
                "active_requests": len(self._active),
                "queue_depth": self.scheduler.queue_depth()}

    @classmethod
    def from_config(cls, params, model_config, ds_config, rank=0,
                    injector=None):
        """Build from a ds_config (dict or DeepSpeedConfig): the validated
        ``serving`` block plus the shared monitor construction path."""
        from deepspeed_tpu.monitor import monitor_from_config
        from deepspeed_tpu.runtime.config import DeepSpeedConfig

        if isinstance(ds_config, dict):
            ds_config = DeepSpeedConfig(ds_config, world_size=1)
        return cls(params, model_config,
                   serving_config=ds_config.serving_config,
                   monitor=monitor_from_config(ds_config, rank),
                   injector=injector,
                   sentinel_config=ds_config.sentinel_config,
                   telemetry_config=ds_config.telemetry_config)

    # -- request intake -------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens=None, eos_token_id=None,
               timeout_s=None, stream_cb=None):
        """Queue one request; returns its ``ServingFuture``.

        ``prompt_ids`` is a 1-D token sequence. Raises ``QueueFullError``
        when the admission queue is at capacity (backpressure) and
        ``ValueError`` for requests that can never fit. ``stream_cb``
        (optional) is called as ``stream_cb(request_id, token)`` for every
        generated token, including the first."""
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if len(prompt) < 1:
            raise ValueError("prompt must contain at least one token")
        if max_new_tokens is None:
            max_new_tokens = self.config.default_max_new_tokens
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        bucket_for(len(prompt), self.scheduler.buckets)  # raises if too long
        total = len(prompt) + int(max_new_tokens)
        if total > self.max_seq_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"= {total} exceeds serving max_seq_len={self.max_seq_len}")
        if eos_token_id is not None and not (
                0 <= int(eos_token_id) < self.model_config.vocab_size):
            raise ValueError(
                f"eos_token_id={eos_token_id} outside vocab "
                f"[0, {self.model_config.vocab_size})")
        req = self.scheduler.submit(
            prompt, max_new_tokens=int(max_new_tokens),
            eos_token_id=None if eos_token_id is None else int(eos_token_id),
            timeout_s=timeout_s, stream_cb=stream_cb)
        return req.future

    # -- the serving loop ----------------------------------------------
    def step(self):
        """One scheduler iteration: expire, advance any chunked prefill,
        admit (batched per bucket), one batched decode step, retire.
        Returns an activity dict (all zeros = idle)."""
        now = time.monotonic()
        stats = {"admitted": 0, "decoded": 0, "retired": 0,
                 "prefill_chunks": 0}

        for req in self.scheduler.pop_expired(now):
            self._finish_timeout(req, phase="queued")
            stats["retired"] += 1

        # one chunk per step: a long prompt makes progress without ever
        # stalling the in-flight lanes' inter-token latency
        if self._chunking is not None:
            self._advance_chunk(stats)

        self._admit_from_queue(stats)

        if self.injector is not None:
            self.injector.maybe_evict_prefix(self._step_count,
                                             self.prefix_cache)
        if self._active:
            if self.injector is not None:
                self.injector.maybe_slow_decode(self._step_count)
            # span args (request ids) are built ONLY when tracing is armed:
            # disabled-mode cost is this one attribute read
            if self._tracer.enabled:
                dspan = self._tracer.span(
                    "serving/decode_step", cat="serving",
                    args={"request_ids": [r.id for r in self._active.values()],
                          "active": len(self._active)})
            else:
                dspan = telemetry.NULL_SPAN
            dspan.__enter__()
            t0 = time.monotonic()
            if self._lane_dirty:
                # lane churn: ONE explicit upload of the lane vectors;
                # between churn events they live on device and never move
                self._dev_tokens, self._dev_positions, self._dev_active = \
                    jax.device_put(  # jaxlint: disable=JL002(churn-only explicit upload)
                        (self._lane_tokens,
                         np.ascontiguousarray(self.pool.positions,
                                              dtype=np.int32),
                         self._lane_active))
                self._lane_dirty = False
            guard = transfer_free() if self._transfer_guard else nullcontext()
            with guard:
                (self._dev_tokens, self._dev_positions,
                 self.pool.k, self.pool.v) = _decode_step_jit(
                    self.params, self.pool.k, self.pool.v,
                    self._dev_tokens, self._dev_positions, self._dev_active,
                    n_heads=self.n_heads)
            if self.decode_sentinel is not None:
                self.decode_sentinel.check()
            # the step's single deliberate sync: EOS checks need the tokens
            host_tokens = jax.device_get(self._dev_tokens)  # jaxlint: disable=JL002(one explicit host read per step)
            step_s = time.monotonic() - t0
            dspan.__exit__(None, None, None)
            self._lane_tokens = host_tokens.copy()
            toks = host_tokens.tolist()
            now = time.monotonic()
            n_active = len(self._active)
            for slot in list(self._active):
                req = self._active[slot]
                self.pool.advance(slot)
                self._emit(req, toks[slot])
                stats["decoded"] += 1
                stats["retired"] += self._maybe_retire(req, toks[slot], now)
            self.metrics.record_step(
                queue_depth=self.scheduler.queue_depth(),
                active_slots=n_active, max_slots=self.pool.max_slots,
                tokens_this_step=n_active, step_s=step_s)
        self._step_count += 1
        return stats

    def drain(self, max_steps=None):
        """Step until no request is queued, prefilling, or in flight.
        ``max_steps`` bounds the loop (a deadline-less stuck request
        would otherwise spin forever under fault injection)."""
        steps = 0
        while (self._active or self._chunking is not None
               or self.scheduler.queue_depth() > 0):
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return steps

    # -- background mode ------------------------------------------------
    def start(self, idle_sleep_s=0.001):
        """Run the serving loop on a daemon thread until ``stop()``."""
        if self._loop_thread is not None:
            raise RuntimeError("serving loop already running")
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                busy = self.step()
                if not any(busy.values()) and not self._active:
                    time.sleep(idle_sleep_s)

        self._loop_thread = threading.Thread(
            target=loop, name="serving-loop", daemon=True)
        self._loop_thread.start()

    def stop(self, timeout_s=5.0):
        if self._loop_thread is None:
            return
        self._stop.set()
        self._loop_thread.join(timeout_s)
        self._loop_thread = None

    def close(self):
        self.stop()
        self.metrics.close()
        if self.telemetry_server is not None:
            self.telemetry_server.stop()
            self.telemetry_server = None
        if self._trace_file:
            self._tracer.write(self._trace_file)

    # -- admission ------------------------------------------------------
    def _admit_from_queue(self, stats):
        """Join-at-free-slot admission, batched per bucket: pop the FIFO
        head, gather every queued request sharing its (prefix-adjusted)
        bucket up to the free-slot count, and prefill them as ONE call.
        Long prompts divert to the chunked path (one at a time)."""
        if self._tracer.enabled and self.scheduler.queue_depth() > 0:
            with self._tracer.span(
                    "serving/admission", cat="serving",
                    args={"queue_depth": self.scheduler.queue_depth()}):
                self._admit_from_queue_now(stats)
        else:
            self._admit_from_queue_now(stats)

    def _admit_from_queue_now(self, stats):
        while self.pool.free_slots > 0:
            head = self.scheduler.pop_next()
            if head is None:
                return
            if self._needs_chunking(head):
                if self._chunking is None:
                    self._start_chunked(head)
                    stats["admitted"] += 1
                    continue
                self.scheduler.requeue_front(head)   # chunk lane is busy
                return
            bucket = bucket_for(self._suffix_len(head), self.scheduler.buckets)
            group = [head]
            room = min(self.pool.free_slots - 1, self._prefill_batch - 1)
            if room > 0:
                group += self.scheduler.pop_matching(
                    lambda r: (not self._needs_chunking(r)
                               and bucket_for(self._suffix_len(r),
                                              self.scheduler.buckets)
                               == bucket),
                    room)
            stats["admitted"] += len(group)
            stats["retired"] += self._admit_batch(group, bucket)

    def _admit_batch(self, group, bucket):
        """Prefill ``group`` (same bucket) as one [MaxSlots, bucket] call
        and install each lane into its slot. Returns how many requests
        retired on their very first token."""
        pspan = (self._tracer.span(
                     "serving/prefill_batch", cat="serving",
                     args={"request_ids": [r.id for r in group],
                           "bucket": bucket})
                 if self._tracer.enabled else telemetry.NULL_SPAN)
        pspan.__enter__()
        B, total = self._prefill_batch, self.max_seq_len
        ids = np.zeros((B, bucket), np.int32)
        starts = np.zeros(B, np.int32)
        lens = np.ones(B, np.int32)        # dummy lanes: 1-token no-ops
        plan = []
        any_hit = False
        for i, req in enumerate(group):
            reuse, entry = self._acquire_prefix(req)
            suffix = req.prompt[reuse:]
            ids[i, :len(suffix)] = suffix
            starts[i] = reuse
            lens[i] = len(req.prompt)
            plan.append((req, reuse, entry))
            any_hit = any_hit or reuse > 0
        shape = (self.n_layers, B, self.n_heads, total, self.head_dim)
        if any_hit:
            # seed hit lanes from host-resident prefix KV; one transfer
            init_k = np.zeros(shape, self.pool.k.dtype)
            init_v = np.zeros(shape, self.pool.k.dtype)
            for i, (req, reuse, entry) in enumerate(plan):
                if reuse > 0:
                    init_k[:, i, :, :reuse] = entry.k[:, :, :reuse]
                    init_v[:, i, :, :reuse] = entry.v[:, :, :reuse]
            init_k, init_v = jnp.asarray(init_k), jnp.asarray(init_v)
        else:
            init_k = jnp.zeros(shape, self.pool.k.dtype)
            init_v = jnp.zeros(shape, self.pool.k.dtype)

        t0 = time.monotonic()
        k, v, first = _prefill_batch_jit(
            self.params, init_k, init_v, jnp.asarray(ids),
            jnp.asarray(starts), jnp.asarray(lens), n_heads=self.n_heads)
        if self.prefill_sentinel is not None:
            self.prefill_sentinel.check()
        first_host = np.asarray(first)             # sync: TTFT endpoint
        prefill_s = time.monotonic() - t0
        self.metrics.record_prefill(
            tokens=sum(len(r.prompt) - re for r, re, _ in plan),
            reused_tokens=sum(re for _, re, _ in plan),
            requests=len(group), prefill_s=prefill_s)

        now = time.monotonic()
        retired = 0
        for i, (req, reuse, entry) in enumerate(plan):
            self._maybe_insert_prefix(req, reuse, k, v, lane=i)
            slot = self.pool.allocate()
            self.pool.install_lane(k, v, lane=i, slot=slot,
                                   position=len(req.prompt))
            req.prefix_entry = entry
            req.first_token_time = now
            self.metrics.record_first_token(now - req.submit_time)
            self._activate(req, slot, int(first_host[i]))
            retired += self._maybe_retire(req, int(first_host[i]), now)
        # settle the queued lane installs here so they are accounted to
        # admission, not silently absorbed into the next decode step's
        # measured latency
        self.pool.k.block_until_ready()
        pspan.__exit__(None, None, None)
        return retired

    # -- chunked prefill ------------------------------------------------
    def _needs_chunking(self, req):
        chunk = self.config.prefill_chunk_tokens
        return chunk > 0 and self._suffix_len(req) > chunk

    def _start_chunked(self, req):
        """Reserve a slot and a private cache for ``req`` and let
        ``_advance_chunk`` feed it one chunk per engine step."""
        reuse, entry = self._acquire_prefix(req)
        req.prefix_entry = entry
        slot = self.pool.allocate()       # reserved: completion can't stall
        shape = (self.n_layers, 1, self.n_heads, self.max_seq_len,
                 self.head_dim)
        if reuse > 0:
            k0 = np.zeros(shape, self.pool.k.dtype)
            v0 = np.zeros(shape, self.pool.k.dtype)
            k0[:, 0, :, :reuse] = entry.k[:, :, :reuse]
            v0[:, 0, :, :reuse] = entry.v[:, :, :reuse]
            k0, v0 = jnp.asarray(k0), jnp.asarray(v0)
        else:
            k0 = jnp.zeros(shape, self.pool.k.dtype)
            v0 = jnp.zeros(shape, self.pool.k.dtype)
        self._chunking = _ChunkedPrefill(req, k0, v0, pos=reuse, reuse=reuse,
                                         slot=slot)

    def _advance_chunk(self, stats):
        """Run the next chunk of the in-flight chunked prefill (same
        compiled program as batched prefill, at B=1/Sb=chunk); install
        and activate on the final chunk. Mid chunks never block the host
        — only the final chunk syncs, for its first token."""
        st = self._chunking
        req = st.req
        now = time.monotonic()
        if req.deadline_exceeded(now):
            req.slot = st.slot             # hand the reserved slot back
            self._finish_timeout(req, phase="prefill")
            self._chunking = None
            stats["retired"] += 1
            return
        chunk_len = self.config.prefill_chunk_tokens
        chunk = req.prompt[st.pos:st.pos + chunk_len]
        ids = np.zeros((1, chunk_len), np.int32)
        ids[0, :len(chunk)] = chunk
        cspan = (self._tracer.span("serving/prefill_chunk", cat="serving",
                                   args={"request_id": req.id, "pos": st.pos,
                                         "chunk": len(chunk)})
                 if self._tracer.enabled else telemetry.NULL_SPAN)
        t0 = time.monotonic()
        with cspan:
            st.k, st.v, first = _prefill_batch_jit(
                self.params, st.k, st.v, jnp.asarray(ids),
                jnp.asarray([st.pos], jnp.int32),
                jnp.asarray([len(req.prompt)], jnp.int32),
                n_heads=self.n_heads)
            if self.prefill_sentinel is not None:
                self.prefill_sentinel.check()
        st.pos += len(chunk)
        stats["prefill_chunks"] += 1
        if st.pos < len(req.prompt):
            st.prefill_s += time.monotonic() - t0
            return
        first_tok = int(np.asarray(first)[0])      # sync: TTFT endpoint
        st.prefill_s += time.monotonic() - t0
        now = time.monotonic()
        self.metrics.record_prefill(
            tokens=len(req.prompt) - st.reuse, reused_tokens=st.reuse,
            requests=1, prefill_s=st.prefill_s)
        self._maybe_insert_prefix(req, st.reuse, st.k, st.v, lane=0)
        self.pool.install(st.k, st.v, st.slot, position=len(req.prompt))
        req.first_token_time = now
        self.metrics.record_first_token(now - req.submit_time)
        self._activate(req, st.slot, first_tok)
        stats["retired"] += self._maybe_retire(req, first_tok, now)
        self._chunking = None

    # -- prefix cache ---------------------------------------------------
    def _suffix_len(self, req):
        """Tokens a prefill would actually compute for ``req`` after
        prefix-cache reuse (always >= 1: the last prompt position is
        recomputed to produce the first token's logits)."""
        if self.prefix_cache is None:
            return len(req.prompt)
        length, _ = self.prefix_cache.match(req.prompt)
        return len(req.prompt) - min(length, len(req.prompt) - 1)

    def _acquire_prefix(self, req):
        """Counted, ref-taking lookup at admission time. Returns
        (reused_tokens, entry-or-None); the ref is released at the
        request's retirement (any path)."""
        if self.prefix_cache is None:
            return 0, None
        length, entry = self.prefix_cache.acquire(req.prompt)
        reuse = min(length, len(req.prompt) - 1)
        if entry is not None and reuse <= 0:
            self.prefix_cache.release(entry)
            entry, reuse = None, 0
        self.metrics.record_prefix_lookup(hit=reuse > 0)
        return reuse, entry

    def _maybe_insert_prefix(self, req, reuse, k, v, lane):
        """Store the freshly-prefilled prompt's KV for future requests
        (skipped when an existing entry already covers the whole prompt
        — nothing new to add)."""
        if self.prefix_cache is None:
            return
        n = len(req.prompt)
        if reuse >= n - 1:
            return
        self.prefix_cache.insert(
            req.prompt,
            np.asarray(k[:, lane, :, :n]), np.asarray(v[:, lane, :, :n]))

    # -- internals ------------------------------------------------------
    def _activate(self, req, slot, first_tok):
        req.slot = slot
        self._active[slot] = req
        self._lane_tokens[slot] = first_tok
        self._lane_active[slot] = True
        self._lane_dirty = True
        self._emit(req, first_tok)

    def _emit(self, req, token):
        req.emitted += 1
        req.future._append(token)
        if req.stream_cb is not None:
            try:
                req.stream_cb(req.id, token)
            except Exception:  # a broken callback must not kill the loop
                pass

    def _maybe_retire(self, req, token, now):
        stuck = (self.injector is not None
                 and self.injector.request_is_stuck(req.id))
        if req.deadline_exceeded(now):
            self._finish_timeout(req, phase="decoding")
            return 1
        if self.scheduler.should_retire(req, token, stuck=stuck) is not None:
            self._release_slot(req)
            req.future._finish()
            self.scheduler.completed += 1
            self.metrics.record_completion()
            if self._tracer.enabled:
                self._tracer.instant("serving/retire", cat="serving",
                                     args={"request_id": req.id,
                                           "tokens": req.emitted})
            return 1
        return 0

    def _finish_timeout(self, req, phase):
        self._release_slot(req)
        if self._tracer.enabled:
            self._tracer.instant("serving/retire_timeout", cat="serving",
                                 args={"request_id": req.id, "phase": phase,
                                       "tokens": req.emitted})
        req.future._finish(RequestTimeoutError(
            req.id, req.timeout_s, phase, tokens_done=req.emitted))
        self.scheduler.timed_out += 1
        self.metrics.record_timeout()

    def _release_slot(self, req):
        if req.slot is not None:
            self._lane_active[req.slot] = False
            self._lane_dirty = True
            self._active.pop(req.slot, None)
            self.pool.free(req.slot)
            req.slot = None
        if req.prefix_entry is not None and self.prefix_cache is not None:
            self.prefix_cache.release(req.prefix_entry)
            req.prefix_entry = None

    # -- introspection ---------------------------------------------------
    def occupancy(self):
        return self.pool.occupancy()

    def prefix_stats(self):
        """Prefix-cache counters, or None when the cache is disabled."""
        return None if self.prefix_cache is None else self.prefix_cache.stats()
