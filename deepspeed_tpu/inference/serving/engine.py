"""Continuous-batching serving engine over the KV-cache decode path.

The decode loop is ONE jitted program for the life of the server: a
masked batched step over the pool's ``MaxSlots`` lanes, each lane
running the SAME per-token ``_step`` the one-shot ``generate()`` path
uses (vmapped with a per-lane position counter). ``MaxSlots`` is static,
the lane-active mask and positions are traced operands — so requests
joining, retiring, or swapping slots NEVER recompile.

Prefill is a SINGLE-PASS batched causal forward (``_forward_chunk`` —
the same core ``generate()``/``beam_search()`` prefill with): the
scheduler groups queued requests that share a prompt bucket and
prefills them as one ``[MaxSlots, Sb]`` call straight into their pool
slots, so a prompt of length S costs one whole-sequence forward instead
of S sequential batch-1 matmuls. The batch dimension is padded to the
static ``MaxSlots`` and per-lane starts/true-lengths are traced, so the
compile count stays bounded by the bucket ladder — never by how many
requests happen to arrive together. Long prompts can additionally be
split into fixed-size chunks (``serving.prefill_chunk_tokens``)
interleaved with decode steps, and previously-served prompt prefixes
can be seeded from the prefix KV cache (``serving.prefix_cache_mb``,
prefix_cache.py) instead of recomputed.

Correctness oracle (tests/unit/test_serving.py): continuous-batched
greedy output is BITWISE equal to per-request ``generate()`` output for
any arrival order. Why it holds:

- prefill pads the prompt up to its bucket but *selects* the logits at
  the true last prompt position; a valid query position only ever
  attends true prompt tokens (causal mask), so the selected logits
  match the unpadded forward;
- pad/stale cache beyond a lane's position is either overwritten before
  it is reachable (decode writes position p before attending to it) or
  hidden by the causal mask, whose -1e30 scores underflow to exactly 0
  probability — extra masked cache length is numerically invisible;
- lanes are vmapped, hence computed independently: a neighbor admitting,
  retiring, or holding garbage cannot perturb another lane's values
  (the batch-independence property test_generation.py already pins);
- a prefix-cache hit seeds bits a previous identical computation
  produced, so seeding and recomputing are the same bits.

Greedy only: serving argmax-decodes (temperature-0), the mode with a
bitwise oracle. Sampling needs per-request RNG streams and is future
work.

Speculative decoding (``serving.speculative_k > 0``): each step drafts
``k`` tokens per lane with a free n-gram drafter over the lane's own
history (no second model), verifies all k+1 positions in ONE batched
causal forward (the same ``_forward_chunk`` core prefill uses), and
emits the longest draft prefix the greedy oracle confirms — plus the
oracle's own next token, so every step yields between 1 and k+1 tokens
per lane. Emitted tokens always COME FROM the oracle, so draft quality
affects only throughput, never output: the emitted sequence is
output-identical to ``speculative_k=0`` (and the k=0 path itself stays
bitwise — it runs the exact same program as before). Rejected drafts
need no KV rollback: their stale cache rows sit inside the next step's
k+1-wide write window and are overwritten before any mask can expose
them, so "rollback" is just advancing the position counter by
accepted+1. ``k`` and ``MaxSlots`` are static; acceptance counts,
drafts, and noise are traced — variable acceptance never recompiles and
steady state still runs under ``transfer_free()``.

KV quantization (``serving.kv_cache_dtype``): "fp32" stores the model's
compute dtype (bitwise-transparent default); "bf16" and "int8" store
the pool narrower and dequantize at use inside the decode/verify reads
(int8 carries per-(slot, head) symmetric scales, fixed at install — see
kv_pool.py). Quantized modes trade a threshold-based parity oracle
(token-match rate, allclose attention outputs) for 2-4x more KV slots
per byte.
"""

import threading
import time
from contextlib import nullcontext
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.generation import (
    _cache_dtype,
    _forward_chunk,
    _ln,
    _ngram_draft,
    _speculative_verify,
    _step,
)
from deepspeed_tpu.profiling.sentinels import CompileSentinel, transfer_free
from deepspeed_tpu import telemetry
from deepspeed_tpu.inference.quantization import (
    dequantize_kv,
    dequantize_kv_np,
    logits_table,
    quantize_kv_np,
    requantize_kv,
    vocab_size,
)
from deepspeed_tpu.inference.serving.config import ServingConfig
from deepspeed_tpu.inference.serving.fault_injection import ServingFaultInjector
from deepspeed_tpu.inference.serving.kv_pool import KV_CACHE_DTYPES, KVCachePool
from deepspeed_tpu.inference.serving.metrics import ServingMetrics
from deepspeed_tpu.inference.serving.prefix_cache import PrefixKVCache
from deepspeed_tpu.inference.serving.scheduler import (
    ContinuousBatchingScheduler,
    RequestTimeoutError,
    bucket_for,
    default_buckets,
)


@partial(jax.jit, static_argnames=("n_heads",), donate_argnums=(1, 2))
def _prefill_batch_jit(params, init_k, init_v, padded_ids, starts, true_lens,
                       *, n_heads):
    """Single-pass batched prefill: ``padded_ids`` [B, Sb] (each lane's
    to-be-computed tokens, right-padded to the bucket) forwarded in ONE
    causal call into ``init_k``/``init_v`` ([L, B, nh, S_max, hd] —
    zeros, or prefix-cache KV for lanes resuming at ``starts[i] > 0``).
    Returns (k, v, first greedy token per lane).

    ``starts`` and ``true_lens`` are traced [B] vectors, so ONE compiled
    program per (B, Sb, S_max) serves every group composition: plain
    prompts, prefix-cache hits at any offset, and (at B=1, Sb=chunk)
    every chunk of a chunked prefill. The logits are *selected* at each
    lane's true last prompt position, which makes both pad tokens and
    dummy lanes invisible to the emitted token."""
    B, Sb = padded_ids.shape
    tr = params["params"]["transformer"]
    h, (k, v) = _forward_chunk(params, n_heads, (init_k, init_v),
                               padded_ids, starts)
    idx = jnp.clip(true_lens - 1 - starts, 0, Sb - 1)
    h_sel = jnp.take_along_axis(h, idx[:, None, None], axis=1)[:, 0]
    h_sel = _ln(h_sel, tr["ln_f"])
    logits = h_sel @ logits_table(tr["wte"], h_sel.dtype).T
    first = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return k, v, first


@partial(jax.jit, static_argnames=("n_heads",), donate_argnums=(1, 2, 3, 4))
def _decode_step_jit(params, pool_k, pool_v, tokens, positions, active, *,
                     n_heads):
    """One masked batched decode step over every pool lane.

    Each lane feeds its last token at its own position through the
    one-shot path's ``_step`` (vmapped as a B=1 lane). Inactive lanes
    compute garbage into their own (dead) lane and keep their token via
    the ``active`` mask; pool buffers, tokens and positions are donated —
    the step is an in-place update of device-resident serving state, and
    active lanes advance their position counter HERE, so steady-state
    decode needs no per-step host->device upload at all."""

    def lane(ck, cv, tok, pos):
        logits, (ck2, cv2) = _step(params, n_heads, (ck[:, None], cv[:, None]),
                                   tok[None], pos)
        return logits[0], ck2[:, 0], cv2[:, 0]

    logits, pool_k, pool_v = jax.vmap(
        lane, in_axes=(1, 1, 0, 0), out_axes=(0, 1, 1))(
        pool_k, pool_v, tokens, positions)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    tokens = jnp.where(active, nxt, tokens)
    positions = jnp.where(active, positions + 1, positions)
    return tokens, positions, pool_k, pool_v


@partial(jax.jit, static_argnames=("n_heads", "qmode"),
         donate_argnums=(1, 2, 5, 6))
def _decode_step_quant_jit(params, pool_k, pool_v, k_scale, v_scale, tokens,
                           positions, active, *, n_heads, qmode):
    """``_decode_step_jit`` over a QUANTIZED pool: each lane dequantizes
    its KV at use (int8 * per-head scale, or a bf16 cast), runs the same
    vmapped ``_step``, and re-stores against its FIXED install-time
    scales — idempotent on untouched positions (see ``requantize_kv``),
    so the step still only logically appends one token per lane. Scales
    are NOT donated: they are returned unchanged and the host keeps its
    reference. ``qmode`` is static — one program per storage mode, no
    traced branching (for "bf16" the scale operands are None)."""
    dtype = _cache_dtype(params)

    if qmode == "int8":
        def lane(ck, cv, sk, sv, tok, pos):
            logits, (ck2, cv2) = _step(
                params, n_heads,
                (dequantize_kv(ck, sk, dtype)[:, None],
                 dequantize_kv(cv, sv, dtype)[:, None]),
                tok[None], pos)
            return (logits[0], requantize_kv(ck2[:, 0], sk),
                    requantize_kv(cv2[:, 0], sv))

        logits, pool_k, pool_v = jax.vmap(
            lane, in_axes=(1, 1, 1, 1, 0, 0), out_axes=(0, 1, 1))(
            pool_k, pool_v, k_scale, v_scale, tokens, positions)
    else:
        def lane(ck, cv, tok, pos):
            logits, (ck2, cv2) = _step(
                params, n_heads,
                (ck.astype(dtype)[:, None], cv.astype(dtype)[:, None]),
                tok[None], pos)
            return (logits[0], ck2[:, 0].astype(jnp.bfloat16),
                    cv2[:, 0].astype(jnp.bfloat16))

        logits, pool_k, pool_v = jax.vmap(
            lane, in_axes=(1, 1, 0, 0), out_axes=(0, 1, 1))(
            pool_k, pool_v, tokens, positions)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    tokens = jnp.where(active, nxt, tokens)
    positions = jnp.where(active, positions + 1, positions)
    return tokens, positions, pool_k, pool_v


def _spec_core(params, n_heads, caches, history, tokens, positions, active,
               draft_noise, k):
    """Shared body of the speculative step programs: draft -> (optional
    noise) -> one-forward verify -> advance. Operates on COMPUTE-dtype
    caches; the quantized wrapper handles storage conversion."""
    S_max = history.shape[1]
    V = vocab_size(params["params"]["transformer"]["wte"])
    drafts = jax.vmap(partial(_ngram_draft, k=k))(history, positions)
    # fault-injection hook: draft_noise is normally all-zeros (the mod-V
    # add is then the identity, bitwise) — the corrupt_draft arm swaps in
    # nonzero values without changing shapes, so scrambling never
    # recompiles
    drafts = (drafts + draft_noise) % V
    oracle, accepted, caches = _speculative_verify(
        params, n_heads, caches, tokens, drafts, positions)
    # append all k+1 oracle tokens to the history at the lane's write
    # window; positions past the accepted point hold speculative
    # continuations the next step overwrites — the drafter's bigram scan
    # only trusts positions below its pending one, and emitted output
    # never comes from history, so they cannot corrupt anything
    idx = jnp.where(active[:, None],
                    positions[:, None] + 1 + jnp.arange(k + 1)[None, :],
                    S_max)                                   # OOB -> dropped
    history = jax.vmap(
        lambda h, i, t: h.at[i].set(t, mode="drop"))(history, idx, oracle)
    last = jnp.take_along_axis(oracle, accepted[:, None], axis=1)[:, 0]
    tokens = jnp.where(active, last, tokens)
    positions = jnp.where(active,
                          jnp.minimum(positions + accepted + 1, S_max - 1),
                          positions)
    return tokens, positions, caches, history, oracle, accepted


@partial(jax.jit, static_argnames=("n_heads", "k"),
         donate_argnums=(1, 2, 3, 4, 5))
def _spec_step_jit(params, pool_k, pool_v, history, tokens, positions,
                   active, draft_noise, *, n_heads, k):
    """One SPECULATIVE masked batched decode step over every pool lane.

    Per lane: draft ``k`` tokens (n-gram lookup over ``history``), feed
    pending-token + drafts through ONE k+1-wide causal forward against
    the pool (``_forward_chunk`` — the pool IS the chunk cache, no per
    lane re-batching), accept the longest draft prefix the greedy oracle
    confirms, and advance position by accepted+1. ``k`` and the lane
    count are static; drafts/acceptance/noise are traced operands, so
    acceptance variation and slot churn reuse one compiled program.
    Returns the full oracle [B, k+1] and per-lane accepted counts so the
    host emit loop can hand out between 1 and k+1 tokens per lane."""
    tokens, positions, (pool_k, pool_v), history, oracle, accepted = \
        _spec_core(params, n_heads, (pool_k, pool_v), history, tokens,
                   positions, active, draft_noise, k)
    return tokens, positions, pool_k, pool_v, history, oracle, accepted


@partial(jax.jit, static_argnames=("n_heads", "k", "qmode"),
         donate_argnums=(1, 2, 5, 6, 7))
def _spec_step_quant_jit(params, pool_k, pool_v, k_scale, v_scale, history,
                         tokens, positions, active, draft_noise, *,
                         n_heads, k, qmode):
    """Speculative step over a quantized pool: dequantize the pool at
    use, run the same draft/verify core in the compute dtype, then
    requantize against the FIXED per-(slot, head) install scales (or a
    bf16 cast). Untouched positions round-trip bitwise (idempotent
    requant), so only the k+1 freshly-written rows actually change."""
    dtype = _cache_dtype(params)
    if qmode == "int8":
        kf = dequantize_kv(pool_k, k_scale, dtype)
        vf = dequantize_kv(pool_v, v_scale, dtype)
    else:
        kf, vf = pool_k.astype(dtype), pool_v.astype(dtype)
    tokens, positions, (kf, vf), history, oracle, accepted = _spec_core(
        params, n_heads, (kf, vf), history, tokens, positions, active,
        draft_noise, k)
    if qmode == "int8":
        pool_k = requantize_kv(kf, k_scale)
        pool_v = requantize_kv(vf, v_scale)
    else:
        pool_k, pool_v = kf.astype(jnp.bfloat16), vf.astype(jnp.bfloat16)
    return tokens, positions, pool_k, pool_v, history, oracle, accepted


class _ChunkedPrefill:
    """In-flight chunked prefill: the request, its private cache pair
    (carried across engine steps between chunk calls), how far it has
    prefilled, and the pool slot reserved for it at start."""

    __slots__ = ("req", "k", "v", "pos", "reuse", "slot", "prefill_s")

    def __init__(self, req, k, v, pos, reuse, slot):
        self.req = req
        self.k = k
        self.v = v
        self.pos = pos
        self.reuse = reuse
        self.slot = slot
        self.prefill_s = 0.0


class ServingEngine:
    """Request queue + KV pool + the single compiled decode loop.

    Drive it synchronously (``step()`` / ``drain()`` — deterministic, what
    the tests do) or as a background thread (``start()`` / ``stop()``)
    with ``submit()`` from any thread."""

    def __init__(self, params, model_config, serving_config=None,
                 monitor=None, injector=None, sentinel_config=None,
                 telemetry_config=None, rank=None):
        cfg = serving_config or ServingConfig()
        self.params = params
        self.model_config = model_config
        self.config = cfg
        self.n_layers = model_config.num_hidden_layers
        self.n_heads = model_config.num_attention_heads
        self.head_dim = model_config.hidden_size // self.n_heads

        mpe = model_config.max_position_embeddings
        self.max_seq_len = cfg.max_seq_len or mpe
        if self.max_seq_len > mpe:
            raise ValueError(
                f"serving.max_seq_len={self.max_seq_len} exceeds "
                f"max_position_embeddings={mpe}")
        buckets = cfg.prompt_buckets or default_buckets(self.max_seq_len - 1)
        if buckets[-1] > self.max_seq_len - 1:
            raise ValueError(
                f"largest prompt bucket ({buckets[-1]}) must leave room for "
                f"one generated token (max_seq_len={self.max_seq_len})")
        if cfg.prefill_chunk_tokens < 0:
            raise ValueError(
                f"serving.prefill_chunk_tokens must be >= 0 "
                f"(0 disables chunked prefill), got {cfg.prefill_chunk_tokens}")
        if cfg.prefix_cache_mb < 0:
            raise ValueError(
                f"serving.prefix_cache_mb must be >= 0 "
                f"(0 disables the prefix cache), got {cfg.prefix_cache_mb}")
        if (isinstance(cfg.speculative_k, bool)
                or not isinstance(cfg.speculative_k, int)
                or cfg.speculative_k < 0):
            raise ValueError(
                f"serving.speculative_k must be an int >= 0 "
                f"(0 disables speculative decoding), "
                f"got {cfg.speculative_k!r}")
        if cfg.speculative_k >= self.max_seq_len:
            raise ValueError(
                f"serving.speculative_k={cfg.speculative_k} must be "
                f"smaller than max_seq_len={self.max_seq_len}")
        if cfg.kv_cache_dtype not in KV_CACHE_DTYPES:
            raise ValueError(
                f"serving.kv_cache_dtype must be one of {KV_CACHE_DTYPES}, "
                f"got {cfg.kv_cache_dtype!r}")

        dtype = _cache_dtype(params)
        self.pool = KVCachePool(self.n_layers, cfg.max_slots, self.n_heads,
                                self.max_seq_len, self.head_dim, dtype=dtype,
                                kv_cache_dtype=cfg.kv_cache_dtype)
        # _qmode: storage<->compute conversion the decode programs need.
        # "fp32" stores the compute dtype directly, and "bf16" on a bf16
        # checkpoint is ALSO storage==compute — both take the plain
        # (bitwise) programs; only a real narrowing pays the quant path.
        if cfg.kv_cache_dtype == "int8":
            self._qmode = "int8"
        elif jnp.dtype(self.pool.k.dtype) != jnp.dtype(dtype):
            self._qmode = "bf16"
        else:
            self._qmode = None
        self._spec_k = int(cfg.speculative_k)
        self.scheduler = ContinuousBatchingScheduler(
            max_queue=cfg.max_queue, buckets=buckets,
            default_max_new_tokens=cfg.default_max_new_tokens,
            request_timeout_s=cfg.request_timeout_s)
        self.metrics = ServingMetrics(monitor)
        self.metrics.record_kv_pool_bytes(self.pool.nbytes())
        self.prefix_cache = (
            PrefixKVCache(max(1, int(cfg.prefix_cache_mb * 2 ** 20)))
            if cfg.prefix_cache_mb > 0 else None)
        if injector is None and cfg.fault_injection:
            injector = ServingFaultInjector(cfg.fault_injection)
        self.injector = injector

        self._active = {}                                   # slot -> Request
        self._lane_tokens = np.zeros(cfg.max_slots, np.int32)
        self._lane_active = np.zeros(cfg.max_slots, bool)
        # device-resident decode operands: uploaded ONLY on lane churn
        # (_lane_dirty), advanced in-jit otherwise — steady-state decode
        # performs exactly one explicit transfer per step (the EOS read)
        self._dev_tokens = None
        self._dev_positions = None
        self._dev_active = None
        self._lane_dirty = True
        # speculative state: per-lane token-by-position history feeding
        # the n-gram drafter (host mirror for churn re-upload, device
        # buffer advanced in-jit between churns) and the corrupt_draft
        # noise operand (all-zeros = bitwise no-op)
        self._lane_history = (
            np.zeros((cfg.max_slots, self.max_seq_len), np.int32)
            if self._spec_k > 0 else None)
        self._dev_history = None
        self._dev_noise = None
        self._noise_armed = False
        if sentinel_config is not None and sentinel_config.enabled:
            budget = sentinel_config.compile_budget
            if self._spec_k > 0:
                decode_prog = (_spec_step_quant_jit if self._qmode
                               else _spec_step_jit)
            else:
                decode_prog = (_decode_step_quant_jit if self._qmode
                               else _decode_step_jit)
            self.decode_sentinel = CompileSentinel(
                decode_prog, budget, name="serving decode step")
            self.prefill_sentinel = CompileSentinel(
                _prefill_batch_jit, budget, name="serving batched prefill")
            self._transfer_guard = bool(sentinel_config.transfer_guard)
        else:
            self.decode_sentinel = None
            self.prefill_sentinel = None
            self._transfer_guard = False
        # batched prefill always runs at the pool width: the batch dim is
        # STATIC, so any admission-group size shares one program per bucket
        self._prefill_batch = cfg.max_slots
        self._chunking = None               # at most one chunked prefill
        self._step_count = 0
        self._loop_thread = None
        self._stop = threading.Event()

        # telemetry: an explicit block arms the process-global tracer and
        # registry; an absent block leaves them untouched. Hot-path guard
        # is one attribute read (self._tracer.enabled). rank/role become
        # the trace's process identity (the fleet collector's merge key);
        # rank=None falls back to the launcher-exported RANK env var.
        telemetry.configure_from_config(telemetry_config, rank=rank,
                                        role="serve")
        self._tracer = telemetry.get_tracer()
        self._trace_file = None
        self.telemetry_server = None
        self.slo = None
        if telemetry_config is not None and telemetry_config.enabled:
            self._trace_file = telemetry_config.trace_file
            self.metrics.export_to(telemetry.get_registry())
            # explicit http_port wins; a supervised worker with a null
            # port inherits DSTPU_TELEMETRY_PORT so the fleet collector
            # can scrape it without per-worker config edits
            http_port = telemetry.resolve_http_port(telemetry_config)
            if http_port is not None:
                self.telemetry_server = self._build_telemetry_server(
                    http_port)
            self.slo = telemetry.SloEngine.from_config(
                telemetry_config, tracer=self._tracer,
                registry=telemetry.get_registry())
            if self.slo is not None and self.telemetry_server is not None:
                self.slo.attach(self.telemetry_server)

    def _build_telemetry_server(self, port):
        srv = telemetry.TelemetryServer(
            registry=telemetry.get_registry(), tracer=self._tracer, port=port)
        srv.add_snapshot_provider("serving", self.metrics.snapshot)
        srv.add_snapshot_provider("kv_pool", self.occupancy)
        srv.add_snapshot_provider("prefix_cache", self.prefix_stats)
        srv.add_health_provider("serving_loop", self._loop_health)
        return srv.start()

    def _loop_health(self):
        """Healthy unless a background loop was started and then died
        (synchronous step()/drain() driving is always healthy)."""
        t = self._loop_thread
        return {"healthy": t is None or t.is_alive(),
                "background_loop": t is not None,
                "steps": self._step_count,
                "active_requests": len(self._active),
                "queue_depth": self.scheduler.queue_depth()}

    @classmethod
    def from_config(cls, params, model_config, ds_config, rank=0,
                    injector=None):
        """Build from a ds_config (dict or DeepSpeedConfig): the validated
        ``serving`` block plus the shared monitor construction path."""
        from deepspeed_tpu.monitor import monitor_from_config
        from deepspeed_tpu.runtime.config import DeepSpeedConfig

        if isinstance(ds_config, dict):
            ds_config = DeepSpeedConfig(ds_config, world_size=1)
        return cls(params, model_config,
                   serving_config=ds_config.serving_config,
                   monitor=monitor_from_config(ds_config, rank),
                   injector=injector,
                   sentinel_config=ds_config.sentinel_config,
                   telemetry_config=ds_config.telemetry_config,
                   rank=rank)

    # -- request intake -------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens=None, eos_token_id=None,
               timeout_s=None, stream_cb=None):
        """Queue one request; returns its ``ServingFuture``.

        ``prompt_ids`` is a 1-D token sequence. Raises ``QueueFullError``
        when the admission queue is at capacity (backpressure) and
        ``ValueError`` for requests that can never fit. ``stream_cb``
        (optional) is called as ``stream_cb(request_id, token)`` for every
        generated token, including the first."""
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if len(prompt) < 1:
            raise ValueError("prompt must contain at least one token")
        if max_new_tokens is None:
            max_new_tokens = self.config.default_max_new_tokens
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        bucket_for(len(prompt), self.scheduler.buckets)  # raises if too long
        total = len(prompt) + int(max_new_tokens)
        if total > self.max_seq_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"= {total} exceeds serving max_seq_len={self.max_seq_len}")
        if eos_token_id is not None and not (
                0 <= int(eos_token_id) < self.model_config.vocab_size):
            raise ValueError(
                f"eos_token_id={eos_token_id} outside vocab "
                f"[0, {self.model_config.vocab_size})")
        req = self.scheduler.submit(
            prompt, max_new_tokens=int(max_new_tokens),
            eos_token_id=None if eos_token_id is None else int(eos_token_id),
            timeout_s=timeout_s, stream_cb=stream_cb)
        return req.future

    # -- the serving loop ----------------------------------------------
    def step(self):
        """One scheduler iteration: expire, advance any chunked prefill,
        admit (batched per bucket), one batched decode step, retire.
        Returns an activity dict (all zeros = idle)."""
        now = time.monotonic()
        stats = {"admitted": 0, "decoded": 0, "retired": 0,
                 "prefill_chunks": 0}

        for req in self.scheduler.pop_expired(now):
            self._finish_timeout(req, phase="queued")
            stats["retired"] += 1

        # one chunk per step: a long prompt makes progress without ever
        # stalling the in-flight lanes' inter-token latency
        if self._chunking is not None:
            self._advance_chunk(stats)

        self._admit_from_queue(stats)

        if self.injector is not None:
            self.injector.maybe_evict_prefix(self._step_count,
                                             self.prefix_cache)
        if self._active:
            if self.injector is not None:
                self.injector.maybe_slow_decode(self._step_count)
            # span args (request ids) are built ONLY when tracing is armed:
            # disabled-mode cost is this one attribute read. The dict is
            # kept so the spec path can fill in `accepted` post-step (the
            # tracer renders args lazily, at write time).
            span_args = None
            if self._tracer.enabled:
                span_args = {
                    "request_ids": [r.id for r in self._active.values()],
                    "active": len(self._active), "accepted": 0}
                dspan = self._tracer.span("serving/decode_step",
                                          cat="serving", args=span_args)
            else:
                dspan = telemetry.NULL_SPAN
            dspan.__enter__()
            t0 = time.monotonic()
            if self._lane_dirty:
                self._upload_lane_state()
            guard = transfer_free() if self._transfer_guard else nullcontext()
            if self._spec_k > 0:
                self._maybe_update_noise()
                with guard:
                    (self._dev_tokens, self._dev_positions, self.pool.k,
                     self.pool.v, self._dev_history, oracle_dev,
                     accepted_dev) = self._call_spec_step()
                if self.decode_sentinel is not None:
                    self.decode_sentinel.check()
                # the step's single deliberate sync: the emit loop needs
                # the oracle tokens and per-lane acceptance counts
                oracle, accepted = jax.device_get(  # jaxlint: disable=JL002(one explicit host read per step)
                    (oracle_dev, accepted_dev))
                step_s = time.monotonic() - t0
                oracle = oracle.tolist()        # host numpy -> python ints
                accepted = accepted.tolist()
                acc_total = sum(accepted[s] for s in self._active)
                if span_args is not None:
                    span_args["accepted"] = acc_total
                dspan.__exit__(None, None, None)
                now = time.monotonic()
                n_active = len(self._active)
                decoded_before = stats["decoded"]
                for slot in list(self._active):
                    req = self._active[slot]
                    acc = accepted[slot]
                    # mirror the device lane state: the pending token is
                    # now the oracle's post-acceptance token
                    self._lane_tokens[slot] = oracle[slot][acc]
                    base = self.pool.positions[slot]    # host-side counter
                    for j in range(acc + 1):
                        tok = oracle[slot][j]
                        self.pool.advance(slot)
                        if base + 1 + j < self.max_seq_len:
                            self._lane_history[slot, base + 1 + j] = tok
                        self._emit(req, tok)
                        stats["decoded"] += 1
                        if self._maybe_retire(req, tok, now):
                            # EOS/length/deadline truncates the step's
                            # remaining oracle tokens — exactly where a
                            # non-speculative server would have stopped
                            stats["retired"] += 1
                            break
                self.metrics.record_step(
                    queue_depth=self.scheduler.queue_depth(),
                    active_slots=n_active, max_slots=self.pool.max_slots,
                    tokens_this_step=stats["decoded"] - decoded_before,
                    step_s=step_s, accepted_tokens=acc_total,
                    proposed_tokens=self._spec_k * n_active)
            else:
                with guard:
                    if self._qmode is not None:
                        (self._dev_tokens, self._dev_positions, self.pool.k,
                         self.pool.v) = _decode_step_quant_jit(
                            self.params, self.pool.k, self.pool.v,
                            self.pool.k_scale, self.pool.v_scale,
                            self._dev_tokens, self._dev_positions,
                            self._dev_active, n_heads=self.n_heads,
                            qmode=self._qmode)
                    else:
                        (self._dev_tokens, self._dev_positions,
                         self.pool.k, self.pool.v) = _decode_step_jit(
                            self.params, self.pool.k, self.pool.v,
                            self._dev_tokens, self._dev_positions,
                            self._dev_active, n_heads=self.n_heads)
                if self.decode_sentinel is not None:
                    self.decode_sentinel.check()
                # the step's single deliberate sync: EOS checks need the
                # tokens
                host_tokens = jax.device_get(self._dev_tokens)  # jaxlint: disable=JL002(one explicit host read per step)
                step_s = time.monotonic() - t0
                dspan.__exit__(None, None, None)
                self._lane_tokens = host_tokens.copy()
                toks = host_tokens.tolist()
                now = time.monotonic()
                n_active = len(self._active)
                for slot in list(self._active):
                    req = self._active[slot]
                    self.pool.advance(slot)
                    self._emit(req, toks[slot])
                    stats["decoded"] += 1
                    stats["retired"] += self._maybe_retire(req, toks[slot],
                                                           now)
                self.metrics.record_step(
                    queue_depth=self.scheduler.queue_depth(),
                    active_slots=n_active, max_slots=self.pool.max_slots,
                    tokens_this_step=n_active, step_s=step_s)
        self._step_count += 1
        if self.slo is not None:
            # host-only snapshot + pushed gauges; under policy="fail" a
            # firing rule raises SloViolationError out of step()
            self.slo.evaluate(self._slo_values())
        return stats

    def _slo_values(self):
        """SLO inputs: the live serving snapshot under ``Serving/*`` plus
        pushed registry metrics. Pull gauges are skipped — the snapshot is
        already here, and re-polling every callback each step would double
        the work for no fresher data."""
        vals = {k: v
                for k, v in telemetry.get_registry().as_dict(pulled=False).items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)}
        for k, v in self.metrics.snapshot().items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                vals[f"Serving/{k}"] = v
        return vals

    def _upload_lane_state(self):
        """Lane churn: ONE explicit upload of the lane vectors (and the
        drafter history when speculation is armed); between churn events
        they live on device and never move."""
        pos = np.ascontiguousarray(self.pool.positions, dtype=np.int32)
        if self._spec_k > 0:
            (self._dev_tokens, self._dev_positions, self._dev_active,
             self._dev_history) = jax.device_put(
                (self._lane_tokens, pos, self._lane_active,
                 self._lane_history))
            if self._dev_noise is None:
                self._dev_noise = jax.device_put(
                    np.zeros((self.pool.max_slots, self._spec_k), np.int32))
        else:
            self._dev_tokens, self._dev_positions, self._dev_active = \
                jax.device_put((self._lane_tokens, pos, self._lane_active))
        self._lane_dirty = False

    def _call_spec_step(self):
        """Dispatch the speculative step program for the pool's storage
        mode. Both return (tokens, positions, k, v, history, oracle,
        accepted)."""
        if self._qmode is not None:
            return _spec_step_quant_jit(
                self.params, self.pool.k, self.pool.v,
                self.pool.k_scale, self.pool.v_scale, self._dev_history,
                self._dev_tokens, self._dev_positions, self._dev_active,
                self._dev_noise, n_heads=self.n_heads, k=self._spec_k,
                qmode=self._qmode)
        return _spec_step_jit(  # jaxlint: disable=JL005(exclusive branch: the quant dispatch above never ran)
            self.params, self.pool.k, self.pool.v, self._dev_history,
            self._dev_tokens, self._dev_positions, self._dev_active,
            self._dev_noise, n_heads=self.n_heads, k=self._spec_k)

    def _maybe_update_noise(self):
        """Swap the device-resident draft-noise operand when the
        corrupt_draft fault arm fires (and restore zeros after). The
        operand always exists with the same shape, so firing the fault
        can never recompile the step."""
        if self.injector is None:
            return
        noise = self.injector.corrupt_draft_noise(
            self._step_count, self._spec_k, self.model_config.vocab_size)
        if noise is not None:
            self._dev_noise = jax.device_put(np.ascontiguousarray(
                np.broadcast_to(np.asarray(noise, np.int32),
                                (self.pool.max_slots, self._spec_k))))
            self._noise_armed = True
        elif self._noise_armed:
            self._dev_noise = jax.device_put(
                np.zeros((self.pool.max_slots, self._spec_k), np.int32))
            self._noise_armed = False

    def drain(self, max_steps=None):
        """Step until no request is queued, prefilling, or in flight.
        ``max_steps`` bounds the loop (a deadline-less stuck request
        would otherwise spin forever under fault injection)."""
        steps = 0
        while (self._active or self._chunking is not None
               or self.scheduler.queue_depth() > 0):
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return steps

    # -- background mode ------------------------------------------------
    def start(self, idle_sleep_s=0.001):
        """Run the serving loop on a daemon thread until ``stop()``."""
        if self._loop_thread is not None:
            raise RuntimeError("serving loop already running")
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                busy = self.step()
                if not any(busy.values()) and not self._active:
                    time.sleep(idle_sleep_s)

        self._loop_thread = threading.Thread(
            target=loop, name="serving-loop", daemon=True)
        self._loop_thread.start()

    def stop(self, timeout_s=5.0):
        if self._loop_thread is None:
            return
        self._stop.set()
        self._loop_thread.join(timeout_s)
        self._loop_thread = None

    def close(self):
        self.stop()
        self.metrics.close()
        if self.telemetry_server is not None:
            self.telemetry_server.stop()
            self.telemetry_server = None
        if self._trace_file:
            self._tracer.write(self._trace_file)

    # -- admission ------------------------------------------------------
    def _admit_from_queue(self, stats):
        """Join-at-free-slot admission, batched per bucket: pop the FIFO
        head, gather every queued request sharing its (prefix-adjusted)
        bucket up to the free-slot count, and prefill them as ONE call.
        Long prompts divert to the chunked path (one at a time)."""
        if self._tracer.enabled and self.scheduler.queue_depth() > 0:
            with self._tracer.span(
                    "serving/admission", cat="serving",
                    args={"queue_depth": self.scheduler.queue_depth()}):
                self._admit_from_queue_now(stats)
        else:
            self._admit_from_queue_now(stats)

    def _admit_from_queue_now(self, stats):
        while self.pool.free_slots > 0:
            head = self.scheduler.pop_next()
            if head is None:
                return
            if self._needs_chunking(head):
                if self._chunking is None:
                    self._start_chunked(head)
                    stats["admitted"] += 1
                    continue
                self.scheduler.requeue_front(head)   # chunk lane is busy
                return
            bucket = bucket_for(self._suffix_len(head), self.scheduler.buckets)
            group = [head]
            room = min(self.pool.free_slots - 1, self._prefill_batch - 1)
            if room > 0:
                group += self.scheduler.pop_matching(
                    lambda r: (not self._needs_chunking(r)
                               and bucket_for(self._suffix_len(r),
                                              self.scheduler.buckets)
                               == bucket),
                    room)
            stats["admitted"] += len(group)
            stats["retired"] += self._admit_batch(group, bucket)

    def _admit_batch(self, group, bucket):
        """Prefill ``group`` (same bucket) as one [MaxSlots, bucket] call
        and install each lane into its slot. Returns how many requests
        retired on their very first token."""
        pspan = (self._tracer.span(
                     "serving/prefill_batch", cat="serving",
                     args={"request_ids": [r.id for r in group],
                           "bucket": bucket})
                 if self._tracer.enabled else telemetry.NULL_SPAN)
        pspan.__enter__()
        B, total = self._prefill_batch, self.max_seq_len
        ids = np.zeros((B, bucket), np.int32)
        starts = np.zeros(B, np.int32)
        lens = np.ones(B, np.int32)        # dummy lanes: 1-token no-ops
        plan = []
        any_hit = False
        for i, req in enumerate(group):
            reuse, entry = self._acquire_prefix(req)
            suffix = req.prompt[reuse:]
            ids[i, :len(suffix)] = suffix
            starts[i] = reuse
            lens[i] = len(req.prompt)
            plan.append((req, reuse, entry))
            any_hit = any_hit or reuse > 0
        # prefill runs in the COMPUTE dtype regardless of pool storage:
        # the quantize happens once, at lane install
        shape = (self.n_layers, B, self.n_heads, total, self.head_dim)
        cdtype = self.pool.compute_dtype
        if any_hit:
            # seed hit lanes from host-resident prefix KV; one transfer
            init_k = np.zeros(shape, cdtype)
            init_v = np.zeros(shape, cdtype)
            for i, (req, reuse, entry) in enumerate(plan):
                if reuse > 0:
                    ek, ev = self._entry_prefix_kv(entry, reuse)
                    init_k[:, i, :, :reuse] = ek
                    init_v[:, i, :, :reuse] = ev
            init_k, init_v = jnp.asarray(init_k), jnp.asarray(init_v)
        else:
            init_k = jnp.zeros(shape, cdtype)
            init_v = jnp.zeros(shape, cdtype)

        t0 = time.monotonic()
        k, v, first = _prefill_batch_jit(
            self.params, init_k, init_v, jnp.asarray(ids),
            jnp.asarray(starts), jnp.asarray(lens), n_heads=self.n_heads)
        if self.prefill_sentinel is not None:
            self.prefill_sentinel.check()
        first_host = np.asarray(first)             # sync: TTFT endpoint
        prefill_s = time.monotonic() - t0
        self.metrics.record_prefill(
            tokens=sum(len(r.prompt) - re for r, re, _ in plan),
            reused_tokens=sum(re for _, re, _ in plan),
            requests=len(group), prefill_s=prefill_s)

        now = time.monotonic()
        retired = 0
        for i, (req, reuse, entry) in enumerate(plan):
            self._maybe_insert_prefix(req, reuse, k, v, lane=i)
            slot = self.pool.allocate()
            self.pool.install_lane(k, v, lane=i, slot=slot,
                                   position=len(req.prompt))
            req.prefix_entry = entry
            req.first_token_time = now
            self.metrics.record_first_token(now - req.submit_time)
            self._activate(req, slot, int(first_host[i]))
            retired += self._maybe_retire(req, int(first_host[i]), now)
        # settle the queued lane installs here so they are accounted to
        # admission, not silently absorbed into the next decode step's
        # measured latency
        self.pool.k.block_until_ready()
        pspan.__exit__(None, None, None)
        return retired

    # -- chunked prefill ------------------------------------------------
    def _needs_chunking(self, req):
        chunk = self.config.prefill_chunk_tokens
        return chunk > 0 and self._suffix_len(req) > chunk

    def _start_chunked(self, req):
        """Reserve a slot and a private cache for ``req`` and let
        ``_advance_chunk`` feed it one chunk per engine step."""
        reuse, entry = self._acquire_prefix(req)
        req.prefix_entry = entry
        slot = self.pool.allocate()       # reserved: completion can't stall
        shape = (self.n_layers, 1, self.n_heads, self.max_seq_len,
                 self.head_dim)
        cdtype = self.pool.compute_dtype
        if reuse > 0:
            k0 = np.zeros(shape, cdtype)
            v0 = np.zeros(shape, cdtype)
            ek, ev = self._entry_prefix_kv(entry, reuse)
            k0[:, 0, :, :reuse] = ek
            v0[:, 0, :, :reuse] = ev
            k0, v0 = jnp.asarray(k0), jnp.asarray(v0)
        else:
            k0 = jnp.zeros(shape, cdtype)
            v0 = jnp.zeros(shape, cdtype)
        self._chunking = _ChunkedPrefill(req, k0, v0, pos=reuse, reuse=reuse,
                                         slot=slot)

    def _advance_chunk(self, stats):
        """Run the next chunk of the in-flight chunked prefill (same
        compiled program as batched prefill, at B=1/Sb=chunk); install
        and activate on the final chunk. Mid chunks never block the host
        — only the final chunk syncs, for its first token."""
        st = self._chunking
        req = st.req
        now = time.monotonic()
        if req.deadline_exceeded(now):
            req.slot = st.slot             # hand the reserved slot back
            self._finish_timeout(req, phase="prefill")
            self._chunking = None
            stats["retired"] += 1
            return
        chunk_len = self.config.prefill_chunk_tokens
        chunk = req.prompt[st.pos:st.pos + chunk_len]
        ids = np.zeros((1, chunk_len), np.int32)
        ids[0, :len(chunk)] = chunk
        cspan = (self._tracer.span("serving/prefill_chunk", cat="serving",
                                   args={"request_id": req.id, "pos": st.pos,
                                         "chunk": len(chunk)})
                 if self._tracer.enabled else telemetry.NULL_SPAN)
        t0 = time.monotonic()
        with cspan:
            st.k, st.v, first = _prefill_batch_jit(
                self.params, st.k, st.v, jnp.asarray(ids),
                jnp.asarray([st.pos], jnp.int32),
                jnp.asarray([len(req.prompt)], jnp.int32),
                n_heads=self.n_heads)
            if self.prefill_sentinel is not None:
                self.prefill_sentinel.check()
        st.pos += len(chunk)
        stats["prefill_chunks"] += 1
        if st.pos < len(req.prompt):
            st.prefill_s += time.monotonic() - t0
            return
        first_tok = int(np.asarray(first)[0])      # sync: TTFT endpoint
        st.prefill_s += time.monotonic() - t0
        now = time.monotonic()
        self.metrics.record_prefill(
            tokens=len(req.prompt) - st.reuse, reused_tokens=st.reuse,
            requests=1, prefill_s=st.prefill_s)
        self._maybe_insert_prefix(req, st.reuse, st.k, st.v, lane=0)
        self.pool.install(st.k, st.v, st.slot, position=len(req.prompt))
        req.first_token_time = now
        self.metrics.record_first_token(now - req.submit_time)
        self._activate(req, st.slot, first_tok)
        stats["retired"] += self._maybe_retire(req, first_tok, now)
        self._chunking = None

    # -- prefix cache ---------------------------------------------------
    def _suffix_len(self, req):
        """Tokens a prefill would actually compute for ``req`` after
        prefix-cache reuse (always >= 1: the last prompt position is
        recomputed to produce the first token's logits)."""
        if self.prefix_cache is None:
            return len(req.prompt)
        length, _ = self.prefix_cache.match(req.prompt)
        return len(req.prompt) - min(length, len(req.prompt) - 1)

    def _acquire_prefix(self, req):
        """Counted, ref-taking lookup at admission time. Returns
        (reused_tokens, entry-or-None); the ref is released at the
        request's retirement (any path)."""
        if self.prefix_cache is None:
            return 0, None
        length, entry = self.prefix_cache.acquire(req.prompt)
        reuse = min(length, len(req.prompt) - 1)
        if entry is not None and reuse <= 0:
            self.prefix_cache.release(entry)
            entry, reuse = None, 0
        self.metrics.record_prefix_lookup(hit=reuse > 0)
        return reuse, entry

    def _maybe_insert_prefix(self, req, reuse, k, v, lane):
        """Store the freshly-prefilled prompt's KV for future requests
        (skipped when an existing entry already covers the whole prompt
        — nothing new to add). In int8 pool mode entries are stored
        QUANTIZED (per-(layer, head) scales over the cached positions):
        the trie's byte budget buys ~4x the prefix positions, same
        at-use-dequant contract as the pool itself."""
        if self.prefix_cache is None:
            return
        n = len(req.prompt)
        if reuse >= n - 1:
            return
        pk = np.asarray(k[:, lane, :, :n])
        pv = np.asarray(v[:, lane, :, :n])
        if self.pool.kv_cache_dtype == "int8":
            pk, k_scale = quantize_kv_np(pk)
            pv, v_scale = quantize_kv_np(pv)
            self.prefix_cache.insert(req.prompt, pk, pv,
                                     k_scale=k_scale, v_scale=v_scale)
            return
        self.prefix_cache.insert(req.prompt, pk, pv)

    def _entry_prefix_kv(self, entry, reuse):
        """A prefix entry's first ``reuse`` positions in the pool's
        COMPUTE dtype (int8-mode entries dequantize here, at seed
        time — never inside the prefill program)."""
        ek = entry.k[:, :, :reuse]
        ev = entry.v[:, :, :reuse]
        if entry.k_scale is not None:
            dt = np.dtype(self.pool.compute_dtype)
            return (dequantize_kv_np(ek, entry.k_scale, dt),
                    dequantize_kv_np(ev, entry.v_scale, dt))
        return ek, ev

    # -- internals ------------------------------------------------------
    def _activate(self, req, slot, first_tok):
        req.slot = slot
        self._active[slot] = req
        self._lane_tokens[slot] = first_tok
        self._lane_active[slot] = True
        if self._lane_history is not None:
            # seed the drafter: prompt tokens by position, then the
            # PENDING first generated token at position len(prompt)
            row = self._lane_history[slot]
            row[:] = 0
            row[:len(req.prompt)] = req.prompt
            row[len(req.prompt)] = first_tok
        self._lane_dirty = True
        self._emit(req, first_tok)

    def _emit(self, req, token):
        req.emitted += 1
        req.future._append(token)
        if req.stream_cb is not None:
            try:
                req.stream_cb(req.id, token)
            except Exception:  # a broken callback must not kill the loop
                pass

    def _maybe_retire(self, req, token, now):
        stuck = (self.injector is not None
                 and self.injector.request_is_stuck(req.id))
        if req.deadline_exceeded(now):
            self._finish_timeout(req, phase="decoding")
            return 1
        if self.scheduler.should_retire(req, token, stuck=stuck) is not None:
            self._release_slot(req)
            req.future._finish()
            self.scheduler.completed += 1
            self.metrics.record_completion()
            if self._tracer.enabled:
                self._tracer.instant("serving/retire", cat="serving",
                                     args={"request_id": req.id,
                                           "tokens": req.emitted})
            return 1
        return 0

    def _finish_timeout(self, req, phase):
        self._release_slot(req)
        if self._tracer.enabled:
            self._tracer.instant("serving/retire_timeout", cat="serving",
                                 args={"request_id": req.id, "phase": phase,
                                       "tokens": req.emitted})
        req.future._finish(RequestTimeoutError(
            req.id, req.timeout_s, phase, tokens_done=req.emitted))
        self.scheduler.timed_out += 1
        self.metrics.record_timeout()

    def _release_slot(self, req):
        if req.slot is not None:
            self._lane_active[req.slot] = False
            self._lane_dirty = True
            self._active.pop(req.slot, None)
            self.pool.free(req.slot)
            req.slot = None
        if req.prefix_entry is not None and self.prefix_cache is not None:
            self.prefix_cache.release(req.prefix_entry)
            req.prefix_entry = None

    # -- introspection ---------------------------------------------------
    def occupancy(self):
        return self.pool.occupancy()

    def prefix_stats(self):
        """Prefix-cache counters, or None when the cache is disabled."""
        return None if self.prefix_cache is None else self.prefix_cache.stats()
