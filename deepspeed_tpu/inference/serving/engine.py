"""Continuous-batching serving engine over the KV-cache decode path.

The decode loop is ONE jitted program for the life of the server: a
masked batched step over the pool's ``MaxSlots`` lanes, each lane
running the SAME per-token ``_step`` the one-shot ``generate()`` path
uses (vmapped with a per-lane position counter). ``MaxSlots`` is static,
the lane-active mask and positions are traced operands — so requests
joining, retiring, or swapping slots NEVER recompile.

Prefill is a SINGLE-PASS batched causal forward (``_forward_chunk`` —
the same core ``generate()``/``beam_search()`` prefill with): the
scheduler groups queued requests that share a prompt bucket and
prefills them as one ``[MaxSlots, Sb]`` call straight into their pool
slots, so a prompt of length S costs one whole-sequence forward instead
of S sequential batch-1 matmuls. The batch dimension is padded to the
static ``MaxSlots`` and per-lane starts/true-lengths are traced, so the
compile count stays bounded by the bucket ladder — never by how many
requests happen to arrive together. Long prompts can additionally be
split into fixed-size chunks (``serving.prefill_chunk_tokens``)
interleaved with decode steps, and previously-served prompt prefixes
can be seeded from the prefix KV cache (``serving.prefix_cache_mb``,
prefix_cache.py) instead of recomputed.

Correctness oracle (tests/unit/test_serving.py): continuous-batched
greedy output is BITWISE equal to per-request ``generate()`` output for
any arrival order. Why it holds:

- prefill pads the prompt up to its bucket but *selects* the logits at
  the true last prompt position; a valid query position only ever
  attends true prompt tokens (causal mask), so the selected logits
  match the unpadded forward;
- pad/stale cache beyond a lane's position is either overwritten before
  it is reachable (decode writes position p before attending to it) or
  hidden by the causal mask, whose -1e30 scores underflow to exactly 0
  probability — extra masked cache length is numerically invisible;
- lanes are vmapped, hence computed independently: a neighbor admitting,
  retiring, or holding garbage cannot perturb another lane's values
  (the batch-independence property test_generation.py already pins);
- a prefix-cache hit seeds bits a previous identical computation
  produced, so seeding and recomputing are the same bits.

Greedy only: serving argmax-decodes (temperature-0), the mode with a
bitwise oracle. Sampling needs per-request RNG streams and is future
work.

Speculative decoding (``serving.speculative_k > 0``): each step drafts
``k`` tokens per lane with a free n-gram drafter over the lane's own
history (no second model), verifies all k+1 positions in ONE batched
causal forward (the same ``_forward_chunk`` core prefill uses), and
emits the longest draft prefix the greedy oracle confirms — plus the
oracle's own next token, so every step yields between 1 and k+1 tokens
per lane. Emitted tokens always COME FROM the oracle, so draft quality
affects only throughput, never output: the emitted sequence is
output-identical to ``speculative_k=0`` (and the k=0 path itself stays
bitwise — it runs the exact same program as before). Rejected drafts
need no KV rollback: their stale cache rows sit inside the next step's
k+1-wide write window and are overwritten before any mask can expose
them, so "rollback" is just advancing the position counter by
accepted+1. ``k`` and ``MaxSlots`` are static; acceptance counts,
drafts, and noise are traced — variable acceptance never recompiles and
steady state still runs under ``transfer_free()``.

KV quantization (``serving.kv_cache_dtype``): "fp32" stores the model's
compute dtype (bitwise-transparent default); "bf16" and "int8" store
the pool narrower and dequantize at use inside the decode/verify reads
(int8 carries per-(slot, head) symmetric scales, fixed at install — see
kv_pool.py). Quantized modes trade a threshold-based parity oracle
(token-match rate, allclose attention outputs) for 2-4x more KV slots
per byte.

Paged KV pool (this file + kv_pool.py): KV lives in fixed-size pages
under one shared token budget; lanes hold page TABLES, not contiguous
stripes. The jitted programs gather a lane's pages back into the exact
contiguous layout (bitwise — gather/scatter move bits, never values)
and scatter back only freshly-written rows, so short chat requests and
16k-token documents share the pool without ``MaxSlots × S_max`` blowup.
Page tables ride the same churn-only upload as the lane masks.

Attention backends (``serving.attention_impl``): per-prompt-bucket
selection of dense | flash | sparse_xla, threaded through prefill,
decode, and the speculative verify. Dense remains the bitwise parity
oracle. Flash is math-equal dense (online softmax) and shares the
dense decode program — its lanes are "full-gather class". sparse_xla
lanes decode through a windowed program that touches only
O(page_tokens) KV per token (window + anchor pages) — the long-context
speedup — and hold the bitwise oracle against sparse ``generate()``.
Requests are grouped at admission by (bucket, backend); the lane
classes run as (at most) one jitted call per armed class per step
sharing the token/position/pool operands, still with ONE host read per
step.

Kernel-tier backends (``pallas_decode`` / ``pallas_sparse``): the same
dispatch seam routed through ``deepspeed_tpu/kernels`` — hand-fused
Pallas attention resolved ONCE at engine construction through the
op_builder-style ``KernelRegistry`` (``serving.attention_kernel`` can
force "pallas"/"xla"; None takes the probe result, degrading to the
composed-XLA fallback with an edge-triggered ``jax/kernel_fallback``
instant instead of crashing). ``pallas_decode`` lanes decode through
``_decode_step_kernel_jit``: the fused paged kernel consumes the pool's
STORAGE-dtype pages directly through the lane page tables (int8 scales
fused into the matmul — no dequantized gather copy), so the paged
``pool[tables]`` reassembly disappears into the kernel's DMA schedule.
``pallas_sparse`` lanes run the windowed program with the band math
swapped for the fused band kernel. The resolved (impl, interpret) pair
is threaded into every jitted program as STATIC arguments — selection
is part of the jit cache key, and each backend holds the same
continuous-vs-``generate()`` oracle as its XLA twin (bitwise for
fp32/bf16-compute parity classes, threshold for int8).
"""

import queue as _queue_mod
import threading
import time
from contextlib import nullcontext
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.generation import (
    ATTENTION_IMPLS,
    DEFAULT_PAGE_TOKENS,
    SPARSE_BAND,
    _attend_window_one,
    _cache_dtype,
    _chunk_layer_with,
    _forward_chunk,
    _layer_tree,
    _ln,
    _ngram_draft,
    _round_up,
    _speculative_verify,
    _step,
    _window_base,
    _window_finish,
    _window_qkv,
    _window_slice_one,
    resolve_page_tokens,
)
from deepspeed_tpu.profiling.sentinels import CompileSentinel, transfer_free
from deepspeed_tpu import kernels, telemetry
from deepspeed_tpu.parallel.mesh import mp_world_size
from deepspeed_tpu.parallel.sharding_registry import (
    create_serving_mesh,
    serving_registry,
    serving_sharding,
)
from deepspeed_tpu.inference.quantization import (
    dequantize_kv,
    dequantize_kv_np,
    embed_rows,
    logits_table,
    quantize_kv_np,
    requantize_kv,
    vocab_size,
)
from deepspeed_tpu.inference.serving.config import ServingConfig
from deepspeed_tpu.inference.serving.fault_injection import ServingFaultInjector
from deepspeed_tpu.inference.serving.kv_pool import (
    KV_CACHE_DTYPES,
    KVCachePool,
    PoolExhaustedError,
)
from deepspeed_tpu.inference.serving.metrics import ServingMetrics
from deepspeed_tpu.inference.serving.prefix_cache import (
    MemoryPressureGuard,
    PrefixKVCache,
    read_host_rss_mb,
)
from deepspeed_tpu.inference.serving.degrade import DegradeLadder
from deepspeed_tpu.inference.serving.scheduler import (
    ContinuousBatchingScheduler,
    EngineDrainingError,
    QueueFullError,
    RequestTimeoutError,
    bucket_for,
    default_buckets,
)


def _parse_attention_impl(spec, buckets):
    """Validate ``serving.attention_impl``: None / a backend name (every
    bucket) / a ``{bucket: impl}`` dict with an optional ``"default"``
    key. Returns ``(default_impl, {bucket: impl})``."""
    if spec is None:
        return "dense", {}
    if isinstance(spec, str):
        if spec not in ATTENTION_IMPLS:
            raise ValueError(
                f"serving.attention_impl must be one of {ATTENTION_IMPLS}, "
                f"got {spec!r}")
        return spec, {}
    if not isinstance(spec, dict):
        raise ValueError(
            f"serving.attention_impl must be one of {ATTENTION_IMPLS} or a "
            f"{{bucket: impl}} dict, got {spec!r}")
    default = "dense"
    table = {}
    for key, impl in spec.items():
        if impl not in ATTENTION_IMPLS:
            raise ValueError(
                f"serving.attention_impl[{key!r}] must be one of "
                f"{ATTENTION_IMPLS}, got {impl!r}")
        if key == "default":
            default = impl
            continue
        if isinstance(key, bool) or not isinstance(key, int):
            raise ValueError(
                f"serving.attention_impl keys must be prompt-bucket ints "
                f"or 'default', got {key!r}")
        if key not in tuple(buckets):
            raise ValueError(
                f"serving.attention_impl bucket {key} is not in the prompt "
                f"bucket ladder {tuple(buckets)}")
        table[int(key)] = impl
    return default, table


# -- paged-pool index plumbing ------------------------------------------
# The pool stores KV as fixed-size pages ([L, n_pages, nh, pt, hd]) with
# per-lane page tables ([MaxSlots, mp], physical page 0 reserved as the
# null/garbage sink — see kv_pool.py). The decode programs below never
# see a contiguous [S_max] lane; they gather the pages a lane actually
# owns and scatter back only the rows they wrote.

def _gather_lanes(pool_side, page_tables):
    """Reassemble every lane's contiguous [nh, S_max, hd] KV stripe from
    its pages: pool [L, P, nh, pt, hd] + tables [B, mp] ->
    [L, B, nh, mp*pt, hd]. Unmapped logical pages read the null page;
    those positions are either beyond the lane's position counter
    (masked to exact-zero probability by the causal mask) or belong to
    inactive lanes (outputs discarded) — the same invisible-garbage
    argument the contiguous layout relied on."""
    L, _, nh, pt, hd = pool_side.shape
    B, mp = page_tables.shape
    g = pool_side[:, page_tables]                    # [L, B, mp, nh, pt, hd]
    return jnp.moveaxis(g, 2, 3).reshape(L, B, nh, mp * pt, hd)


def _row_pages(page_tables, tok, active, page_tokens):
    """Physical destination page for per-lane token indices ``tok``
    ([B] or [B, n]): the lane's mapped page, or the null page 0 for
    inactive lanes and out-of-range indices — bad writes are DROPPED
    into the sink, never clipped onto a live row."""
    B, mp = page_tables.shape
    tok2 = tok if tok.ndim == 2 else tok[:, None]
    logical = jnp.clip(tok2 // page_tokens, 0, mp - 1)
    phys = jnp.take_along_axis(page_tables, logical, axis=1)
    ok = active[:, None] & (tok2 >= 0) & (tok2 < mp * page_tokens)
    phys = jnp.where(ok, phys, 0)
    return phys if tok.ndim == 2 else phys[:, 0]


def _lane_rows(lanes, tok):
    """Extract each lane's row(s) at token indices ``tok`` from gathered
    [L, B, nh, S, hd] stripes -> [L, B, nh, hd] (or [L, B, n, nh, hd]
    for ``tok`` [B, n]): the freshly-written KV the pool needs back.
    Reads clip (the scatter drops the same indices, so a clipped read
    is never stored anywhere that matters)."""
    S = lanes.shape[3]
    tok2 = tok if tok.ndim == 2 else tok[:, None]
    idx = jnp.clip(tok2, 0, S - 1)
    out = jnp.take_along_axis(
        lanes, idx[None, :, None, :, None], axis=3)  # [L, B, nh, n, hd]
    out = jnp.moveaxis(out, 3, 2)                    # [L, B, n, nh, hd]
    return out[:, :, 0] if tok.ndim == 1 else out


def _scatter_rows(pool_side, page_tables, rows, tok, active, page_tokens):
    """Write per-lane rows back into their pages. ``rows`` is
    [L, B, nh, hd] (``tok`` [B]) or [L, B, n, nh, hd] (``tok`` [B, n]);
    writes from inactive lanes or beyond a lane's mapped pages land on
    the null page. Advanced indices at non-adjacent axes put the batch
    dims FIRST, hence the moveaxis."""
    dp = _row_pages(page_tables, tok, active, page_tokens)
    off = tok % page_tokens
    vals = jnp.moveaxis(rows, 0, 1 if tok.ndim == 1 else 2)
    return pool_side.at[:, dp, :, off].set(vals.astype(pool_side.dtype))


@partial(jax.jit, static_argnames=("n_heads",), donate_argnums=(1, 2))
def _prefill_batch_jit(params, init_k, init_v, padded_ids, starts, true_lens,
                       *, n_heads):
    """Single-pass batched prefill: ``padded_ids`` [B, Sb] (each lane's
    to-be-computed tokens, right-padded to the bucket) forwarded in ONE
    causal call into ``init_k``/``init_v`` ([L, B, nh, S_max, hd] —
    zeros, or prefix-cache KV for lanes resuming at ``starts[i] > 0``).
    Returns (k, v, first greedy token per lane).

    ``starts`` and ``true_lens`` are traced [B] vectors, so ONE compiled
    program per (B, Sb, S_max) serves every group composition: plain
    prompts, prefix-cache hits at any offset, and (at B=1, Sb=chunk)
    every chunk of a chunked prefill. The logits are *selected* at each
    lane's true last prompt position, which makes both pad tokens and
    dummy lanes invisible to the emitted token."""
    B, Sb = padded_ids.shape
    tr = params["params"]["transformer"]
    h, (k, v) = _forward_chunk(params, n_heads, (init_k, init_v),
                               padded_ids, starts)
    idx = jnp.clip(true_lens - 1 - starts, 0, Sb - 1)
    h_sel = jnp.take_along_axis(h, idx[:, None, None], axis=1)[:, 0]
    h_sel = _ln(h_sel, tr["ln_f"])
    logits = h_sel @ logits_table(tr["wte"], h_sel.dtype).T
    first = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return k, v, first


def _prefill_tail(params, h, starts, true_lens):
    """Shared logits tail of every prefill program: select each lane's
    true last prompt position, final LN, greedy first token."""
    Sb = h.shape[1]
    tr = params["params"]["transformer"]
    idx = jnp.clip(true_lens - 1 - starts, 0, Sb - 1)
    h_sel = jnp.take_along_axis(h, idx[:, None, None], axis=1)[:, 0]
    h_sel = _ln(h_sel, tr["ln_f"])
    logits = h_sel @ logits_table(tr["wte"], h_sel.dtype).T
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


@partial(jax.jit, static_argnames=("n_heads", "page_tokens"),
         donate_argnums=(1, 2))
def _prefill_batch_flash_jit(params, init_k, init_v, padded_ids, starts,
                             true_lens, *, n_heads, page_tokens):
    """``_prefill_batch_jit`` with the flash (online-softmax) backend:
    same contract, never materializes the [Sb, S_max] score matrix.
    Math-equal to dense (allclose, not bitwise); the cache length is a
    page multiple by construction (``resolve_page_tokens``)."""
    h, (k, v) = _forward_chunk(params, n_heads, (init_k, init_v),
                               padded_ids, starts, attn_impl="flash",
                               page_tokens=page_tokens)
    return k, v, _prefill_tail(params, h, starts, true_lens)


@partial(jax.jit, static_argnames=("n_heads", "page_tokens"),
         donate_argnums=(1, 2))
def _prefill_batch_window_jit(params, init_k, init_v, padded_ids, starts,
                              true_lens, *, n_heads, page_tokens):
    """``_prefill_batch_jit`` with the banded block-sparse backend:
    every query attends only its canonical window + anchor page —
    O(Sb*pt) attention instead of O(Sb*S_max), which is what makes 16k+
    prompts admissible at interactive TTFT. Callers pad ``padded_ids``
    to a page-multiple width; pad queries write garbage KV past the true
    length, which decode overwrites in order before it is ever
    attendable (the same write-before-attend argument dense prefill
    uses for its pad region)."""
    h, (k, v) = _forward_chunk(params, n_heads, (init_k, init_v),
                               padded_ids, starts, attn_impl="sparse_xla",
                               page_tokens=page_tokens)
    return k, v, _prefill_tail(params, h, starts, true_lens)


@partial(jax.jit, static_argnames=("n_heads", "page_tokens", "kernel_impl",
                                   "kernel_interpret"),
         donate_argnums=(1, 2))
def _prefill_batch_kernel_jit(params, init_k, init_v, padded_ids, starts,
                              true_lens, *, n_heads, page_tokens,
                              kernel_impl, kernel_interpret):
    """``_prefill_batch_jit`` through the fused decode-attention kernel
    (``pallas_decode`` lanes): the chunk attends via ``chunk_attend`` —
    the contiguous-cache adapter over the SAME paged kernel the decode
    step runs — so prefill and decode share one math path and the
    per-backend oracle holds bitwise. ``kernel_impl``/``kernel_interpret``
    are the registry's resolved statics (part of the cache key: a
    selection change can never serve a stale program)."""
    h, (k, v) = _forward_chunk(params, n_heads, (init_k, init_v),
                               padded_ids, starts, attn_impl="pallas_decode",
                               page_tokens=page_tokens,
                               kernel_impl=kernel_impl,
                               kernel_interpret=kernel_interpret)
    return k, v, _prefill_tail(params, h, starts, true_lens)


@partial(jax.jit, static_argnames=("n_heads", "page_tokens", "kernel_impl",
                                   "kernel_interpret"),
         donate_argnums=(1, 2))
def _prefill_batch_kernel_window_jit(params, init_k, init_v, padded_ids,
                                     starts, true_lens, *, n_heads,
                                     page_tokens, kernel_impl,
                                     kernel_interpret):
    """``_prefill_batch_window_jit`` with the band math fused into the
    Pallas band kernel (``pallas_sparse`` lanes): same canonical
    window + anchor key set, same page-multiple chunk-width contract."""
    h, (k, v) = _forward_chunk(params, n_heads, (init_k, init_v),
                               padded_ids, starts, attn_impl="pallas_sparse",
                               page_tokens=page_tokens,
                               kernel_impl=kernel_impl,
                               kernel_interpret=kernel_interpret)
    return k, v, _prefill_tail(params, h, starts, true_lens)


@partial(jax.jit, static_argnames=("n_heads",), donate_argnums=(1, 2, 4, 5))
def _decode_step_jit(params, pool_k, pool_v, page_tables, tokens, positions,
                     active, *, n_heads):
    """One masked batched decode step over every pool lane.

    Each lane's pages are gathered into the EXACT contiguous stripe the
    old layout stored (unmapped pages read masked-invisible garbage),
    its last token runs through the one-shot path's ``_step`` (vmapped
    as a B=1 lane), and only the freshly-written row is scattered back
    by page index — untouched positions keep their bits, so the step is
    bitwise the contiguous step. Inactive lanes compute garbage routed
    to the null page and keep their token via the ``active`` mask; pool
    buffers, tokens and positions are donated, page tables and the mask
    are NOT (they live on device across steps), so steady-state decode
    still needs no per-step host->device upload at all."""
    pt = pool_k.shape[3]
    lanes_k = _gather_lanes(pool_k, page_tables)
    lanes_v = _gather_lanes(pool_v, page_tables)

    def lane(ck, cv, tok, pos):
        logits, (ck2, cv2) = _step(params, n_heads, (ck[:, None], cv[:, None]),
                                   tok[None], pos)
        return logits[0], ck2[:, 0], cv2[:, 0]

    logits, lanes_k, lanes_v = jax.vmap(
        lane, in_axes=(1, 1, 0, 0), out_axes=(0, 1, 1))(
        lanes_k, lanes_v, tokens, positions)
    pool_k = _scatter_rows(pool_k, page_tables, _lane_rows(lanes_k, positions),
                           positions, active, pt)
    pool_v = _scatter_rows(pool_v, page_tables, _lane_rows(lanes_v, positions),
                           positions, active, pt)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    tokens = jnp.where(active, nxt, tokens)
    positions = jnp.where(active, positions + 1, positions)
    return tokens, positions, pool_k, pool_v


@partial(jax.jit, static_argnames=("n_heads", "qmode"),
         donate_argnums=(1, 2, 6, 7))
def _decode_step_quant_jit(params, pool_k, pool_v, k_scale, v_scale,
                           page_tables, tokens, positions, active, *,
                           n_heads, qmode):
    """``_decode_step_jit`` over a QUANTIZED paged pool: each lane's
    gathered stripe dequantizes at use (int8 * per-head scale, or a
    bf16 cast), runs the same vmapped ``_step``, and the written row is
    re-stored against its FIXED install-time scales — idempotent on
    untouched positions (see ``requantize_kv``), so the step still only
    logically appends one token per lane. Scales are NOT donated: they
    are returned unchanged and the host keeps its reference. ``qmode``
    is static — one program per storage mode, no traced branching (for
    "bf16" the scale operands are None)."""
    dtype = _cache_dtype(params)
    pt = pool_k.shape[3]
    lanes_k = _gather_lanes(pool_k, page_tables)
    lanes_v = _gather_lanes(pool_v, page_tables)

    if qmode == "int8":
        def lane(ck, cv, sk, sv, tok, pos):
            logits, (ck2, cv2) = _step(
                params, n_heads,
                (dequantize_kv(ck, sk, dtype)[:, None],
                 dequantize_kv(cv, sv, dtype)[:, None]),
                tok[None], pos)
            return (logits[0], requantize_kv(ck2[:, 0], sk),
                    requantize_kv(cv2[:, 0], sv))

        logits, lanes_k, lanes_v = jax.vmap(
            lane, in_axes=(1, 1, 1, 1, 0, 0), out_axes=(0, 1, 1))(
            lanes_k, lanes_v, k_scale, v_scale, tokens, positions)
    else:
        def lane(ck, cv, tok, pos):
            logits, (ck2, cv2) = _step(
                params, n_heads,
                (ck.astype(dtype)[:, None], cv.astype(dtype)[:, None]),
                tok[None], pos)
            return (logits[0], ck2[:, 0].astype(jnp.bfloat16),
                    cv2[:, 0].astype(jnp.bfloat16))

        logits, lanes_k, lanes_v = jax.vmap(
            lane, in_axes=(1, 1, 0, 0), out_axes=(0, 1, 1))(
            lanes_k, lanes_v, tokens, positions)
    pool_k = _scatter_rows(pool_k, page_tables, _lane_rows(lanes_k, positions),
                           positions, active, pt)
    pool_v = _scatter_rows(pool_v, page_tables, _lane_rows(lanes_v, positions),
                           positions, active, pt)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    tokens = jnp.where(active, nxt, tokens)
    positions = jnp.where(active, positions + 1, positions)
    return tokens, positions, pool_k, pool_v


@partial(jax.jit, static_argnames=("n_heads", "page_tokens", "qmode",
                                   "kernel_impl", "kernel_interpret"),
         donate_argnums=(1, 2, 6, 7))
def _decode_step_window_jit(params, pool_k, pool_v, k_scale, v_scale,
                            page_tables, tokens, positions, active, *,
                            n_heads, page_tokens, qmode, kernel_impl=None,
                            kernel_interpret=False):
    """Banded block-sparse decode over the paged pool. Unlike the dense
    step, it never reassembles whole lanes: each lane touches only its
    canonical window pages (SPARSE_BAND+1 pages ending at the query)
    plus the anchor page — O(page_tokens) KV traffic per token per lane
    instead of O(S_max), which is where the 16k-bucket speedup lives.
    Per layer: project qkv, store the written row into its page, gather
    the window/anchor pages, attend with the SAME ``_attend_window_one``
    the one-shot sparse ``generate()`` path uses (write-then-attend,
    matching ``_decode_one_window``) — the per-lane key set is identical
    by construction, so fp32 storage keeps the bitwise oracle. Window
    lanes use their own ``active`` mask; the pool and the token/position
    vectors are threaded through both class programs each step.

    ``kernel_impl`` (static, ``pallas_sparse`` lanes) swaps the band
    MATH for the fused Pallas band kernel (``kernels.band_attend``) —
    the window/anchor gather stays on the XLA side either way, so the
    per-lane key set (hence the oracle) is backend-identical."""
    dtype = _cache_dtype(params)
    pt = page_tokens
    B, mp = page_tables.shape
    tr = params["params"]["transformer"]
    layer_p = _layer_tree(params)

    h = embed_rows(tr["wte"], tokens) + tr["wpe"]["embedding"][positions]

    pp = jnp.clip(positions // pt, 0, mp - 1)          # each query's page
    lo = jnp.maximum(pp - SPARSE_BAND, 0)              # window's first page
    base = lo * pt
    win_logical = jnp.clip(
        lo[:, None] + jnp.arange(SPARSE_BAND + 1)[None, :], 0, mp - 1)
    win_phys = jnp.take_along_axis(page_tables, win_logical, axis=1)
    sink_phys = page_tables[:, 0]
    dp = _row_pages(page_tables, positions, active, pt)
    off = positions % pt

    def layer_body(h, inputs):
        lp, pk_l, pv_l, sk_l, sv_l = inputs
        q, kk, vv = _window_qkv(lp, h, n_heads)        # each [B, nh, hd]
        if qmode == "int8":
            krow = requantize_kv(kk[:, :, None, :], sk_l)[:, :, 0]
            vrow = requantize_kv(vv[:, :, None, :], sv_l)[:, :, 0]
        elif qmode == "bf16":
            krow, vrow = kk.astype(jnp.bfloat16), vv.astype(jnp.bfloat16)
        else:
            krow, vrow = kk, vv
        pk_l = pk_l.at[dp, :, off].set(krow)
        pv_l = pv_l.at[dp, :, off].set(vrow)

        def stripe(buf, scale):
            def dq(x):
                if qmode == "int8":
                    return dequantize_kv(x, scale, dtype)
                if qmode == "bf16":
                    return x.astype(dtype)
                return x
            win = jnp.moveaxis(buf[win_phys], 1, 2)    # [B, nh, bw, pt, hd]
            win = win.reshape(B, n_heads, (SPARSE_BAND + 1) * pt, -1)
            return dq(win), dq(buf[sink_phys])

        k_win, k_sink = stripe(pk_l, sk_l)
        v_win, v_sink = stripe(pv_l, sv_l)
        if kernel_impl is not None:
            ctx = kernels.band_attend(
                q, k_win, v_win, k_sink, v_sink, positions, base,
                dtype=dtype, impl=kernel_impl, interpret=kernel_interpret)
        else:
            ctx = jax.vmap(_attend_window_one,
                           in_axes=(0, 0, 0, 0, 0, 0, 0, None))(
                q, k_win, v_win, k_sink, v_sink, positions, base, dtype)
        h = _window_finish(lp, h, ctx)
        return h, (pk_l, pv_l)

    h, (pool_k, pool_v) = jax.lax.scan(
        layer_body, h, (layer_p, pool_k, pool_v, k_scale, v_scale))
    h = _ln(h, tr["ln_f"])
    logits = h @ logits_table(tr["wte"], h.dtype).T
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    tokens = jnp.where(active, nxt, tokens)
    positions = jnp.where(active, positions + 1, positions)
    return tokens, positions, pool_k, pool_v


@partial(jax.jit, static_argnames=("n_heads", "page_tokens", "qmode",
                                   "kernel_impl", "kernel_interpret"),
         donate_argnums=(1, 2, 6, 7))
def _decode_step_kernel_jit(params, pool_k, pool_v, k_scale, v_scale,
                            page_tables, tokens, positions, active, *,
                            n_heads, page_tokens, qmode, kernel_impl,
                            kernel_interpret):
    """Fused-kernel decode for ``pallas_decode`` lanes. Unlike the dense
    step it never reassembles contiguous stripes on the XLA side: each
    layer writes the lane's fresh KV row into its page, then hands the
    POOL ITSELF (storage dtype — int8 pages included) plus the lane page
    tables to ``kernels.decode_attend``, whose scalar-prefetch index map
    performs the paged gather inside the kernel's DMA schedule. int8
    pools pass per-page scales (the lane's fixed install scale scattered
    to its pages) so dequantization fuses into the QK/PV matmuls —
    no dequantized pool copy ever exists. The online-softmax recurrence
    is bitwise invariant to trailing fully-masked pages, so fp32 pools
    keep the bitwise continuous-vs-``generate()`` oracle even though
    ``generate()`` runs a shorter identity-table cache."""
    dtype = _cache_dtype(params)
    pt = page_tokens
    B, mp = page_tables.shape
    P = pool_k.shape[1]
    tr = params["params"]["transformer"]
    layer_p = _layer_tree(params)

    h = embed_rows(tr["wte"], tokens) + tr["wpe"]["embedding"][positions]
    dp = _row_pages(page_tables, positions, active, pt)
    off = positions % pt
    qpos = positions[:, None]

    def page_scales(sl):
        # per-(slot, head) install scales -> per-physical-page scales the
        # kernel gathers alongside each page block. Lanes never share
        # data pages; the null page takes whatever lane scatters last,
        # which only ever scales masked (exact-zero-probability) keys.
        s = jnp.broadcast_to(sl.reshape(B, 1, n_heads), (B, mp, n_heads))
        return jnp.zeros((P, n_heads), jnp.float32).at[page_tables].set(s)

    def layer_body(h, inputs):
        lp, pk_l, pv_l, sk_l, sv_l = inputs
        q, kk, vv = _window_qkv(lp, h, n_heads)        # each [B, nh, hd]
        if qmode == "int8":
            krow = requantize_kv(kk[:, :, None, :], sk_l)[:, :, 0]
            vrow = requantize_kv(vv[:, :, None, :], sv_l)[:, :, 0]
            ksp, vsp = page_scales(sk_l), page_scales(sv_l)
        elif qmode == "bf16":
            krow, vrow = kk.astype(jnp.bfloat16), vv.astype(jnp.bfloat16)
            ksp = vsp = None
        else:
            krow, vrow = kk, vv
            ksp = vsp = None
        pk_l = pk_l.at[dp, :, off].set(krow)
        pv_l = pv_l.at[dp, :, off].set(vrow)
        ctx = kernels.decode_attend(
            q[:, None], pk_l, pv_l, page_tables, qpos, page_tokens=pt,
            dtype=dtype, impl=kernel_impl, interpret=kernel_interpret,
            k_scale=ksp, v_scale=vsp)[:, 0]
        h = _window_finish(lp, h, ctx)
        return h, (pk_l, pv_l)

    h, (pool_k, pool_v) = jax.lax.scan(
        layer_body, h, (layer_p, pool_k, pool_v, k_scale, v_scale))
    h = _ln(h, tr["ln_f"])
    logits = h @ logits_table(tr["wte"], h.dtype).T
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    tokens = jnp.where(active, nxt, tokens)
    positions = jnp.where(active, positions + 1, positions)
    return tokens, positions, pool_k, pool_v


def _attend_window_chunk(q, cache_k, cache_v, qpos, pt, dtype):
    """Per-query canonical window attention for a SMALL chunk of queries
    (the k+1-wide speculative verify): no page-multiple chunk-width
    requirement — each query dynamic-slices its own window from the full
    lane stripe and attends with the same ``_attend_window_one`` every
    other sparse path uses, so the per-query key set (and hence the
    fp32 result, bitwise) matches the blocked prefill formulation."""
    def one(qi, p, ck, cv):
        b = _window_base(p, pt)
        k_win, v_win, k_sink, v_sink = _window_slice_one(ck, cv, b, pt)
        return _attend_window_one(qi, k_win, v_win, k_sink, v_sink, p, b,
                                  dtype)

    return jax.vmap(lambda qrow, prow, ck, cv: jax.vmap(
        lambda qi, p: one(qi, p, ck, cv))(qrow, prow))(
        q, qpos, cache_k, cache_v)


def _forward_chunk_window(params, n_heads, caches, ids, starts, pt):
    """The sparse-backend twin of ``_forward_chunk`` for the speculative
    verify: same embed/scan shell and cache writes, attention via
    ``_attend_window_chunk`` (verify chunks are k+1 wide — not a page
    multiple, so the blocked ``_chunk_attend_window`` cannot be used)."""
    tr = params["params"]["transformer"]
    layer_p = _layer_tree(params)
    C = ids.shape[1]
    pos = starts[:, None] + jnp.arange(C)[None, :]
    h = embed_rows(tr["wte"], ids) + tr["wpe"]["embedding"][pos]

    def layer_body(h, inputs):
        lp, ck_l, cv_l = inputs
        h, ck_l, cv_l = _chunk_layer_with(
            lp, h, ck_l, cv_l, starts, n_heads,
            lambda q, ck, cv, qpos: _attend_window_chunk(q, ck, cv, qpos,
                                                         pt, h.dtype))
        return h, (ck_l, cv_l)

    h, caches = jax.lax.scan(layer_body, h, (layer_p,) + tuple(caches))
    return h, caches


def _speculative_verify_window(params, n_heads, caches, tokens, drafts,
                               positions, pt):
    """``_speculative_verify`` with windowed attention: identical
    draft/oracle/acceptance logic, the one-forward verify runs the
    sparse key set. See ``_speculative_verify`` for the rollback-free
    stale-KV argument (it is backend-independent: the stale range sits
    inside the next step's write window either way)."""
    tr = params["params"]["transformer"]
    k = drafts.shape[1]
    ids = jnp.concatenate([tokens[:, None], drafts], axis=1)     # [B, k+1]
    h, caches = _forward_chunk_window(params, n_heads, caches, ids,
                                      positions, pt)
    h = _ln(h, tr["ln_f"])
    logits = h @ logits_table(tr["wte"], h.dtype).T
    oracle = jnp.argmax(logits, axis=-1).astype(jnp.int32)       # [B, k+1]
    ok = (drafts == oracle[:, :k]).astype(jnp.int32)
    accepted = jnp.sum(jnp.cumprod(ok, axis=1), axis=1)          # [B]
    return oracle, accepted, caches


def _spec_core(params, n_heads, caches, history, tokens, positions, active,
               draft_noise, k, window_pt=None, kernel_backend=None,
               kernel_impl=None, kernel_interpret=False):
    """Shared body of the speculative step programs: draft -> (optional
    noise) -> one-forward verify -> advance. Operates on COMPUTE-dtype
    caches; the quantized wrapper handles storage conversion.
    ``kernel_backend`` (static) routes the k+1-wide verify forward
    through the kernel tier ("pallas_decode"/"pallas_sparse" with
    ``window_pt`` as its page size) instead of the dense/window XLA
    verifies."""
    S_max = history.shape[1]
    V = vocab_size(params["params"]["transformer"]["wte"])
    drafts = jax.vmap(partial(_ngram_draft, k=k))(history, positions)
    # fault-injection hook: draft_noise is normally all-zeros (the mod-V
    # add is then the identity, bitwise) — the corrupt_draft arm swaps in
    # nonzero values without changing shapes, so scrambling never
    # recompiles
    drafts = (drafts + draft_noise) % V
    if kernel_backend is not None:
        oracle, accepted, caches = _speculative_verify(
            params, n_heads, caches, tokens, drafts, positions,
            attn_impl=kernel_backend, page_tokens=window_pt,
            kernel_impl=kernel_impl, kernel_interpret=kernel_interpret)
    elif window_pt is None:
        oracle, accepted, caches = _speculative_verify(
            params, n_heads, caches, tokens, drafts, positions)
    else:
        oracle, accepted, caches = _speculative_verify_window(
            params, n_heads, caches, tokens, drafts, positions, window_pt)
    # append all k+1 oracle tokens to the history at the lane's write
    # window; positions past the accepted point hold speculative
    # continuations the next step overwrites — the drafter's bigram scan
    # only trusts positions below its pending one, and emitted output
    # never comes from history, so they cannot corrupt anything
    idx = jnp.where(active[:, None],
                    positions[:, None] + 1 + jnp.arange(k + 1)[None, :],
                    S_max)                                   # OOB -> dropped
    history = jax.vmap(
        lambda h, i, t: h.at[i].set(t, mode="drop"))(history, idx, oracle)
    last = jnp.take_along_axis(oracle, accepted[:, None], axis=1)[:, 0]
    tokens = jnp.where(active, last, tokens)
    positions = jnp.where(active,
                          jnp.minimum(positions + accepted + 1, S_max - 1),
                          positions)
    return tokens, positions, caches, history, oracle, accepted


@partial(jax.jit, static_argnames=("n_heads", "k"),
         donate_argnums=(1, 2, 4, 5, 6))
def _spec_step_jit(params, pool_k, pool_v, page_tables, history, tokens,
                   positions, active, draft_noise, *, n_heads, k):
    """One SPECULATIVE masked batched decode step over every pool lane.

    Per lane: gather the lane's pages into its contiguous stripe, draft
    ``k`` tokens (n-gram lookup over ``history``), feed pending-token +
    drafts through ONE k+1-wide causal forward against the stripes
    (``_forward_chunk`` — the gathered pool IS the chunk cache), accept
    the longest draft prefix the greedy oracle confirms, advance
    position by accepted+1, and scatter the k+1 written rows back by
    page index (overflow past a lane's pages drops to the null sink —
    only reachable after the request's retirement point, see
    ``_alloc_tokens``). ``k`` and the lane count are static; drafts,
    acceptance and noise are traced, so acceptance variation and slot
    churn reuse one compiled program. Returns the full oracle [B, k+1]
    and per-lane accepted counts for the host emit loop."""
    pt = pool_k.shape[3]
    lanes = (_gather_lanes(pool_k, page_tables),
             _gather_lanes(pool_v, page_tables))
    written = positions[:, None] + jnp.arange(k + 1)[None, :]
    tokens, positions, (lk, lv), history, oracle, accepted = \
        _spec_core(params, n_heads, lanes, history, tokens,
                   positions, active, draft_noise, k)
    pool_k = _scatter_rows(pool_k, page_tables, _lane_rows(lk, written),
                           written, active, pt)
    pool_v = _scatter_rows(pool_v, page_tables, _lane_rows(lv, written),
                           written, active, pt)
    return tokens, positions, pool_k, pool_v, history, oracle, accepted


@partial(jax.jit, static_argnames=("n_heads", "k", "qmode"),
         donate_argnums=(1, 2, 6, 7, 8))
def _spec_step_quant_jit(params, pool_k, pool_v, k_scale, v_scale,
                         page_tables, history, tokens, positions, active,
                         draft_noise, *, n_heads, k, qmode):
    """Speculative step over a quantized paged pool: dequantize the
    gathered stripes at use, run the same draft/verify core in the
    compute dtype, then requantize against the FIXED per-(slot, head)
    install scales (or a bf16 cast) and scatter back the k+1 written
    rows. Untouched positions round-trip bitwise (idempotent requant),
    so only the freshly-written rows actually change."""
    dtype = _cache_dtype(params)
    pt = pool_k.shape[3]
    lk = _gather_lanes(pool_k, page_tables)
    lv = _gather_lanes(pool_v, page_tables)
    if qmode == "int8":
        kf = dequantize_kv(lk, k_scale, dtype)
        vf = dequantize_kv(lv, v_scale, dtype)
    else:
        kf, vf = lk.astype(dtype), lv.astype(dtype)
    written = positions[:, None] + jnp.arange(k + 1)[None, :]
    tokens, positions, (kf, vf), history, oracle, accepted = _spec_core(
        params, n_heads, (kf, vf), history, tokens, positions, active,
        draft_noise, k)
    if qmode == "int8":
        rows_k = _lane_rows(requantize_kv(kf, k_scale), written)
        rows_v = _lane_rows(requantize_kv(vf, v_scale), written)
    else:
        rows_k = _lane_rows(kf, written).astype(jnp.bfloat16)
        rows_v = _lane_rows(vf, written).astype(jnp.bfloat16)
    pool_k = _scatter_rows(pool_k, page_tables, rows_k, written, active, pt)
    pool_v = _scatter_rows(pool_v, page_tables, rows_v, written, active, pt)
    return tokens, positions, pool_k, pool_v, history, oracle, accepted


@partial(jax.jit, static_argnames=("n_heads", "k", "page_tokens", "qmode"),
         donate_argnums=(1, 2, 6, 7, 8))
def _spec_step_window_jit(params, pool_k, pool_v, k_scale, v_scale,
                          page_tables, history, tokens, positions, active,
                          draft_noise, *, n_heads, k, page_tokens, qmode):
    """Speculative step for sparse-backend lanes: same draft/accept core,
    with the k+1-wide verify forward attending the windowed key set
    (``_speculative_verify_window``). The verify gathers full lane
    stripes like the dense spec step — speculation is a latency
    trade-off knob, not the steady-state path the windowed decode
    optimizes — and scatters the k+1 written rows back by page index.
    ``qmode`` is static; scale operands are None unless int8."""
    dtype = _cache_dtype(params)
    pt = pool_k.shape[3]
    lk = _gather_lanes(pool_k, page_tables)
    lv = _gather_lanes(pool_v, page_tables)
    if qmode == "int8":
        kf = dequantize_kv(lk, k_scale, dtype)
        vf = dequantize_kv(lv, v_scale, dtype)
    elif qmode == "bf16":
        kf, vf = lk.astype(dtype), lv.astype(dtype)
    else:
        kf, vf = lk, lv
    written = positions[:, None] + jnp.arange(k + 1)[None, :]
    tokens, positions, (kf, vf), history, oracle, accepted = _spec_core(
        params, n_heads, (kf, vf), history, tokens, positions, active,
        draft_noise, k, window_pt=page_tokens)
    if qmode == "int8":
        rows_k = _lane_rows(requantize_kv(kf, k_scale), written)
        rows_v = _lane_rows(requantize_kv(vf, v_scale), written)
    elif qmode == "bf16":
        rows_k = _lane_rows(kf, written).astype(jnp.bfloat16)
        rows_v = _lane_rows(vf, written).astype(jnp.bfloat16)
    else:
        rows_k = _lane_rows(kf, written)
        rows_v = _lane_rows(vf, written)
    pool_k = _scatter_rows(pool_k, page_tables, rows_k, written, active, pt)
    pool_v = _scatter_rows(pool_v, page_tables, rows_v, written, active, pt)
    return tokens, positions, pool_k, pool_v, history, oracle, accepted


@partial(jax.jit, static_argnames=("n_heads", "k", "page_tokens", "qmode",
                                   "attn_backend", "kernel_impl",
                                   "kernel_interpret"),
         donate_argnums=(1, 2, 6, 7, 8))
def _spec_step_kernel_jit(params, pool_k, pool_v, k_scale, v_scale,
                          page_tables, history, tokens, positions, active,
                          draft_noise, *, n_heads, k, page_tokens, qmode,
                          attn_backend, kernel_impl, kernel_interpret):
    """Speculative step for kernel-tier lanes: same draft/accept core as
    ``_spec_step_window_jit``, with the k+1-wide verify forward routed
    through the resolved kernel backend (``attn_backend`` is the static
    ``pallas_decode``/``pallas_sparse`` name; the verify gathers full
    lane stripes like every spec step — speculation trades gather
    traffic for acceptance throughput) and the k+1 written rows
    scattered back by page index. ``qmode`` is static; scale operands
    are None unless int8."""
    dtype = _cache_dtype(params)
    pt = pool_k.shape[3]
    lk = _gather_lanes(pool_k, page_tables)
    lv = _gather_lanes(pool_v, page_tables)
    if qmode == "int8":
        kf = dequantize_kv(lk, k_scale, dtype)
        vf = dequantize_kv(lv, v_scale, dtype)
    elif qmode == "bf16":
        kf, vf = lk.astype(dtype), lv.astype(dtype)
    else:
        kf, vf = lk, lv
    written = positions[:, None] + jnp.arange(k + 1)[None, :]
    tokens, positions, (kf, vf), history, oracle, accepted = _spec_core(
        params, n_heads, (kf, vf), history, tokens, positions, active,
        draft_noise, k, window_pt=page_tokens, kernel_backend=attn_backend,
        kernel_impl=kernel_impl, kernel_interpret=kernel_interpret)
    if qmode == "int8":
        rows_k = _lane_rows(requantize_kv(kf, k_scale), written)
        rows_v = _lane_rows(requantize_kv(vf, v_scale), written)
    elif qmode == "bf16":
        rows_k = _lane_rows(kf, written).astype(jnp.bfloat16)
        rows_v = _lane_rows(vf, written).astype(jnp.bfloat16)
    else:
        rows_k = _lane_rows(kf, written)
        rows_v = _lane_rows(vf, written)
    pool_k = _scatter_rows(pool_k, page_tables, rows_k, written, active, pt)
    pool_v = _scatter_rows(pool_v, page_tables, rows_v, written, active, pt)
    return tokens, positions, pool_k, pool_v, history, oracle, accepted


class _ChunkedPrefill:
    """In-flight chunked prefill: the request, its private cache pair
    (carried across engine steps between chunk calls), how far it has
    prefilled, and the pool slot reserved for it at start."""

    __slots__ = ("req", "k", "v", "pos", "reuse", "slot", "prefill_s")

    def __init__(self, req, k, v, pos, reuse, slot):
        self.req = req
        self.k = k
        self.v = v
        self.pos = pos
        self.reuse = reuse
        self.slot = slot
        self.prefill_s = 0.0


class _EngineLadderShim:
    """Ladder facade handed to MemoryPressureGuard: the engine creates
    its DegradeLadder lazily (configure_degrade), so the guard must not
    capture the ladder object at construction — it reads/writes through
    the engine, which creates the ladder on first set_rung."""

    __slots__ = ("_engine",)

    def __init__(self, engine):
        self._engine = engine

    @property
    def rung(self):
        return self._engine._degrade_rung

    def set_rung(self, rung, reason="forced"):
        return self._engine.set_degrade_rung(rung, reason=reason)


class ServingEngine:
    """Request queue + KV pool + the single compiled decode loop.

    Drive it synchronously (``step()`` / ``drain()`` — deterministic, what
    the tests do) or as a background thread (``start()`` / ``stop()``)
    with ``submit()`` from any thread."""

    def __init__(self, params, model_config, serving_config=None,
                 monitor=None, injector=None, sentinel_config=None,
                 telemetry_config=None, rank=None):
        cfg = serving_config or ServingConfig()
        self.params = params
        self.model_config = model_config
        self.config = cfg
        self.n_layers = model_config.num_hidden_layers
        self.n_heads = model_config.num_attention_heads
        self.head_dim = model_config.hidden_size // self.n_heads

        mpe = model_config.max_position_embeddings
        self.max_seq_len = cfg.max_seq_len or mpe
        if self.max_seq_len > mpe:
            raise ValueError(
                f"serving.max_seq_len={self.max_seq_len} exceeds "
                f"max_position_embeddings={mpe}")
        buckets = cfg.prompt_buckets or default_buckets(self.max_seq_len - 1)
        if buckets[-1] > self.max_seq_len - 1:
            raise ValueError(
                f"largest prompt bucket ({buckets[-1]}) must leave room for "
                f"one generated token (max_seq_len={self.max_seq_len})")
        if cfg.prefill_chunk_tokens < 0:
            raise ValueError(
                f"serving.prefill_chunk_tokens must be >= 0 "
                f"(0 disables chunked prefill), got {cfg.prefill_chunk_tokens}")
        if cfg.prefix_cache_mb < 0:
            raise ValueError(
                f"serving.prefix_cache_mb must be >= 0 "
                f"(0 disables the prefix cache), got {cfg.prefix_cache_mb}")
        if cfg.prefix_spill_mb < 0:
            raise ValueError(
                f"serving.prefix_spill_mb must be >= 0 "
                f"(0 disables the spill tier), got {cfg.prefix_spill_mb}")
        if cfg.prefix_spill_mb > 0 and cfg.prefix_cache_mb <= 0:
            raise ValueError(
                f"serving.prefix_spill_mb={cfg.prefix_spill_mb} needs a "
                f"live prefix cache (prefix_cache_mb > 0) to spill from")
        if cfg.prefix_spill_dir is not None and cfg.prefix_spill_mb <= 0:
            raise ValueError(
                f"serving.prefix_spill_dir={cfg.prefix_spill_dir!r} needs "
                f"a spill tier (prefix_spill_mb > 0) above it")
        if cfg.host_mem_watermark_mb < 0:
            raise ValueError(
                f"serving.host_mem_watermark_mb must be >= 0 "
                f"(0 disables the memory-pressure guard), "
                f"got {cfg.host_mem_watermark_mb}")
        if (isinstance(cfg.speculative_k, bool)
                or not isinstance(cfg.speculative_k, int)
                or cfg.speculative_k < 0):
            raise ValueError(
                f"serving.speculative_k must be an int >= 0 "
                f"(0 disables speculative decoding), "
                f"got {cfg.speculative_k!r}")
        if cfg.speculative_k >= self.max_seq_len:
            raise ValueError(
                f"serving.speculative_k={cfg.speculative_k} must be "
                f"smaller than max_seq_len={self.max_seq_len}")
        if cfg.kv_cache_dtype not in KV_CACHE_DTYPES:
            raise ValueError(
                f"serving.kv_cache_dtype must be one of {KV_CACHE_DTYPES}, "
                f"got {cfg.kv_cache_dtype!r}")
        if cfg.kv_page_tokens is not None and (
                isinstance(cfg.kv_page_tokens, bool)
                or not isinstance(cfg.kv_page_tokens, int)
                or cfg.kv_page_tokens < 1):
            raise ValueError(
                f"serving.kv_page_tokens must be an int >= 1 "
                f"(None = {DEFAULT_PAGE_TOKENS}), got {cfg.kv_page_tokens!r}")
        if cfg.kv_pool_tokens is not None and (
                isinstance(cfg.kv_pool_tokens, bool)
                or not isinstance(cfg.kv_pool_tokens, int)
                or cfg.kv_pool_tokens < 1):
            raise ValueError(
                f"serving.kv_pool_tokens must be an int >= 1 (None = "
                f"max_slots * max_seq_len, the contiguous-equivalent "
                f"budget), got {cfg.kv_pool_tokens!r}")
        self._impl_default, self._impl_map = _parse_attention_impl(
            cfg.attention_impl, buckets)
        impls = set(self._impl_map.values())
        impls.add(self._impl_default)
        self._any_window = "sparse_xla" in impls
        self._any_flash = "flash" in impls
        self._any_kfull = "pallas_decode" in impls
        self._any_kwin = "pallas_sparse" in impls
        page_tokens = resolve_page_tokens(
            cfg.kv_page_tokens or DEFAULT_PAGE_TOKENS, self.max_seq_len)
        if ((self._any_window or self._any_kwin)
                and self.max_seq_len < (SPARSE_BAND + 1) * page_tokens):
            raise ValueError(
                f"serving.attention_impl='sparse_xla'/'pallas_sparse' needs "
                f"at least {SPARSE_BAND + 1} pages per lane: max_seq_len="
                f"{self.max_seq_len} < {(SPARSE_BAND + 1) * page_tokens} "
                f"(kv_page_tokens={page_tokens})")
        # kernel-tier backends: resolve the (impl, interpret) statics ONCE
        # here, through the registry's availability probe — a failed probe
        # degrades the whole engine to the XLA fallback math (same oracle)
        # instead of crashing construction or, worse, the serving loop.
        kernel_backends = sorted(impls & set(kernels.KERNEL_BACKENDS))
        if cfg.attention_kernel is not None and not kernel_backends:
            raise ValueError(
                f"serving.attention_kernel={cfg.attention_kernel!r} applies "
                f"only when a kernel-tier attention_impl "
                f"({tuple(sorted(kernels.KERNEL_BACKENDS))}) is armed")
        if (cfg.kernel_interpret is not None
                and not isinstance(cfg.kernel_interpret, bool)):
            raise ValueError(
                f"serving.kernel_interpret must be a bool or None "
                f"(None = auto: interpret off-TPU), "
                f"got {cfg.kernel_interpret!r}")
        self._kernel_impl = {}
        self._kernel_interpret = {}
        for be in kernel_backends:
            ki, kint = kernels.resolve(be, requested=cfg.attention_kernel,
                                       interpret=cfg.kernel_interpret)
            self._kernel_impl[be] = ki
            self._kernel_interpret[be] = kint

        # Tensor-parallel mesh (serving.mesh_shape / the ds_config
        # `parallel` block): build the mesh and the shared sharding
        # registry ONCE, shard the params per the registry rules, and
        # hand both to the pool so KV pages split their heads dim over
        # the `model` axis. The decode/prefill/spec programs are
        # unchanged — jit compiles them SPMD from the operand shardings
        # (GSPMD), so each lane class still compiles exactly once.
        # mesh_shape=None keeps the single-device engine byte-identical.
        self.mesh = None
        self.registry = None
        self._replicated_sharding = None
        self._prefill_kv_sharding = None
        if cfg.mesh_shape is not None:
            self.registry = serving_registry(
                extra_rules=cfg.partition_rules,
                replicate_unmatched=cfg.replicate_unmatched)
            self.mesh = create_serving_mesh(cfg.mesh_shape)
            self.registry.validate_axes(self.mesh)
            mp = mp_world_size(self.mesh)
            if self.n_heads % mp != 0:
                raise ValueError(
                    f"serving.mesh_shape model axis {mp} must divide "
                    f"num_attention_heads={self.n_heads} (the KV pool "
                    f"shards heads)")
            self.params = self.registry.shard(self.mesh, params)
            self._replicated_sharding = serving_sharding(
                self.mesh, "serving/lane_state", registry=self.registry)
            self._prefill_kv_sharding = serving_sharding(
                self.mesh, "serving/prefill_kv", registry=self.registry)

        dtype = _cache_dtype(params)
        self.pool = KVCachePool(self.n_layers, cfg.max_slots, self.n_heads,
                                self.max_seq_len, self.head_dim, dtype=dtype,
                                kv_cache_dtype=cfg.kv_cache_dtype,
                                page_tokens=cfg.kv_page_tokens,
                                pool_tokens=cfg.kv_pool_tokens,
                                mesh=self.mesh, registry=self.registry)
        # _qmode: storage<->compute conversion the decode programs need.
        # "fp32" stores the compute dtype directly, and "bf16" on a bf16
        # checkpoint is ALSO storage==compute — both take the plain
        # (bitwise) programs; only a real narrowing pays the quant path.
        if cfg.kv_cache_dtype == "int8":
            self._qmode = "int8"
        elif jnp.dtype(self.pool.k.dtype) != jnp.dtype(dtype):
            self._qmode = "bf16"
        else:
            self._qmode = None
        self._spec_k = int(cfg.speculative_k)
        # degraded-mode ladder: armed by configure_degrade() (from_config
        # wires the fleet.degrade block) or lazily by set_degrade_rung()
        # (the replica "degrade" socket op / the autoscaler's push).
        # _degrade_rung is the hot-path mirror — one int read per check.
        self._degrade = None
        self._degrade_rung = 0
        self.scheduler = ContinuousBatchingScheduler(
            max_queue=cfg.max_queue, buckets=buckets,
            default_max_new_tokens=cfg.default_max_new_tokens,
            request_timeout_s=cfg.request_timeout_s)
        self.metrics = ServingMetrics(monitor)
        self.metrics.record_kv_pool_bytes(self.pool.nbytes())
        if injector is None and cfg.fault_injection:
            injector = ServingFaultInjector(cfg.fault_injection)
        self.injector = injector
        self.prefix_cache = (
            PrefixKVCache(max(1, int(cfg.prefix_cache_mb * 2 ** 20)),
                          spill_budget_bytes=int(
                              cfg.prefix_spill_mb * 2 ** 20),
                          spill_dir=cfg.prefix_spill_dir,
                          listener=self._on_spill_event)
            if cfg.prefix_cache_mb > 0 else None)
        if (self.prefix_cache is not None
                and self.prefix_cache.spill is not None):
            # torn-write fault surface: consulted per disk write, False
            # while unarmed — re-wired live if an injector arrives later
            # over the replica inject op (same object, arm-time only)
            if self.injector is not None:
                self.prefix_cache.spill.torn_write_hook = (
                    self.injector.torn_spill_write)
            self.metrics.set_spill_sources(
                spill_stats_fn=self.prefix_cache.spill.stats,
                host_rss_mb_fn=self._host_rss_mb)
        elif cfg.host_mem_watermark_mb > 0:
            self.metrics.set_spill_sources(host_rss_mb_fn=self._host_rss_mb)
        # host-memory watchdog: one check per step(); sheds the spill
        # tier, pauses prefix inserts, then climbs the degrade ladder
        self._mem_guard = (
            MemoryPressureGuard(cfg.host_mem_watermark_mb,
                                cache=self.prefix_cache,
                                ladder=_EngineLadderShim(self),
                                read_rss_mb=self._guard_rss_mb,
                                listener=self._on_mem_pressure_level)
            if cfg.host_mem_watermark_mb > 0 else None)
        # edge-trigger memo for the serving/spill_corrupt instant
        self._spill_corrupt_seen = 0
        # pool-pressure relief: one evict+shed attempt per exhaustion
        # event (satellite: requeue-after-relief instead of plain requeue)
        self._pool_relief_attempts = 0

        self._active = {}                                   # slot -> Request
        self._lane_tokens = np.zeros(cfg.max_slots, np.int32)
        self._lane_active = np.zeros(cfg.max_slots, bool)
        # which active lanes run the windowed (sparse) decode program;
        # the complement runs the full-gather (dense/flash) program.
        # Each program masks with its own class vector, so threading the
        # shared token/position/pool operands through both leaves every
        # lane with exactly its own class's result.
        self._lane_impl_window = np.zeros(cfg.max_slots, bool)
        # which active lanes route through the kernel tier: pallas_decode
        # lanes are (kernel & ~window), pallas_sparse (kernel & window) —
        # four lane classes total, each masked by its own class vector
        self._lane_impl_kernel = np.zeros(cfg.max_slots, bool)
        # device-resident decode operands: uploaded ONLY on lane churn
        # (_lane_dirty), advanced in-jit otherwise — steady-state decode
        # performs exactly one explicit transfer per step (the EOS read)
        self._dev_tokens = None
        self._dev_positions = None
        self._dev_active = None
        self._dev_active_win = None
        self._dev_active_kfull = None
        self._dev_active_kwin = None
        self._dev_page_tables = None
        self._lane_dirty = True
        # speculative state: per-lane token-by-position history feeding
        # the n-gram drafter (host mirror for churn re-upload, device
        # buffer advanced in-jit between churns) and the corrupt_draft
        # noise operand (all-zeros = bitwise no-op)
        self._lane_history = (
            np.zeros((cfg.max_slots, self.max_seq_len), np.int32)
            if self._spec_k > 0 else None)
        self._dev_history = None
        self._dev_noise = None
        self._noise_armed = False
        if sentinel_config is not None and sentinel_config.enabled:
            budget = sentinel_config.compile_budget
            if self._spec_k > 0:
                decode_prog = (_spec_step_quant_jit if self._qmode
                               else _spec_step_jit)
            else:
                decode_prog = (_decode_step_quant_jit if self._qmode
                               else _decode_step_jit)
            self.decode_sentinel = CompileSentinel(
                decode_prog, budget, name="serving decode step")
            self.prefill_sentinel = CompileSentinel(
                _prefill_batch_jit, budget, name="serving batched prefill")
            # backend programs get their own pins only when armed — an
            # all-dense config keeps the exact legacy sentinel set
            self.decode_window_sentinel = (
                CompileSentinel(
                    _spec_step_window_jit if self._spec_k > 0
                    else _decode_step_window_jit,
                    budget, name="serving window decode step")
                if self._any_window else None)
            self.prefill_window_sentinel = (
                CompileSentinel(_prefill_batch_window_jit, budget,
                                name="serving window prefill")
                if self._any_window else None)
            self.prefill_flash_sentinel = (
                CompileSentinel(_prefill_batch_flash_jit, budget,
                                name="serving flash prefill")
                if self._any_flash else None)
            # kernel-class decode pins: pallas_decode lanes always run a
            # kernel-tier program; pallas_sparse lanes run the kernel spec
            # step under speculation but the (kernel-static) window
            # program otherwise, so non-spec kwin pins that instead
            self.decode_kernel_sentinel = (
                CompileSentinel(
                    _spec_step_kernel_jit if self._spec_k > 0
                    else _decode_step_kernel_jit,
                    budget, name="serving kernel decode step")
                if (self._any_kfull
                    or (self._any_kwin and self._spec_k > 0)) else None)
            if (self._any_kwin and self._spec_k == 0
                    and self.decode_window_sentinel is None):
                self.decode_window_sentinel = CompileSentinel(
                    _decode_step_window_jit, budget,
                    name="serving window decode step")
            self.prefill_kernel_sentinel = (
                CompileSentinel(_prefill_batch_kernel_jit, budget,
                                name="serving kernel prefill")
                if self._any_kfull else None)
            self.prefill_kernel_window_sentinel = (
                CompileSentinel(_prefill_batch_kernel_window_jit, budget,
                                name="serving kernel window prefill")
                if self._any_kwin else None)
            self._transfer_guard = bool(sentinel_config.transfer_guard)
        else:
            self.decode_sentinel = None
            self.prefill_sentinel = None
            self.decode_window_sentinel = None
            self.prefill_window_sentinel = None
            self.prefill_flash_sentinel = None
            self.decode_kernel_sentinel = None
            self.prefill_kernel_sentinel = None
            self.prefill_kernel_window_sentinel = None
            self._transfer_guard = False
        # batched prefill always runs at the pool width: the batch dim is
        # STATIC, so any admission-group size shares one program per bucket
        self._prefill_batch = cfg.max_slots
        self._chunking = None               # at most one chunked prefill
        self._step_count = 0
        self._busy_steps = 0                # steps that had active lanes
        self._loop_thread = None
        self._stop = threading.Event()
        self._draining = False              # planned restart: admit nothing
        # the pool has no lock: every mutation happens on the serving-loop
        # thread. Handoff pool ops (claim/install/free/resume) arrive on
        # replica connection threads and are marshaled here, drained at
        # the top of step().
        self._loop_ops = _queue_mod.Queue()

        # telemetry: an explicit block arms the process-global tracer and
        # registry; an absent block leaves them untouched. Hot-path guard
        # is one attribute read (self._tracer.enabled). rank/role become
        # the trace's process identity (the fleet collector's merge key);
        # rank=None falls back to the launcher-exported RANK env var.
        telemetry.configure_from_config(telemetry_config, rank=rank,
                                        role="serve")
        self._tracer = telemetry.get_tracer()
        self._trace_file = None
        self.telemetry_server = None
        self.slo = None
        if telemetry_config is not None and telemetry_config.enabled:
            self._trace_file = telemetry_config.trace_file
            self.metrics.export_to(telemetry.get_registry())
            if (self.prefix_cache is not None
                    and self.prefix_cache.spill is not None):
                telemetry.get_registry().gauge_fn(
                    "Serving/SpillTier", self.prefix_cache.spill.stats,
                    help="host-RAM/disk spill tier occupancy")
            if self._mem_guard is not None or cfg.host_mem_watermark_mb > 0:
                telemetry.get_registry().gauge_fn(
                    "Serving/HostRssMb", self._host_rss_mb,
                    help="process resident set size (MiB)")
            if self._kernel_impl:
                # per-kernel selected-backend gauges next to the
                # Kernels/<name>/calls counters at /metrics
                kernels.get_registry().export_gauges(telemetry.get_registry())
            # explicit http_port wins; a supervised worker with a null
            # port inherits DSTPU_TELEMETRY_PORT so the fleet collector
            # can scrape it without per-worker config edits
            http_port = telemetry.resolve_http_port(telemetry_config)
            if http_port is not None:
                self.telemetry_server = self._build_telemetry_server(
                    http_port)
            self.slo = telemetry.SloEngine.from_config(
                telemetry_config, tracer=self._tracer,
                registry=telemetry.get_registry())
            if self.slo is not None and self.telemetry_server is not None:
                self.slo.attach(self.telemetry_server)

    def _build_telemetry_server(self, port):
        srv = telemetry.TelemetryServer(
            registry=telemetry.get_registry(), tracer=self._tracer, port=port)
        srv.add_snapshot_provider("serving", self.metrics.snapshot)
        srv.add_snapshot_provider("kv_pool", self.occupancy)
        srv.add_snapshot_provider("prefix_cache", self.prefix_stats)
        srv.add_snapshot_provider("memtier", self.memtier_stats)
        srv.add_snapshot_provider("kernels", kernels.registry_snapshot)
        srv.add_health_provider("serving_loop", self._loop_health)
        return srv.start()

    def _loop_health(self):
        """Healthy unless a background loop was started and then died
        (synchronous step()/drain() driving is always healthy)."""
        t = self._loop_thread
        return {"healthy": t is None or t.is_alive(),
                "background_loop": t is not None,
                "steps": self._step_count,
                "active_requests": len(self._active),
                "queue_depth": self.scheduler.queue_depth(),
                "draining": self._draining,
                "degrade_rung": self._degrade_rung}

    # -- degraded-mode ladder -------------------------------------------
    def configure_degrade(self, degrade_config):
        """Arm the degraded-mode ladder (fleet.degrade block or a
        DegradeLadder). Rung 1 disables speculation (k -> 0 — safe
        mid-flight: emitted tokens always come from the verify oracle,
        so the classic program continues the exact same sequence);
        rung 2 additionally pauses prefix-cache inserts and halves the
        admission queue budget. Rung 3 is router-side (class shedding).
        """
        if isinstance(degrade_config, DegradeLadder):
            self._degrade = degrade_config
            self._degrade._on_change = self._on_degrade_change
        else:
            self._degrade = DegradeLadder(
                degrade_config, on_change=self._on_degrade_change,
                name="engine")
        self._degrade_rung = self._degrade.rung
        self._degrade.export_gauges(telemetry.get_registry())
        return self._degrade

    def set_degrade_rung(self, rung, reason="forced"):
        """External rung override (the replica's ``degrade`` socket op,
        the autoscaler's no-headroom push). Arms a default ladder when
        none is configured, so the op always works."""
        if self._degrade is None:
            self.configure_degrade(None)
        return self._degrade.set_rung(rung, reason=reason)

    @property
    def degrade_rung(self):
        return self._degrade_rung

    def _effective_spec_k(self):
        """Speculation knob after the ladder: rung >= 1 runs the classic
        one-token decode program (which always exists — it IS the k=0
        path), so toggling never recompiles anything new per rung flip."""
        return 0 if self._degrade_rung >= 1 else self._spec_k

    def _on_degrade_change(self, old, new, reason):
        self._degrade_rung = new
        # crossing the speculation boundary switches decode programs;
        # re-upload lane state so the program about to run sees fresh
        # operands (spec needs the host history mirror, which the classic
        # path keeps warm — see step()).
        if self._spec_k > 0 and (old >= 1) != (new >= 1):
            self._lane_dirty = True

    def _degrade_queue_budget(self):
        """Effective admission-queue budget under the ladder: rung >= 2
        halves it (earlier backpressure, less queued work to carry)."""
        if self._degrade_rung >= 2:
            return max(1, self.config.max_queue // 2)
        return self.config.max_queue

    # -- memory tiering & pressure (spill tier + guard) ------------------
    def _host_rss_mb(self):
        """Current host RSS (MiB) — the snapshot/gauge source."""
        return read_host_rss_mb()

    def _guard_rss_mb(self):
        """RSS reader the MemoryPressureGuard ticks on: the
        host_mem_pressure fault arm substitutes a fake over-watermark
        value while armed, so chaos drives the escalation path without
        actually ballooning the process."""
        if (self.injector is not None
                and self.injector.host_mem_pressure_active()):
            return self.config.host_mem_watermark_mb * 4.0
        return read_host_rss_mb()

    def _on_spill_event(self, event):
        """Spill-tier listener (fires under the cache lock — metrics and
        tracer only, never back into the cache)."""
        if event == "spill_hit":
            self.metrics.record_spill_lookup(True)
        elif event == "spill_miss":
            self.metrics.record_spill_lookup(False)
        elif event == "spill_corrupt":
            self.metrics.record_spill_corrupt()
            tracer = getattr(self, "_tracer", None)
            if tracer is not None and tracer.enabled:
                tracer.instant("serving/spill_corrupt", args={
                    "total": self.metrics.spill_corrupt_total})

    def _on_mem_pressure_level(self, level, rss_mb):
        """Edge-triggered on every MemoryPressureGuard level change."""
        tracer = getattr(self, "_tracer", None)
        if tracer is not None and tracer.enabled:
            tracer.instant("serving/mem_pressure", args={
                "level": level,
                "level_name": MemoryPressureGuard.LEVELS[level],
                "rss_mb": None if rss_mb is None else round(rss_mb, 1)})

    def _relieve_memory_pressure(self):
        """One-shot relief when admission hits pool/page exhaustion:
        evict every unreferenced live prefix entry (demoting to spill)
        and shed the spill tier, so transient pressure self-heals before
        the request round-trips through requeue backpressure. Returns
        True when anything was actually released."""
        if self.prefix_cache is None:
            return False
        self._pool_relief_attempts += 1
        evicted = self.prefix_cache.evict_unreferenced()
        shed = self.prefix_cache.shed_spill()
        return bool(evicted or shed)

    def memtier_stats(self):
        """Spill-tier + pressure-guard snapshot (telemetry provider)."""
        out = {"pool_relief_attempts": self._pool_relief_attempts}
        if self.prefix_cache is not None:
            out["prefix_cache"] = self.prefix_cache.stats()
        if self._mem_guard is not None:
            out["mem_guard"] = self._mem_guard.stats()
        return out

    @classmethod
    def from_config(cls, params, model_config, ds_config, rank=0,
                    injector=None):
        """Build from a ds_config (dict or DeepSpeedConfig): the validated
        ``serving`` block plus the shared monitor construction path."""
        from deepspeed_tpu.monitor import monitor_from_config
        from deepspeed_tpu.runtime.config import DeepSpeedConfig

        if isinstance(ds_config, dict):
            ds_config = DeepSpeedConfig(ds_config, world_size=1)
        serving_cfg = ds_config.serving_config
        parallel = getattr(ds_config, "parallel_config", None)
        if parallel is not None and parallel.enabled:
            # the validated `parallel` block arms tensor parallelism for
            # the serving engine; replace() keeps the frozen-ish config
            # object semantics (serving_cfg may be shared across engines)
            import dataclasses
            serving_cfg = dataclasses.replace(
                serving_cfg, mesh_shape=parallel.mesh_shape,
                partition_rules=parallel.partition_rules,
                replicate_unmatched=parallel.replicate_unmatched)
        eng = cls(params, model_config,
                  serving_config=serving_cfg,
                  monitor=monitor_from_config(ds_config, rank),
                  injector=injector,
                  sentinel_config=ds_config.sentinel_config,
                  telemetry_config=ds_config.telemetry_config,
                  rank=rank)
        fleet = getattr(ds_config, "fleet_config", None)
        if fleet is not None and fleet.enabled and fleet.degrade.enabled:
            eng.configure_degrade(fleet.degrade)
        return eng

    # -- request intake -------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens=None, eos_token_id=None,
               timeout_s=None, stream_cb=None, age_s=0.0):
        """Queue one request; returns its ``ServingFuture``.

        ``prompt_ids`` is a 1-D token sequence. Raises ``QueueFullError``
        when the admission queue is at capacity (backpressure),
        ``EngineDrainingError`` during a planned drain, and ``ValueError``
        for requests that can never fit. ``stream_cb`` (optional) is
        called as ``stream_cb(request_id, token)`` for every generated
        token, including the first. ``age_s`` backdates the enqueue
        timestamp by that many seconds — a re-routed or requeued request
        keeps its original deadline/TTFT clock instead of resetting it."""
        if self._draining:
            raise EngineDrainingError(
                "engine is draining for a planned restart; "
                "route this request to another replica")
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if len(prompt) < 1:
            raise ValueError("prompt must contain at least one token")
        if max_new_tokens is None:
            max_new_tokens = self.config.default_max_new_tokens
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        bucket_for(len(prompt), self.scheduler.buckets)  # raises if too long
        total = len(prompt) + int(max_new_tokens)
        if total > self.max_seq_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"= {total} exceeds serving max_seq_len={self.max_seq_len}")
        if eos_token_id is not None and not (
                0 <= int(eos_token_id) < self.model_config.vocab_size):
            raise ValueError(
                f"eos_token_id={eos_token_id} outside vocab "
                f"[0, {self.model_config.vocab_size})")
        if self._degrade_rung >= 2:
            # budget_shrink rung: earlier backpressure at half the queue
            budget = self._degrade_queue_budget()
            if self.scheduler.queue_depth() >= budget:
                raise QueueFullError(
                    f"admission queue shrunk to {budget} at degrade rung "
                    f"{self._degrade_rung}")
        submitted_at = (time.monotonic() - float(age_s)
                        if age_s and age_s > 0 else None)
        req = self.scheduler.submit(
            prompt, max_new_tokens=int(max_new_tokens),
            eos_token_id=None if eos_token_id is None else int(eos_token_id),
            timeout_s=timeout_s, stream_cb=stream_cb,
            submitted_at=submitted_at)
        return req.future

    # -- disaggregated prefill/decode handoff ---------------------------
    def submit_handoff(self, prompt_ids, reserve_new_tokens,
                       eos_token_id=None, timeout_s=None, stream_cb=None,
                       age_s=0.0):
        """Prefill-only submit: run prefill for ``prompt_ids``, emit the
        first token, then retire immediately (``max_new_tokens=1``) while
        exporting the lane's KV pages as ``req.export_payload`` for a
        decode-worker handoff.

        ``reserve_new_tokens`` is the ORIGINAL request's generation
        budget — the page allocation spans the full request so the
        exported layout (and int8 scales, which quantize over the whole
        allocated span) is bit-identical to what a mixed-mode admission
        would have produced. Returns the Request (the caller reads
        ``export_payload`` after ``future.result()``)."""
        if self._draining:
            raise EngineDrainingError(
                "engine is draining for a planned restart; "
                "route this request to another replica")
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if len(prompt) < 1:
            raise ValueError("prompt must contain at least one token")
        reserve = int(reserve_new_tokens)
        if reserve < 1:
            raise ValueError(
                f"reserve_new_tokens must be >= 1, got {reserve}")
        bucket_for(len(prompt), self.scheduler.buckets)
        total = len(prompt) + reserve
        if total > self.max_seq_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + reserve_new_tokens ({reserve}) "
                f"= {total} exceeds serving max_seq_len={self.max_seq_len}")
        if eos_token_id is not None and not (
                0 <= int(eos_token_id) < self.model_config.vocab_size):
            raise ValueError(
                f"eos_token_id={eos_token_id} outside vocab "
                f"[0, {self.model_config.vocab_size})")
        if self._degrade_rung >= 2:
            budget = self._degrade_queue_budget()
            if self.scheduler.queue_depth() >= budget:
                raise QueueFullError(
                    f"admission queue shrunk to {budget} at degrade rung "
                    f"{self._degrade_rung}")
        submitted_at = (time.monotonic() - float(age_s)
                        if age_s and age_s > 0 else None)
        req = self.scheduler.adopt(
            prompt, max_new_tokens=1,
            eos_token_id=None if eos_token_id is None else int(eos_token_id),
            timeout_s=timeout_s, stream_cb=stream_cb,
            submitted_at=submitted_at)
        # flags set BEFORE the request becomes loop-visible
        req.handoff_export = True
        req.alloc_tokens_override = min(total, self.max_seq_len)
        self.scheduler.enqueue(req)
        return req

    def handoff_claim(self, n_tokens):
        """Decode-side phase 1: allocate a pool slot sized for the full
        request span. Raises PoolExhaustedError under pressure. Mirrors
        ``_alloc_tokens``: an armed injector forces full-lane claims, so
        the claim always holds at least as many pages as the (also
        full-lane) prefill-side export ships."""
        n = None if self.injector is not None else int(n_tokens)
        return self._run_on_loop(lambda: self.pool.allocate(n))

    def handoff_install(self, slot, meta, frames, handoff_key=None):
        """Decode-side phase 2: install transferred pages into the
        claimed slot. Returns False on an idempotent duplicate."""
        def _do():
            fresh = self.pool.install_raw(slot, meta, frames,
                                          handoff_key=handoff_key)
            self.metrics.record_handoff("install" if fresh else "dup_install")
            return fresh
        return self._run_on_loop(_do)

    def handoff_release(self, slot):
        """Free a claimed/installed slot (orphan reap, failed resume)."""
        return self._run_on_loop(lambda: self.pool.free(slot))

    def resume_handoff(self, slot, prompt_ids, first_token, max_new_tokens,
                       eos_token_id=None, timeout_s=None, stream_cb=None,
                       age_s=0.0):
        """Activate a lane whose KV pages were installed by a handoff and
        continue decoding exactly where prefill left off. The first
        generated token was already delivered by the prefill worker, so
        it is recorded (``emitted=1``, appended to the future) but NOT
        re-streamed through ``stream_cb``. Returns the Request."""
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        submitted_at = (time.monotonic() - float(age_s)
                        if age_s and age_s > 0 else None)
        req = self.scheduler.adopt(
            prompt, max_new_tokens=int(max_new_tokens),
            eos_token_id=None if eos_token_id is None else int(eos_token_id),
            timeout_s=timeout_s, stream_cb=stream_cb,
            submitted_at=submitted_at)

        def _do():
            req.attn_impl = self._impl_for_len(len(prompt))
            now = time.monotonic()
            req.first_token_time = now
            self._activate(req, slot, int(first_token), emit=False)
            req.future._append(int(first_token))
            req.emitted = 1
            self.metrics.record_handoff("resume")
            # defensively retire right away if the first token already
            # ended the request (the router short-circuits these, but a
            # direct caller may not)
            self._maybe_retire(req, int(first_token), now)
            return None
        self._run_on_loop(_do)
        return req

    # -- the serving loop ----------------------------------------------
    def step(self):
        """One scheduler iteration: expire, advance any chunked prefill,
        admit (batched per bucket), one batched decode step, retire.
        Returns an activity dict (all zeros = idle)."""
        now = time.monotonic()
        stats = {"admitted": 0, "decoded": 0, "retired": 0,
                 "prefill_chunks": 0}

        self._drain_loop_ops()

        for req in self.scheduler.pop_expired(now):
            self._finish_timeout(req, phase="queued")
            stats["retired"] += 1

        # one chunk per step: a long prompt makes progress without ever
        # stalling the in-flight lanes' inter-token latency
        if self._chunking is not None:
            self._advance_chunk(stats)

        self._admit_from_queue(stats)

        if self.injector is not None:
            self.injector.maybe_evict_prefix(self._step_count,
                                             self.prefix_cache)
            self.injector.maybe_corrupt_spill(self._step_count,
                                              self.prefix_cache)
        if self._mem_guard is not None:
            self._mem_guard.check()
        if self._active:
            # busy steps (not raw _step_count, which idles forward between
            # requests in background mode): the kill_replica arm's at_step
            # must mean "the Nth decode step that had work" to be
            # reproducible against a live server
            self._busy_steps += 1
            if self.injector is not None:
                self.injector.maybe_slow_decode(self._step_count)
                self.injector.maybe_kill_replica(self._busy_steps)
            # span args (request ids) are built ONLY when tracing is armed:
            # disabled-mode cost is this one attribute read. The dict is
            # kept so the spec path can fill in `accepted` post-step (the
            # tracer renders args lazily, at write time).
            span_args = None
            if self._tracer.enabled:
                span_args = {
                    "request_ids": [r.id for r in self._active.values()],
                    "active": len(self._active), "accepted": 0}
                dspan = self._tracer.span("serving/decode_step",
                                          cat="serving", args=span_args)
            else:
                dspan = telemetry.NULL_SPAN
            dspan.__enter__()
            t0 = time.monotonic()
            if self._lane_dirty:
                self._upload_lane_state()
            guard = transfer_free() if self._transfer_guard else nullcontext()
            # host-side np masks: np.bool_ drives the dispatch branches
            # directly (a bool() cast here reads as a device sync to JL002)
            lw, lk = self._lane_impl_window, self._lane_impl_kernel
            full_mask = self._lane_active & ~lw & ~lk
            win_mask = self._lane_active & lw & ~lk
            kfull_mask = self._lane_active & ~lw & lk
            kwin_mask = self._lane_active & lw & lk
            full_any = np.any(full_mask)
            win_any = np.any(win_mask)
            kfull_any = np.any(kfull_mask)
            kwin_any = np.any(kwin_mask)
            if self._effective_spec_k() > 0:
                self._maybe_update_noise()
                with guard:
                    got = []           # (class mask, oracle, accepted)
                    if full_any:
                        (self._dev_tokens, self._dev_positions, self.pool.k,
                         self.pool.v, self._dev_history, oracle_dev,
                         accepted_dev) = self._call_spec_step()
                        got.append((full_mask, oracle_dev, accepted_dev))
                    if win_any:
                        (self._dev_tokens, self._dev_positions, self.pool.k,
                         self.pool.v, self._dev_history, oracle_dev,
                         accepted_dev) = self._call_spec_step_window()
                        got.append((win_mask, oracle_dev, accepted_dev))
                    if kfull_any:
                        (self._dev_tokens, self._dev_positions, self.pool.k,
                         self.pool.v, self._dev_history, oracle_dev,
                         accepted_dev) = self._call_spec_step_kernel(
                            "pallas_decode")
                        got.append((kfull_mask, oracle_dev, accepted_dev))
                    if kwin_any:
                        (self._dev_tokens, self._dev_positions, self.pool.k,
                         self.pool.v, self._dev_history, oracle_dev,
                         accepted_dev) = self._call_spec_step_kernel(
                            "pallas_sparse")
                        got.append((kwin_mask, oracle_dev, accepted_dev))
                self._check_decode_sentinels()
                # the step's single deliberate sync: the emit loop needs
                # the oracle tokens and per-lane acceptance counts (one
                # tuple read even when several class programs ran)
                host = jax.device_get(tuple((o, a) for _, o, a in got))  # jaxlint: disable=JL002(one explicit host read per step)
                oracle, accepted = host[0]
                if len(got) > 1:
                    # overlay each later class's lanes onto the first's
                    # result (every active lane is in exactly one class);
                    # device_get already landed host numpy — no copies here
                    oracle = oracle.copy()
                    accepted = accepted.copy()
                    for (mask, _, _), (o, a) in zip(got[1:], host[1:]):
                        oracle[mask] = o[mask]
                        accepted[mask] = a[mask]
                step_s = time.monotonic() - t0
                oracle = oracle.tolist()        # host numpy -> python ints
                accepted = accepted.tolist()
                acc_total = sum(accepted[s] for s in self._active)
                if span_args is not None:
                    span_args["accepted"] = acc_total
                dspan.__exit__(None, None, None)
                now = time.monotonic()
                n_active = len(self._active)
                decoded_before = stats["decoded"]
                for slot in list(self._active):
                    req = self._active[slot]
                    acc = accepted[slot]
                    # mirror the device lane state: the pending token is
                    # now the oracle's post-acceptance token
                    self._lane_tokens[slot] = oracle[slot][acc]
                    base = self.pool.positions[slot]    # host-side counter
                    for j in range(acc + 1):
                        tok = oracle[slot][j]
                        self.pool.advance(slot)
                        if base + 1 + j < self.max_seq_len:
                            self._lane_history[slot, base + 1 + j] = tok
                        self._emit(req, tok)
                        stats["decoded"] += 1
                        if self._maybe_retire(req, tok, now):
                            # EOS/length/deadline truncates the step's
                            # remaining oracle tokens — exactly where a
                            # non-speculative server would have stopped
                            stats["retired"] += 1
                            break
                occ = self.pool.occupancy()
                self.metrics.record_step(
                    queue_depth=self.scheduler.queue_depth(),
                    active_slots=n_active, max_slots=self.pool.max_slots,
                    tokens_this_step=stats["decoded"] - decoded_before,
                    step_s=step_s, accepted_tokens=acc_total,
                    proposed_tokens=self._spec_k * n_active,
                    pages_in_use=occ["pages_in_use"],
                    page_fragmentation=occ["page_fragmentation"])
            else:
                with guard:
                    if full_any:
                        if self._qmode is not None:
                            (self._dev_tokens, self._dev_positions,
                             self.pool.k, self.pool.v) = \
                                _decode_step_quant_jit(
                                    self.params, self.pool.k, self.pool.v,
                                    self.pool.k_scale, self.pool.v_scale,
                                    self._dev_page_tables, self._dev_tokens,
                                    self._dev_positions, self._dev_active,
                                    n_heads=self.n_heads, qmode=self._qmode)
                        else:
                            (self._dev_tokens, self._dev_positions,
                             self.pool.k, self.pool.v) = _decode_step_jit(
                                self.params, self.pool.k, self.pool.v,
                                self._dev_page_tables, self._dev_tokens,
                                self._dev_positions, self._dev_active,
                                n_heads=self.n_heads)
                    if win_any:
                        (self._dev_tokens, self._dev_positions, self.pool.k,
                         self.pool.v) = _decode_step_window_jit(
                            self.params, self.pool.k, self.pool.v,
                            self.pool.k_scale, self.pool.v_scale,
                            self._dev_page_tables, self._dev_tokens,
                            self._dev_positions, self._dev_active_win,
                            n_heads=self.n_heads,
                            page_tokens=self.pool.page_tokens,
                            qmode=self._qmode)
                    if kfull_any:
                        kernels.record_call(
                            "decode_attention",
                            self._kernel_impl["pallas_decode"])
                        (self._dev_tokens, self._dev_positions, self.pool.k,
                         self.pool.v) = _decode_step_kernel_jit(
                            self.params, self.pool.k, self.pool.v,
                            self.pool.k_scale, self.pool.v_scale,
                            self._dev_page_tables, self._dev_tokens,
                            self._dev_positions, self._dev_active_kfull,
                            n_heads=self.n_heads,
                            page_tokens=self.pool.page_tokens,
                            qmode=self._qmode,
                            kernel_impl=self._kernel_impl["pallas_decode"],
                            kernel_interpret=self._kernel_interpret[
                                "pallas_decode"])
                    if kwin_any:
                        kernels.record_call(
                            "sparse_attention",
                            self._kernel_impl["pallas_sparse"])
                        (self._dev_tokens, self._dev_positions, self.pool.k,
                         self.pool.v) = _decode_step_window_jit(
                            self.params, self.pool.k, self.pool.v,
                            self.pool.k_scale, self.pool.v_scale,
                            self._dev_page_tables, self._dev_tokens,
                            self._dev_positions, self._dev_active_kwin,
                            n_heads=self.n_heads,
                            page_tokens=self.pool.page_tokens,
                            qmode=self._qmode,
                            kernel_impl=self._kernel_impl["pallas_sparse"],
                            kernel_interpret=self._kernel_interpret[
                                "pallas_sparse"])
                self._check_decode_sentinels()
                # the step's single deliberate sync: EOS checks need the
                # tokens
                host_tokens = jax.device_get(self._dev_tokens)  # jaxlint: disable=JL002(one explicit host read per step)
                step_s = time.monotonic() - t0
                dspan.__exit__(None, None, None)
                self._lane_tokens = host_tokens.copy()
                toks = host_tokens.tolist()
                now = time.monotonic()
                n_active = len(self._active)
                for slot in list(self._active):
                    req = self._active[slot]
                    base = self.pool.positions[slot]
                    self.pool.advance(slot)
                    if (self._lane_history is not None
                            and base + 1 < self.max_seq_len):
                        # speculation is configured but ladder-disabled:
                        # keep the host history mirror warm so recovery
                        # back to the spec program re-uploads fresh
                        # drafter context (stale history would only cost
                        # accept rate, but fresh is free here)
                        self._lane_history[slot, base + 1] = toks[slot]
                    self._emit(req, toks[slot])
                    stats["decoded"] += 1
                    stats["retired"] += self._maybe_retire(req, toks[slot],
                                                           now)
                occ = self.pool.occupancy()
                self.metrics.record_step(
                    queue_depth=self.scheduler.queue_depth(),
                    active_slots=n_active, max_slots=self.pool.max_slots,
                    tokens_this_step=n_active, step_s=step_s,
                    pages_in_use=occ["pages_in_use"],
                    page_fragmentation=occ["page_fragmentation"])
        self._step_count += 1
        if self._degrade is not None and self._degrade.config.enabled:
            # host-only pressure signal, evaluated once per step: a
            # sustained near-full admission queue climbs the ladder one
            # rung; sustained quiet walks it back down
            threshold = max(1, int(self._degrade.config.pressure_queue_frac
                                   * self.config.max_queue))
            self._degrade.update(self.scheduler.queue_depth() >= threshold)
        if self.slo is not None:
            # host-only snapshot + pushed gauges; under policy="fail" a
            # firing rule raises SloViolationError out of step()
            self.slo.evaluate(self._slo_values())
        return stats

    def _slo_values(self):
        """SLO inputs: the live serving snapshot under ``Serving/*`` plus
        pushed registry metrics. Pull gauges are skipped — the snapshot is
        already here, and re-polling every callback each step would double
        the work for no fresher data."""
        vals = {k: v
                for k, v in telemetry.get_registry().as_dict(pulled=False).items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)}
        for k, v in self.metrics.snapshot().items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                vals[f"Serving/{k}"] = v
        return vals

    def _put_host(self, tree):
        """Sharding-aware host upload: on a mesh, commit to the
        registry's replicated lane-state sharding — a default-device
        put on a >1-device mesh would land on device 0 and force a
        reshard inside the next jitted step, breaking the
        ``transfer_free()`` steady-state contract."""
        if self._replicated_sharding is None:
            return jax.device_put(tree)
        return jax.device_put(tree, self._replicated_sharding)

    def _upload_lane_state(self):
        """Lane churn: ONE explicit upload of the lane vectors, both
        per-class active masks, the page tables, and the drafter history
        when speculation is armed; between churn events they live on
        device and never move. Page-table churn rides the same dirty
        flag lane churn already sets (allocate/free happen exactly
        there), so paging adds no extra steady-state transfers."""
        pos = np.ascontiguousarray(self.pool.positions, dtype=np.int32)
        lw, lk = self._lane_impl_window, self._lane_impl_kernel
        full = self._lane_active & ~lw & ~lk
        win = self._lane_active & lw & ~lk
        kfull = self._lane_active & ~lw & lk
        kwin = self._lane_active & lw & lk
        tables = np.ascontiguousarray(self.pool.page_tables)
        if self._spec_k > 0:
            (self._dev_tokens, self._dev_positions, self._dev_active,
             self._dev_active_win, self._dev_active_kfull,
             self._dev_active_kwin, self._dev_page_tables,
             self._dev_history) = self._put_host(
                (self._lane_tokens, pos, full, win, kfull, kwin, tables,
                 self._lane_history))
            if self._dev_noise is None:
                self._dev_noise = self._put_host(
                    np.zeros((self.pool.max_slots, self._spec_k), np.int32))
        else:
            (self._dev_tokens, self._dev_positions, self._dev_active,
             self._dev_active_win, self._dev_active_kfull,
             self._dev_active_kwin, self._dev_page_tables) = self._put_host(
                (self._lane_tokens, pos, full, win, kfull, kwin, tables))
        self._lane_dirty = False

    def _call_spec_step(self):
        """Dispatch the full-gather speculative step program (dense and
        flash lanes) for the pool's storage mode. Both return (tokens,
        positions, k, v, history, oracle, accepted)."""
        if self._qmode is not None:
            return _spec_step_quant_jit(
                self.params, self.pool.k, self.pool.v,
                self.pool.k_scale, self.pool.v_scale,
                self._dev_page_tables, self._dev_history,
                self._dev_tokens, self._dev_positions, self._dev_active,
                self._dev_noise, n_heads=self.n_heads, k=self._spec_k,
                qmode=self._qmode)
        return _spec_step_jit(  # jaxlint: disable=JL005(exclusive branch: the quant dispatch above never ran)
            self.params, self.pool.k, self.pool.v, self._dev_page_tables,
            self._dev_history, self._dev_tokens, self._dev_positions,
            self._dev_active, self._dev_noise, n_heads=self.n_heads,
            k=self._spec_k)

    def _call_spec_step_window(self):
        """Dispatch the windowed speculative step program (sparse lanes;
        one program handles every storage mode via the static qmode —
        scale operands are None unless int8)."""
        return _spec_step_window_jit(
            self.params, self.pool.k, self.pool.v,
            self.pool.k_scale, self.pool.v_scale, self._dev_page_tables,
            self._dev_history, self._dev_tokens, self._dev_positions,
            self._dev_active_win, self._dev_noise,
            n_heads=self.n_heads, k=self._spec_k,
            page_tokens=self.pool.page_tokens, qmode=self._qmode)

    def _call_spec_step_kernel(self, backend):
        """Dispatch the kernel-tier speculative step program for one lane
        class (``pallas_decode`` = kfull mask, ``pallas_sparse`` = kwin)
        with that backend's resolved registry statics."""
        kernels.record_call(kernels.kernel_for_backend(backend),
                            self._kernel_impl[backend])
        mask = (self._dev_active_kwin if backend == "pallas_sparse"
                else self._dev_active_kfull)
        return _spec_step_kernel_jit(
            self.params, self.pool.k, self.pool.v,
            self.pool.k_scale, self.pool.v_scale, self._dev_page_tables,
            self._dev_history, self._dev_tokens, self._dev_positions,
            mask, self._dev_noise, n_heads=self.n_heads, k=self._spec_k,
            page_tokens=self.pool.page_tokens, qmode=self._qmode,
            attn_backend=backend,
            kernel_impl=self._kernel_impl[backend],
            kernel_interpret=self._kernel_interpret[backend])

    def _check_decode_sentinels(self):
        """Post-dispatch budget asserts for every armed decode pin (the
        per-class programs share the step, so they share the check)."""
        for s in (self.decode_sentinel, self.decode_window_sentinel,
                  self.decode_kernel_sentinel):
            if s is not None:
                s.check()

    def _maybe_update_noise(self):
        """Swap the device-resident draft-noise operand when the
        corrupt_draft fault arm fires (and restore zeros after). The
        operand always exists with the same shape, so firing the fault
        can never recompile the step."""
        if self.injector is None:
            return
        noise = self.injector.corrupt_draft_noise(
            self._step_count, self._spec_k, self.model_config.vocab_size)
        if noise is not None:
            self._dev_noise = self._put_host(np.ascontiguousarray(
                np.broadcast_to(np.asarray(noise, np.int32),
                                (self.pool.max_slots, self._spec_k))))
            self._noise_armed = True
        elif self._noise_armed:
            self._dev_noise = self._put_host(
                np.zeros((self.pool.max_slots, self._spec_k), np.int32))
            self._noise_armed = False

    def drain(self, max_steps=None):
        """Step until no request is queued, prefilling, or in flight.
        ``max_steps`` bounds the loop (a deadline-less stuck request
        would otherwise spin forever under fault injection)."""
        steps = 0
        while self.pending():
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return steps

    def pending(self):
        """Requests still owed work: queued + chunking + in flight."""
        return (len(self._active) + (1 if self._chunking is not None else 0)
                + self.scheduler.queue_depth())

    def _put_prefill_kv(self, arr):
        """Host prefix-KV seed -> device, heads-sharded on a mesh (dims
        [L, B, nh, S, hd] split at nh like the pool) so prefill starts
        from the layout its outputs and the pool install already use."""
        if self._prefill_kv_sharding is None:
            return jnp.asarray(arr)
        return jax.device_put(np.asarray(arr), self._prefill_kv_sharding)

    def _zeros_prefill_kv(self, shape, dtype):
        if self._prefill_kv_sharding is None:
            return jnp.zeros(shape, dtype)
        return jnp.zeros(shape, dtype, device=self._prefill_kv_sharding)

    @property
    def draining(self):
        return self._draining

    def begin_drain(self):
        """Planned-restart drain: stop admitting (``submit`` raises
        ``EngineDrainingError``), keep stepping accepted work to
        completion. The SIGTERM path: a replica flips this, finishes its
        in-flight lanes, then exits ``EXIT_PREEMPTED`` so the supervisor
        restarts it without backoff while the router re-routes around
        it."""
        self._draining = True

    # -- loop-thread marshaling -----------------------------------------
    def _drain_loop_ops(self):
        """Run pool ops posted by other threads (handoff claim/install/
        free/resume) on the serving-loop thread, where all pool mutation
        belongs."""
        while True:
            try:
                fn, done, box = self._loop_ops.get_nowait()
            except _queue_mod.Empty:
                return
            try:
                box.append(("ok", fn()))
            except BaseException as exc:  # marshal, don't kill the loop
                box.append(("err", exc))
            finally:
                done.set()

    def _run_on_loop(self, fn, timeout_s=30.0):
        """Execute ``fn`` on the serving-loop thread and return its
        result (re-raising its exception here). Runs inline when no
        background loop is active or when already on the loop thread."""
        t = self._loop_thread
        if t is None or not t.is_alive() \
                or t is threading.current_thread():
            return fn()
        done = threading.Event()
        box = []
        self._loop_ops.put((fn, done, box))
        if not done.wait(timeout_s):
            raise TimeoutError(
                f"serving loop did not service a marshaled op within "
                f"{timeout_s}s (loop stalled?)")
        kind, val = box[0]
        if kind == "err":
            raise val
        return val

    # -- background mode ------------------------------------------------
    def start(self, idle_sleep_s=0.001):
        """Run the serving loop on a daemon thread until ``stop()``."""
        if self._loop_thread is not None:
            raise RuntimeError("serving loop already running")
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                busy = self.step()
                if not any(busy.values()) and not self._active:
                    time.sleep(idle_sleep_s)

        self._loop_thread = threading.Thread(
            target=loop, name="serving-loop", daemon=True)
        self._loop_thread.start()

    def stop(self, timeout_s=5.0):
        if self._loop_thread is None:
            return
        self._stop.set()
        self._loop_thread.join(timeout_s)
        self._loop_thread = None
        self._drain_loop_ops()   # release any waiter the loop left behind

    def close(self):
        self.stop()
        self.metrics.close()
        if self.telemetry_server is not None:
            self.telemetry_server.stop()
            self.telemetry_server = None
        if self._trace_file:
            self._tracer.write(self._trace_file)

    # -- admission ------------------------------------------------------
    def _admit_from_queue(self, stats):
        """Join-at-free-slot admission, batched per bucket: pop the FIFO
        head, gather every queued request sharing its (prefix-adjusted)
        bucket up to the free-slot count, and prefill them as ONE call.
        Long prompts divert to the chunked path (one at a time)."""
        if self._tracer.enabled and self.scheduler.queue_depth() > 0:
            with self._tracer.span(
                    "serving/admission", cat="serving",
                    args={"queue_depth": self.scheduler.queue_depth()}):
                self._admit_from_queue_now(stats)
        else:
            self._admit_from_queue_now(stats)

    def _impl_for_len(self, prompt_len):
        """Attention backend for a request, selected by its FULL prompt
        length's bucket (not the prefix-adjusted suffix bucket — the
        prefix lookup itself is backend-filtered, so selection must not
        depend on it)."""
        return self._impl_map.get(
            bucket_for(prompt_len, self.scheduler.buckets),
            self._impl_default)

    def _alloc_tokens(self, req):
        """Page budget claimed for a request at admission: the exact
        prompt + generation span (rounded up to whole pages by the
        allocator). Under fault injection, stuck/runaway lanes may
        decode past their natural length, so claim the full lane.

        A handoff-export request overrides the budget with the ORIGINAL
        request's full reserve: int8 install quantizes over the whole
        allocated span, so the exported pages must be laid out exactly
        as a mixed-mode admission of the original request would lay
        them out — bit-for-bit."""
        if self.injector is not None:
            return None
        override = getattr(req, "alloc_tokens_override", None)
        if override is not None:
            return int(override)
        return min(len(req.prompt) + req.max_new_tokens, self.max_seq_len)

    def _admit_from_queue_now(self, stats):
        while self.pool.free_slots > 0:
            head = self.scheduler.pop_next()
            if head is None:
                return
            if not self.pool.can_allocate(self._alloc_tokens(head)):
                # page-pool backpressure: release host-side ballast once
                # (unreferenced prefix entries demote to spill, spill
                # tier sheds) before parking the FIFO head — transient
                # memory pressure self-heals instead of round-tripping
                # through requeue backpressure
                if (not self._relieve_memory_pressure()
                        or not self.pool.can_allocate(
                            self._alloc_tokens(head))):
                    self.scheduler.requeue_front(head)
                    return
            if self._needs_chunking(head):
                if self._chunking is not None:
                    self.scheduler.requeue_front(head)   # chunk lane is busy
                    return
                if not self._start_chunked(head):
                    return                   # pages raced away (requeued)
                stats["admitted"] += 1
                continue
            bucket = bucket_for(self._suffix_len(head), self.scheduler.buckets)
            impl = self._impl_for_len(len(head.prompt))
            group = [head]
            room = min(self.pool.free_slots - 1, self._prefill_batch - 1)
            if room > 0:
                group += self.scheduler.pop_matching(
                    lambda r: (not self._needs_chunking(r)
                               and self._impl_for_len(len(r.prompt)) == impl
                               and bucket_for(self._suffix_len(r),
                                              self.scheduler.buckets)
                               == bucket),
                    room)
            admitted, retired = self._admit_batch(group, bucket, impl)
            stats["admitted"] += admitted
            stats["retired"] += retired
            if admitted < len(group):
                return                       # pages ran out mid-group

    def _admit_batch(self, group, bucket, impl):
        """Prefill ``group`` (same bucket AND attention backend) as one
        [MaxSlots, Sb] call and install each lane into its slot. Slots
        and pages are claimed FIRST: members the page pool cannot hold
        are requeued in FIFO order before any compute runs. Returns
        (admitted, retired-on-their-very-first-token) counts."""
        pspan = (self._tracer.span(
                     "serving/prefill_batch", cat="serving",
                     args={"request_ids": [r.id for r in group],
                           "bucket": bucket})
                 if self._tracer.enabled else telemetry.NULL_SPAN)
        pspan.__enter__()
        B, total = self._prefill_batch, self.max_seq_len
        pt = self.pool.page_tokens
        # the sparse prefills' blocked attention needs a page-multiple
        # chunk width; pad queries are invisible (outputs discarded,
        # their garbage KV is overwritten by decode before attendable)
        Sb = (_round_up(bucket, pt)
              if impl in ("sparse_xla", "pallas_sparse") else bucket)
        ids = np.zeros((B, Sb), np.int32)
        starts = np.zeros(B, np.int32)
        lens = np.ones(B, np.int32)        # dummy lanes: 1-token no-ops
        plan = []
        any_hit = False
        for req in group:
            try:
                slot = self.pool.allocate(self._alloc_tokens(req))
            except PoolExhaustedError:
                if not self._relieve_memory_pressure():
                    break
                try:        # one retry after shedding host-side ballast
                    slot = self.pool.allocate(self._alloc_tokens(req))
                except PoolExhaustedError:
                    break
            i = len(plan)
            req.attn_impl = impl
            reuse, entry = self._acquire_prefix(req)
            suffix = req.prompt[reuse:]
            ids[i, :len(suffix)] = suffix
            starts[i] = reuse
            lens[i] = len(req.prompt)
            plan.append((req, reuse, entry, slot))
            any_hit = any_hit or reuse > 0
            self.metrics.record_admission(bucket, len(req.prompt))
        for req in reversed(group[len(plan):]):
            self.scheduler.requeue_front(req)    # pages exhausted mid-group
        if not plan:
            pspan.__exit__(None, None, None)
            return 0, 0
        # prefill runs in the COMPUTE dtype regardless of pool storage:
        # the quantize happens once, at lane install
        shape = (self.n_layers, B, self.n_heads, total, self.head_dim)
        cdtype = self.pool.compute_dtype
        if any_hit:
            # seed hit lanes from host-resident prefix KV; one transfer
            init_k = np.zeros(shape, cdtype)
            init_v = np.zeros(shape, cdtype)
            for i, (req, reuse, entry, _slot) in enumerate(plan):
                if reuse > 0:
                    ek, ev = self._entry_prefix_kv(entry, reuse)
                    init_k[:, i, :, :reuse] = ek
                    init_v[:, i, :, :reuse] = ev
            init_k = self._put_prefill_kv(init_k)
            init_v = self._put_prefill_kv(init_v)
        else:
            init_k = self._zeros_prefill_kv(shape, cdtype)
            init_v = self._zeros_prefill_kv(shape, cdtype)

        t0 = time.monotonic()
        k, v, first = self._run_prefill(impl, init_k, init_v,
                                        self._put_host(ids),
                                        self._put_host(starts),
                                        self._put_host(lens))
        first_host = np.asarray(first)             # sync: TTFT endpoint
        prefill_s = time.monotonic() - t0
        self.metrics.record_prefill(
            tokens=sum(len(r.prompt) - re for r, re, _, _ in plan),
            reused_tokens=sum(re for _, re, _, _ in plan),
            requests=len(plan), prefill_s=prefill_s)

        now = time.monotonic()
        retired = 0
        for i, (req, reuse, entry, slot) in enumerate(plan):
            self._maybe_insert_prefix(req, reuse, k, v, lane=i)
            self.pool.install_lane(k, v, lane=i, slot=slot,
                                   position=len(req.prompt))
            req.prefix_entry = entry
            req.first_token_time = now
            self.metrics.record_first_token(now - req.submit_time)
            self._activate(req, slot, int(first_host[i]))
            retired += self._maybe_retire(req, int(first_host[i]), now)
        # settle the queued lane installs here so they are accounted to
        # admission, not silently absorbed into the next decode step's
        # measured latency
        self.pool.k.block_until_ready()
        pspan.__exit__(None, None, None)
        return len(plan), retired

    def _run_prefill(self, impl, init_k, init_v, ids, starts, lens):
        """Dispatch the per-backend batched prefill program (each with
        its own CompileSentinel pin when armed)."""
        if impl == "sparse_xla":
            out = _prefill_batch_window_jit(
                self.params, init_k, init_v, ids, starts, lens,
                n_heads=self.n_heads, page_tokens=self.pool.page_tokens)
            sentinel = self.prefill_window_sentinel
        elif impl == "pallas_decode":
            kernels.record_call("decode_attention",
                                self._kernel_impl["pallas_decode"])
            out = _prefill_batch_kernel_jit(
                self.params, init_k, init_v, ids, starts, lens,
                n_heads=self.n_heads, page_tokens=self.pool.page_tokens,
                kernel_impl=self._kernel_impl["pallas_decode"],
                kernel_interpret=self._kernel_interpret["pallas_decode"])
            sentinel = self.prefill_kernel_sentinel
        elif impl == "pallas_sparse":
            kernels.record_call("sparse_attention",
                                self._kernel_impl["pallas_sparse"])
            out = _prefill_batch_kernel_window_jit(
                self.params, init_k, init_v, ids, starts, lens,
                n_heads=self.n_heads, page_tokens=self.pool.page_tokens,
                kernel_impl=self._kernel_impl["pallas_sparse"],
                kernel_interpret=self._kernel_interpret["pallas_sparse"])
            sentinel = self.prefill_kernel_window_sentinel
        elif impl == "flash":
            out = _prefill_batch_flash_jit(
                self.params, init_k, init_v, ids, starts, lens,
                n_heads=self.n_heads, page_tokens=self.pool.page_tokens)
            sentinel = self.prefill_flash_sentinel
        else:
            out = _prefill_batch_jit(
                self.params, init_k, init_v, ids, starts, lens,
                n_heads=self.n_heads)
            sentinel = self.prefill_sentinel
        if sentinel is not None:
            sentinel.check()
        return out

    # -- chunked prefill ------------------------------------------------
    def _needs_chunking(self, req):
        chunk = self.config.prefill_chunk_tokens
        return chunk > 0 and self._suffix_len(req) > chunk

    def _start_chunked(self, req):
        """Reserve a slot+pages and a private cache for ``req`` and let
        ``_advance_chunk`` feed it one chunk per engine step. Returns
        False (request requeued) if the page pool cannot hold it."""
        req.attn_impl = self._impl_for_len(len(req.prompt))
        reuse, entry = self._acquire_prefix(req)
        req.prefix_entry = entry
        try:
            # reserved up front: completion can't stall on a full pool
            slot = self.pool.allocate(self._alloc_tokens(req))
        except PoolExhaustedError:
            slot = None
            if self._relieve_memory_pressure():
                try:    # one retry after shedding host-side ballast
                    slot = self.pool.allocate(self._alloc_tokens(req))
                except PoolExhaustedError:
                    slot = None
            if slot is None:
                if entry is not None and self.prefix_cache is not None:
                    self.prefix_cache.release(entry)
                    req.prefix_entry = None
                self.scheduler.requeue_front(req)
                return False
        self.metrics.record_admission(
            bucket_for(self._suffix_len(req), self.scheduler.buckets),
            len(req.prompt))
        shape = (self.n_layers, 1, self.n_heads, self.max_seq_len,
                 self.head_dim)
        cdtype = self.pool.compute_dtype
        if reuse > 0:
            k0 = np.zeros(shape, cdtype)
            v0 = np.zeros(shape, cdtype)
            ek, ev = self._entry_prefix_kv(entry, reuse)
            k0[:, 0, :, :reuse] = ek
            v0[:, 0, :, :reuse] = ev
            k0, v0 = self._put_prefill_kv(k0), self._put_prefill_kv(v0)
        else:
            k0 = self._zeros_prefill_kv(shape, cdtype)
            v0 = self._zeros_prefill_kv(shape, cdtype)
        self._chunking = _ChunkedPrefill(req, k0, v0, pos=reuse, reuse=reuse,
                                         slot=slot)
        return True

    def _advance_chunk(self, stats):
        """Run the next chunk of the in-flight chunked prefill (same
        compiled program as batched prefill, at B=1/Sb=chunk); install
        and activate on the final chunk. Mid chunks never block the host
        — only the final chunk syncs, for its first token."""
        st = self._chunking
        req = st.req
        now = time.monotonic()
        if req.deadline_exceeded(now):
            req.slot = st.slot             # hand the reserved slot back
            self._finish_timeout(req, phase="prefill")
            self._chunking = None
            stats["retired"] += 1
            return
        impl = getattr(req, "attn_impl", "dense")
        chunk_len = self.config.prefill_chunk_tokens
        # sparse chunks pad to a page multiple (blocked attention width
        # constraint); a chunk's pad garbage is overwritten by the next
        # chunk's real writes before it is ever attendable, and the
        # final chunk's by decode — same write-before-attend argument
        # as batched prefill padding
        cw = (_round_up(chunk_len, self.pool.page_tokens)
              if impl in ("sparse_xla", "pallas_sparse") else chunk_len)
        chunk = req.prompt[st.pos:st.pos + chunk_len]
        ids = np.zeros((1, cw), np.int32)
        ids[0, :len(chunk)] = chunk
        cspan = (self._tracer.span("serving/prefill_chunk", cat="serving",
                                   args={"request_id": req.id, "pos": st.pos,
                                         "chunk": len(chunk)})
                 if self._tracer.enabled else telemetry.NULL_SPAN)
        t0 = time.monotonic()
        with cspan:
            st.k, st.v, first = self._run_prefill(
                impl, st.k, st.v, self._put_host(ids),
                self._put_host(np.asarray([st.pos], np.int32)),
                self._put_host(np.asarray([len(req.prompt)], np.int32)))
        st.pos += len(chunk)
        stats["prefill_chunks"] += 1
        if st.pos < len(req.prompt):
            st.prefill_s += time.monotonic() - t0
            return
        first_tok = int(np.asarray(first)[0])      # sync: TTFT endpoint
        st.prefill_s += time.monotonic() - t0
        now = time.monotonic()
        self.metrics.record_prefill(
            tokens=len(req.prompt) - st.reuse, reused_tokens=st.reuse,
            requests=1, prefill_s=st.prefill_s)
        self._maybe_insert_prefix(req, st.reuse, st.k, st.v, lane=0)
        self.pool.install(st.k, st.v, st.slot, position=len(req.prompt))
        req.first_token_time = now
        self.metrics.record_first_token(now - req.submit_time)
        self._activate(req, st.slot, first_tok)
        stats["retired"] += self._maybe_retire(req, first_tok, now)
        self._chunking = None

    # -- prefix cache ---------------------------------------------------
    def _suffix_len(self, req):
        """Tokens a prefill would actually compute for ``req`` after
        prefix-cache reuse (always >= 1: the last prompt position is
        recomputed to produce the first token's logits)."""
        if self.prefix_cache is None:
            return len(req.prompt)
        length, _ = self.prefix_cache.match(
            req.prompt, impl=self._impl_for_len(len(req.prompt)))
        return len(req.prompt) - min(length, len(req.prompt) - 1)

    def _acquire_prefix(self, req):
        """Counted, ref-taking lookup at admission time. Returns
        (reused_tokens, entry-or-None); the ref is released at the
        request's retirement (any path)."""
        if self.prefix_cache is None:
            return 0, None
        length, entry = self.prefix_cache.acquire(
            req.prompt, impl=getattr(req, "attn_impl", "dense"))
        reuse = min(length, len(req.prompt) - 1)
        if entry is not None and reuse <= 0:
            self.prefix_cache.release(entry)
            entry, reuse = None, 0
        self.metrics.record_prefix_lookup(hit=reuse > 0)
        return reuse, entry

    def _maybe_insert_prefix(self, req, reuse, k, v, lane):
        """Store the freshly-prefilled prompt's KV for future requests
        (skipped when an existing entry already covers the whole prompt
        — nothing new to add). In int8 pool mode entries are stored
        QUANTIZED (per-(layer, head) scales over the cached positions):
        the trie's byte budget buys ~4x the prefix positions, same
        at-use-dequant contract as the pool itself."""
        if self.prefix_cache is None:
            return
        if self._degrade_rung >= 2:
            # budget_shrink rung: stop growing the host-RAM trie under
            # overload (lookups/hits still work — reuse stays free)
            return
        if self._mem_guard is not None and self._mem_guard.inserts_paused:
            # host-RSS watermark breached: stop allocating host memory
            # for new entries until the guard recovers (hits still work)
            return
        n = len(req.prompt)
        if reuse >= n - 1:
            return
        # entries are tagged with the backend that produced them: for
        # L >= 2 layers the backends' hidden states (hence deep-layer
        # KV) differ in low bits, so cross-backend seeding would break
        # the per-backend bitwise oracle
        impl = getattr(req, "attn_impl", "dense")
        pk = np.asarray(k[:, lane, :, :n])
        pv = np.asarray(v[:, lane, :, :n])
        if self.pool.kv_cache_dtype == "int8":
            pk, k_scale = quantize_kv_np(pk)
            pv, v_scale = quantize_kv_np(pv)
            self.prefix_cache.insert(req.prompt, pk, pv,
                                     k_scale=k_scale, v_scale=v_scale,
                                     impl=impl)
            return
        self.prefix_cache.insert(req.prompt, pk, pv, impl=impl)

    def _entry_prefix_kv(self, entry, reuse):
        """A prefix entry's first ``reuse`` positions in the pool's
        COMPUTE dtype (int8-mode entries dequantize here, at seed
        time — never inside the prefill program)."""
        ek = entry.k[:, :, :reuse]
        ev = entry.v[:, :, :reuse]
        if entry.k_scale is not None:
            dt = np.dtype(self.pool.compute_dtype)
            return (dequantize_kv_np(ek, entry.k_scale, dt),
                    dequantize_kv_np(ev, entry.v_scale, dt))
        return ek, ev

    # -- internals ------------------------------------------------------
    def _activate(self, req, slot, first_tok, emit=True):
        req.slot = slot
        self._active[slot] = req
        self._lane_tokens[slot] = first_tok
        self._lane_active[slot] = True
        impl = getattr(req, "attn_impl", "dense")
        self._lane_impl_window[slot] = impl in ("sparse_xla", "pallas_sparse")
        self._lane_impl_kernel[slot] = impl in ("pallas_decode",
                                                "pallas_sparse")
        if self._lane_history is not None:
            # seed the drafter: prompt tokens by position, then the
            # PENDING first generated token at position len(prompt)
            row = self._lane_history[slot]
            row[:] = 0
            row[:len(req.prompt)] = req.prompt
            row[len(req.prompt)] = first_tok
        self._lane_dirty = True
        if emit:
            self._emit(req, first_tok)

    def _emit(self, req, token):
        req.emitted += 1
        req.future._append(token)
        if req.stream_cb is not None:
            try:
                req.stream_cb(req.id, token)
            except Exception:  # a broken callback must not kill the loop
                pass

    def _maybe_retire(self, req, token, now):
        stuck = (self.injector is not None
                 and self.injector.request_is_stuck(req.id))
        if req.deadline_exceeded(now):
            self._finish_timeout(req, phase="decoding")
            return 1
        if self.scheduler.should_retire(req, token, stuck=stuck) is not None:
            self._release_slot(req)
            req.future._finish()
            self.scheduler.completed += 1
            self.metrics.record_completion()
            if self._tracer.enabled:
                self._tracer.instant("serving/retire", cat="serving",
                                     args={"request_id": req.id,
                                           "tokens": req.emitted})
            return 1
        return 0

    def _finish_timeout(self, req, phase):
        self._release_slot(req)
        if self._tracer.enabled:
            self._tracer.instant("serving/retire_timeout", cat="serving",
                                 args={"request_id": req.id, "phase": phase,
                                       "tokens": req.emitted})
        req.future._finish(RequestTimeoutError(
            req.id, req.timeout_s, phase, tokens_done=req.emitted))
        self.scheduler.timed_out += 1
        self.metrics.record_timeout()

    def _release_slot(self, req):
        if req.slot is not None and getattr(req, "handoff_export", False):
            # snapshot the lane's pages before the slot is freed; the
            # replica's handoff sender ships them to the decode worker
            try:
                req.export_payload = self.pool.export_lane(req.slot)
                self.metrics.record_handoff("export")
            except Exception as exc:
                req.export_error = exc
        if req.slot is not None:
            self._lane_active[req.slot] = False
            self._lane_impl_window[req.slot] = False
            self._lane_impl_kernel[req.slot] = False
            self._lane_dirty = True
            self._active.pop(req.slot, None)
            self.pool.free(req.slot)
            req.slot = None
        if req.prefix_entry is not None and self.prefix_cache is not None:
            self.prefix_cache.release(req.prefix_entry)
            req.prefix_entry = None

    # -- introspection ---------------------------------------------------
    def occupancy(self):
        return self.pool.occupancy()

    def prefix_stats(self):
        """Prefix-cache counters, or None when the cache is disabled."""
        return None if self.prefix_cache is None else self.prefix_cache.stats()
