"""Continuous-batching serving engine over the KV-cache decode path.

The decode loop is ONE jitted program for the life of the server: a
masked batched step over the pool's ``MaxSlots`` lanes, each lane
running the SAME per-token ``_step`` the one-shot ``generate()`` path
uses (vmapped with a per-lane position counter). ``MaxSlots`` is static,
the lane-active mask and positions are traced operands — so requests
joining, retiring, or swapping slots NEVER recompile. Prompt prefill is
per-request at a bucketed length (one compile per bucket, bounded by the
bucket ladder) and is copied into the request's slot with a traced-slot
install (one compile total).

Correctness oracle (tests/unit/test_serving.py): continuous-batched
greedy output is BITWISE equal to per-request ``generate()`` output for
any arrival order. Why it holds:

- prefill pads the prompt up to its bucket but *selects* the logits at
  the true last prompt position; positions < prompt_len only ever see
  true prompt tokens, so the selected logits match the unpadded scan;
- pad/stale cache beyond a lane's position is either overwritten before
  it is reachable (decode writes position p before attending to it) or
  hidden by the causal mask, whose -1e30 scores underflow to exactly 0
  probability — extra masked cache length is numerically invisible;
- lanes are vmapped, hence computed independently: a neighbor admitting,
  retiring, or holding garbage cannot perturb another lane's values
  (the batch-independence property test_generation.py already pins).

Greedy only: serving argmax-decodes (temperature-0), the mode with a
bitwise oracle. Sampling needs per-request RNG streams and is future
work.
"""

import threading
import time
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.generation import _step
from deepspeed_tpu.inference.serving.config import ServingConfig
from deepspeed_tpu.inference.serving.fault_injection import ServingFaultInjector
from deepspeed_tpu.inference.serving.kv_pool import KVCachePool
from deepspeed_tpu.inference.serving.metrics import ServingMetrics
from deepspeed_tpu.inference.serving.scheduler import (
    ContinuousBatchingScheduler,
    RequestTimeoutError,
    bucket_for,
    default_buckets,
)


@partial(jax.jit, static_argnames=("n_layers", "n_heads", "head_dim", "total"))
def _prefill_request_jit(params, padded_ids, true_len, *, n_layers, n_heads,
                         head_dim, total):
    """Prefill ONE request at its bucketed length into a fresh
    ``total``-long cache; return (k, v, first greedy token).

    ``padded_ids`` is [1, Sb] (prompt right-padded to its bucket);
    ``true_len`` is traced, so every prompt length inside a bucket shares
    the bucket's one compiled program. The scan runs the same ``_step``
    as ``_prefill``; the carried logits are *selected* at the true last
    prompt position instead of taken from the scan's end, which makes
    the padding invisible to the emitted token."""
    B, Sb = padded_ids.shape
    tr = params["params"]["transformer"]
    emb_dtype = (jnp.float32 if "kernel_q" in tr["wte"]
                 else tr["wte"]["embedding"].dtype)
    dtype = jnp.result_type(emb_dtype, tr["wpe"]["embedding"].dtype)
    shape = (n_layers, B, n_heads, total, head_dim)
    caches = (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))

    from deepspeed_tpu.inference.quantization import vocab_size

    V = vocab_size(tr["wte"])

    def body(carry, pos):
        caches, sel = carry
        logits, caches = _step(params, n_heads, caches, padded_ids[:, pos], pos)
        sel = jnp.where(pos == true_len - 1, logits, sel)
        return (caches, sel), None

    (caches, sel), _ = jax.lax.scan(
        body, (caches, jnp.zeros((B, V), dtype)), jnp.arange(Sb))
    first = jnp.argmax(sel, axis=-1).astype(jnp.int32)
    return caches[0], caches[1], first


@partial(jax.jit, static_argnames=("n_heads",), donate_argnums=(1, 2))
def _decode_step_jit(params, pool_k, pool_v, tokens, positions, active, *,
                     n_heads):
    """One masked batched decode step over every pool lane.

    Each lane feeds its last token at its own position through the
    one-shot path's ``_step`` (vmapped as a B=1 lane). Inactive lanes
    compute garbage into their own (dead) lane and keep their token via
    the ``active`` mask; the pool buffers are donated — the step is an
    in-place update of the serving state."""

    def lane(ck, cv, tok, pos):
        logits, (ck2, cv2) = _step(params, n_heads, (ck[:, None], cv[:, None]),
                                   tok[None], pos)
        return logits[0], ck2[:, 0], cv2[:, 0]

    logits, pool_k, pool_v = jax.vmap(
        lane, in_axes=(1, 1, 0, 0), out_axes=(0, 1, 1))(
        pool_k, pool_v, tokens, positions)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jnp.where(active, nxt, tokens), pool_k, pool_v


class ServingEngine:
    """Request queue + KV pool + the single compiled decode loop.

    Drive it synchronously (``step()`` / ``drain()`` — deterministic, what
    the tests do) or as a background thread (``start()`` / ``stop()``)
    with ``submit()`` from any thread."""

    def __init__(self, params, model_config, serving_config=None,
                 monitor=None, injector=None):
        cfg = serving_config or ServingConfig()
        self.params = params
        self.model_config = model_config
        self.config = cfg
        self.n_layers = model_config.num_hidden_layers
        self.n_heads = model_config.num_attention_heads
        self.head_dim = model_config.hidden_size // self.n_heads

        mpe = model_config.max_position_embeddings
        self.max_seq_len = cfg.max_seq_len or mpe
        if self.max_seq_len > mpe:
            raise ValueError(
                f"serving.max_seq_len={self.max_seq_len} exceeds "
                f"max_position_embeddings={mpe}")
        buckets = cfg.prompt_buckets or default_buckets(self.max_seq_len - 1)
        if buckets[-1] > self.max_seq_len - 1:
            raise ValueError(
                f"largest prompt bucket ({buckets[-1]}) must leave room for "
                f"one generated token (max_seq_len={self.max_seq_len})")

        tr = params["params"]["transformer"]
        emb_dtype = (jnp.float32 if "kernel_q" in tr["wte"]
                     else tr["wte"]["embedding"].dtype)
        dtype = jnp.result_type(emb_dtype, tr["wpe"]["embedding"].dtype)
        self.pool = KVCachePool(self.n_layers, cfg.max_slots, self.n_heads,
                                self.max_seq_len, self.head_dim, dtype=dtype)
        self.scheduler = ContinuousBatchingScheduler(
            max_queue=cfg.max_queue, buckets=buckets,
            default_max_new_tokens=cfg.default_max_new_tokens,
            request_timeout_s=cfg.request_timeout_s)
        self.metrics = ServingMetrics(monitor)
        if injector is None and cfg.fault_injection:
            injector = ServingFaultInjector(cfg.fault_injection)
        self.injector = injector

        self._active = {}                                   # slot -> Request
        self._lane_tokens = np.zeros(cfg.max_slots, np.int32)
        self._lane_active = np.zeros(cfg.max_slots, bool)
        self._step_count = 0
        self._loop_thread = None
        self._stop = threading.Event()

    @classmethod
    def from_config(cls, params, model_config, ds_config, rank=0,
                    injector=None):
        """Build from a ds_config (dict or DeepSpeedConfig): the validated
        ``serving`` block plus the shared monitor construction path."""
        from deepspeed_tpu.monitor import monitor_from_config
        from deepspeed_tpu.runtime.config import DeepSpeedConfig

        if isinstance(ds_config, dict):
            ds_config = DeepSpeedConfig(ds_config, world_size=1)
        return cls(params, model_config,
                   serving_config=ds_config.serving_config,
                   monitor=monitor_from_config(ds_config, rank),
                   injector=injector)

    # -- request intake -------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens=None, eos_token_id=None,
               timeout_s=None, stream_cb=None):
        """Queue one request; returns its ``ServingFuture``.

        ``prompt_ids`` is a 1-D token sequence. Raises ``QueueFullError``
        when the admission queue is at capacity (backpressure) and
        ``ValueError`` for requests that can never fit. ``stream_cb``
        (optional) is called as ``stream_cb(request_id, token)`` for every
        generated token, including the first."""
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if len(prompt) < 1:
            raise ValueError("prompt must contain at least one token")
        if max_new_tokens is None:
            max_new_tokens = self.config.default_max_new_tokens
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        bucket_for(len(prompt), self.scheduler.buckets)  # raises if too long
        total = len(prompt) + int(max_new_tokens)
        if total > self.max_seq_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"= {total} exceeds serving max_seq_len={self.max_seq_len}")
        if eos_token_id is not None and not (
                0 <= int(eos_token_id) < self.model_config.vocab_size):
            raise ValueError(
                f"eos_token_id={eos_token_id} outside vocab "
                f"[0, {self.model_config.vocab_size})")
        req = self.scheduler.submit(
            prompt, max_new_tokens=int(max_new_tokens),
            eos_token_id=None if eos_token_id is None else int(eos_token_id),
            timeout_s=timeout_s, stream_cb=stream_cb)
        return req.future

    # -- the serving loop ----------------------------------------------
    def step(self):
        """One scheduler iteration: expire, admit, one batched decode
        step, retire. Returns an activity dict (all zeros = idle)."""
        now = time.monotonic()
        stats = {"admitted": 0, "decoded": 0, "retired": 0}

        for req in self.scheduler.pop_expired(now):
            self._finish_timeout(req, phase="queued")
            stats["retired"] += 1

        # join-at-free-slot admission: fill every free lane from the queue
        while self.pool.free_slots > 0:
            req = self.scheduler.pop_next()
            if req is None:
                break
            retired = self._admit(req)
            stats["admitted"] += 1
            stats["retired"] += retired

        if self._active:
            if self.injector is not None:
                self.injector.maybe_slow_decode(self._step_count)
            t0 = time.monotonic()
            tokens, self.pool.k, self.pool.v = _decode_step_jit(
                self.params, self.pool.k, self.pool.v,
                jnp.asarray(self._lane_tokens),
                jnp.asarray(self.pool.positions),
                jnp.asarray(self._lane_active),
                n_heads=self.n_heads)
            host_tokens = np.asarray(tokens)       # sync point: EOS checks
            step_s = time.monotonic() - t0
            self._lane_tokens = host_tokens.copy()
            now = time.monotonic()
            n_active = len(self._active)
            for slot in list(self._active):
                req = self._active[slot]
                self.pool.advance(slot)
                self._emit(req, int(host_tokens[slot]))
                stats["decoded"] += 1
                stats["retired"] += self._maybe_retire(req, int(host_tokens[slot]), now)
            self.metrics.record_step(
                queue_depth=self.scheduler.queue_depth(),
                active_slots=n_active, max_slots=self.pool.max_slots,
                tokens_this_step=n_active, step_s=step_s)
        self._step_count += 1
        return stats

    def drain(self, max_steps=None):
        """Step until no request is queued or in flight. ``max_steps``
        bounds the loop (a deadline-less stuck request would otherwise
        spin forever under fault injection)."""
        steps = 0
        while self._active or self.scheduler.queue_depth() > 0:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return steps

    # -- background mode ------------------------------------------------
    def start(self, idle_sleep_s=0.001):
        """Run the serving loop on a daemon thread until ``stop()``."""
        if self._loop_thread is not None:
            raise RuntimeError("serving loop already running")
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                busy = self.step()
                if not any(busy.values()) and not self._active:
                    time.sleep(idle_sleep_s)

        self._loop_thread = threading.Thread(
            target=loop, name="serving-loop", daemon=True)
        self._loop_thread.start()

    def stop(self, timeout_s=5.0):
        if self._loop_thread is None:
            return
        self._stop.set()
        self._loop_thread.join(timeout_s)
        self._loop_thread = None

    def close(self):
        self.stop()
        self.metrics.close()

    # -- internals ------------------------------------------------------
    def _admit(self, req):
        """Prefill ``req`` at its bucket length and install it into a
        slot. Returns 1 when the request retired on its very first token
        (max_new_tokens=1 or instant EOS), else 0."""
        bucket = bucket_for(len(req.prompt), self.scheduler.buckets)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(req.prompt)] = req.prompt
        new_k, new_v, first = _prefill_request_jit(
            self.params, jnp.asarray(padded), jnp.int32(len(req.prompt)),
            n_layers=self.n_layers, n_heads=self.n_heads,
            head_dim=self.head_dim, total=self.max_seq_len)
        first_tok = int(first[0])                  # sync: TTFT endpoint
        req.first_token_time = time.monotonic()
        self.metrics.record_first_token(req.first_token_time - req.submit_time)

        slot = self.pool.allocate()
        self.pool.install(new_k, new_v, slot, position=len(req.prompt))
        req.slot = slot
        self._active[slot] = req
        self._lane_tokens[slot] = first_tok
        self._lane_active[slot] = True
        self._emit(req, first_tok)
        return self._maybe_retire(req, first_tok, time.monotonic())

    def _emit(self, req, token):
        req.emitted += 1
        req.future._append(token)
        if req.stream_cb is not None:
            try:
                req.stream_cb(req.id, token)
            except Exception:  # a broken callback must not kill the loop
                pass

    def _maybe_retire(self, req, token, now):
        stuck = (self.injector is not None
                 and self.injector.request_is_stuck(req.id))
        if req.deadline_exceeded(now):
            self._finish_timeout(req, phase="decoding")
            return 1
        if self.scheduler.should_retire(req, token, stuck=stuck) is not None:
            self._release_slot(req)
            req.future._finish()
            self.scheduler.completed += 1
            self.metrics.record_completion()
            return 1
        return 0

    def _finish_timeout(self, req, phase):
        self._release_slot(req)
        req.future._finish(RequestTimeoutError(
            req.id, req.timeout_s, phase, tokens_done=req.emitted))
        self.scheduler.timed_out += 1
        self.metrics.record_timeout()

    def _release_slot(self, req):
        if req.slot is not None:
            self._lane_active[req.slot] = False
            self._active.pop(req.slot, None)
            self.pool.free(req.slot)
            req.slot = None

    # -- introspection ---------------------------------------------------
    def occupancy(self):
        return self.pool.occupancy()

    @staticmethod
    def decode_compile_count():
        """Compiled decode-step program count (jit cache size) — the
        recompile-pin tests assert this stays at 1 across slot churn."""
        return _decode_step_jit._cache_size()

    @staticmethod
    def prefill_compile_count():
        """Compiled prefill program count — bounded by the bucket ladder."""
        return _prefill_request_jit._cache_size()
