"""Deterministic fault injection for the serving engine.

Fourth member of the injector family: extends the PR 2 step-level
``StepFaultInjector`` (itself extending the PR 1 checkpoint I/O
``FaultInjector``), adding *serving-loop* faults so the engine's
recovery paths are testable on CPU without real stragglers:

    slow_decode     sleep ``seconds`` before the batched decode step at
                    scheduler iteration N (a straggler device / slow
                    relay: exercises deadline accounting under a slow
                    loop — queued peers keep their deadlines honest)
    stuck_request   request ``request_id`` never retires naturally: its
                    EOS / max_new_tokens retirements are suppressed, so
                    ONLY the per-request deadline can reap it
                    (exercises RequestTimeoutError recovery + slot
                    reclamation while neighbors keep decoding)
    evict_under_decode
                    forcibly evict every unreferenced prefix-cache entry
                    right before the decode step at scheduler iteration
                    N (cache churn under live traffic: in-flight lanes
                    already copied their KV, so eviction must be
                    output-invisible and later admissions simply miss)
    corrupt_draft   scramble every lane's proposed draft tokens before
                    the speculative verify step at scheduler iteration N
                    (a worst-case / adversarial drafter: the verify
                    forward must reject the garbage and output must stay
                    bitwise identical to non-speculative greedy — only
                    throughput may suffer). No-op with speculation
                    disabled.

Fleet arms (PR 12) extend the same interface to replica-process faults
so router failover paths are drivable from config:

    kill_replica    SIGKILL the replica process right before the Nth
                    decode step that has active lanes (``at_step``
                    counts BUSY steps, not raw scheduler iterations — a
                    background loop idles the iteration counter forward
                    between requests) — the hard-death case
                    (no drain, no goodbye on the socket): the supervisor
                    sees a crash and restarts, the router sees EOF and
                    must re-route every in-flight request
    slow_replica    delay every socket reply by ``seconds`` (a healthy
                    engine behind a slow transport: exercises the
                    router's per-attempt timeout + health scoring
                    without killing anything)
    reject_admission
                    the replica refuses the next ``times`` submissions
                    with an injected rejection (admission-layer flake:
                    the router must re-route WITHOUT burning the
                    request's retry budget)

Memory-tier arms (spill tier + pressure guard, prefix_cache.py)::

    corrupt_spill_entry   flip a byte in one spilled prefix-cache blob at
                          scheduler iteration N — the next promotion must
                          fail its crc32, drop the entry, and fall
                          through to a normal prefill (never an error)
    torn_spill_write      the spill store's next disk write lands
                          truncated under its final name (crash
                          mid-write); the framed reload must drop it
    host_mem_pressure     the MemoryPressureGuard reads a fake
                          over-watermark RSS for the next ``times``
                          checks, driving shed-spill / pause-inserts /
                          degrade-rung escalation without real memory

Arms take ``at_step``/``times`` like the step arms (``slow_decode``,
``evict_under_decode``) or ``request_id`` (``stuck_request``, persistent
by default). Because the class sits at the bottom of the injector
hierarchy, one spec may combine serving faults with step and I/O
faults::

    {"slow_decode": {"at_step": 2, "seconds": 0.05},
     "stuck_request": {"request_id": 1}}

Programmatically::

    fi = ServingFaultInjector()
    fi.arm_serving("slow_decode", at_step=2, seconds=0.05)
    fi.arm_serving("stuck_request", request_id=1)
    fi.arm_serving("evict_under_decode", at_step=3)
    fi.arm_serving("kill_replica", at_step=4)
    fi.arm_serving("reject_admission", times=2)
"""

import os
import signal
import time

import numpy as np

from deepspeed_tpu.runtime.resilience.fault_injection import StepFaultInjector

SERVING_POINTS = ("slow_decode", "stuck_request", "evict_under_decode",
                  "corrupt_draft", "kill_replica", "slow_replica",
                  "reject_admission", "handoff_corrupt_frame",
                  "handoff_kill_mid_transfer", "handoff_kill_post_ack",
                  "corrupt_spill_entry", "torn_spill_write",
                  "host_mem_pressure")


class _ServingArm:
    __slots__ = ("at_step", "times", "seconds", "request_id")

    def __init__(self, at_step=None, times=None, seconds=0.05, request_id=None):
        self.at_step = None if at_step is None else int(at_step)
        self.times = None if times is None else int(times)
        self.seconds = float(seconds)
        self.request_id = None if request_id is None else int(request_id)


class ServingFaultInjector(StepFaultInjector):
    """Checkpoint I/O + step + serving-loop fault injector."""

    def __init__(self, spec=None):
        spec = dict(spec or {})
        serving_spec = {p: spec.pop(p) for p in list(spec) if p in SERVING_POINTS}
        super().__init__(spec)  # remaining points are step / I/O arms
        self._serving_arms = {}
        for point, cfg in serving_spec.items():
            self.arm_serving(point, **dict(cfg or {}))

    def arm_serving(self, point, **kwargs):
        if point not in SERVING_POINTS:
            raise ValueError(
                f"unknown serving fault point '{point}' "
                f"(known: {', '.join(SERVING_POINTS)})")
        if point == "stuck_request" and kwargs.get("request_id") is None:
            raise ValueError("stuck_request requires request_id")
        self._serving_arms[point] = _ServingArm(**kwargs)
        return self

    def disarm_serving(self, point=None):
        if point is None:
            self._serving_arms.clear()
        else:
            self._serving_arms.pop(point, None)

    # -- hooks the serving engine calls ---------------------------------
    def maybe_slow_decode(self, step):
        """Sleep before decode when the slow_decode arm matches ``step``."""
        arm = self._serving_arms.get("slow_decode")
        if arm is None:
            return
        if arm.at_step is not None and step != arm.at_step:
            return
        if arm.times is not None:
            if arm.times <= 0:
                return
            arm.times -= 1
        self._fire("slow_decode")
        time.sleep(arm.seconds)

    def maybe_evict_prefix(self, step, prefix_cache):
        """Evict every unreferenced prefix-cache entry when the
        evict_under_decode arm matches ``step`` (no-op without a cache)."""
        arm = self._serving_arms.get("evict_under_decode")
        if arm is None or prefix_cache is None:
            return
        if arm.at_step is not None and step != arm.at_step:
            return
        if arm.times is not None:
            if arm.times <= 0:
                return
            arm.times -= 1
        self._fire("evict_under_decode")
        prefix_cache.evict_unreferenced()

    def corrupt_draft_noise(self, step, k, vocab_size):
        """Per-draft-position noise [k] when the corrupt_draft arm
        matches ``step``, else None (engine keeps its zero operand).

        Values are deterministic in [1, vocab_size-1], so the engine's
        ``(draft + noise) % vocab_size`` maps EVERY draft token to a
        DIFFERENT token — a guaranteed-wrong drafter, not merely a
        perturbed one."""
        arm = self._serving_arms.get("corrupt_draft")
        if arm is None or k <= 0:
            return None
        if arm.at_step is not None and step != arm.at_step:
            return None
        if arm.times is not None:
            if arm.times <= 0:
                return None
            arm.times -= 1
        self._fire("corrupt_draft")
        if vocab_size < 2:
            return None                  # nowhere to scramble to
        return 1 + (np.arange(k, dtype=np.int32) * 7919) % (vocab_size - 1)

    # -- fleet hooks (replica.py / router tests) ------------------------
    def maybe_kill_replica(self, step):
        """SIGKILL this process when the kill_replica arm matches
        ``step`` — the replica dies mid-decode with no cleanup, exactly
        like an OOM-killed or preempted-without-grace worker. The kill
        primitive is swappable (``_kill``) so unit tests can observe the
        trigger without dying."""
        arm = self._serving_arms.get("kill_replica")
        if arm is None:
            return
        if arm.at_step is not None and step != arm.at_step:
            return
        if arm.times is not None:
            if arm.times <= 0:
                return
            arm.times -= 1
        self._fire("kill_replica")
        self._kill()

    def _kill(self):
        os.kill(os.getpid(), signal.SIGKILL)

    def reply_delay_s(self):
        """Per-reply socket delay while the slow_replica arm is armed
        (``times`` bounds how many replies are delayed), else 0.0."""
        arm = self._serving_arms.get("slow_replica")
        if arm is None:
            return 0.0
        if arm.times is not None:
            if arm.times <= 0:
                return 0.0
            arm.times -= 1
        self._fire("slow_replica")
        return arm.seconds

    def admission_rejected(self):
        """True while the reject_admission arm has shots left: the
        replica server answers the submit with an injected rejection
        instead of reaching the engine."""
        arm = self._serving_arms.get("reject_admission")
        if arm is None:
            return False
        if arm.times is not None:
            if arm.times <= 0:
                return False
            arm.times -= 1
        self._fire("reject_admission")
        return True

    # -- disaggregated-handoff hooks (handoff.py / replica.py) ----------
    def corrupt_handoff_frame(self):
        """True while the handoff_corrupt_frame arm has shots left: the
        sender flips a byte of the NEXT page frame after computing its
        crc header — simulated wire damage the receiver's crc32 check
        must catch (times=1 lets the bounded retry then succeed)."""
        arm = self._serving_arms.get("handoff_corrupt_frame")
        if arm is None:
            return False
        if arm.times is not None:
            if arm.times <= 0:
                return False
            arm.times -= 1
        self._fire("handoff_corrupt_frame")
        return True

    def maybe_kill_mid_transfer(self, frames_sent):
        """SIGKILL the PREFILL worker after it has written ``at_step``
        page frames of a handoff — mid-transfer death with a half-sent
        claim on the decode side (the decode worker's orphan reaper must
        free it). Kill primitive swappable via ``_kill``."""
        arm = self._serving_arms.get("handoff_kill_mid_transfer")
        if arm is None:
            return
        if arm.at_step is not None and frames_sent != arm.at_step:
            return
        if arm.times is not None:
            if arm.times <= 0:
                return
            arm.times -= 1
        self._fire("handoff_kill_mid_transfer")
        self._kill()

    def maybe_kill_post_ack(self):
        """SIGKILL the DECODE worker right after it wrote a handoff ack
        — the prefill side believes the transfer landed, then the resume
        target dies; the router must re-route from its delivered
        high-water mark bitwise. Kill primitive swappable via
        ``_kill``."""
        arm = self._serving_arms.get("handoff_kill_post_ack")
        if arm is None:
            return
        if arm.times is not None:
            if arm.times <= 0:
                return
            arm.times -= 1
        self._fire("handoff_kill_post_ack")
        self._kill()

    # -- memory-tier hooks (prefix_cache.py spill tier / engine) --------
    def maybe_corrupt_spill(self, step, prefix_cache):
        """Flip a byte in one spilled prefix-cache blob when the
        corrupt_spill_entry arm matches ``step`` (no-op without a cache
        or spill tier). The next promotion of that entry must fail its
        crc32 and fall through to a normal prefill — never an error."""
        arm = self._serving_arms.get("corrupt_spill_entry")
        if arm is None or prefix_cache is None:
            return
        if arm.at_step is not None and step != arm.at_step:
            return
        if arm.times is not None:
            if arm.times <= 0:
                return
            arm.times -= 1
        self._fire("corrupt_spill_entry")
        prefix_cache.corrupt_spilled()

    def torn_spill_write(self):
        """True while the torn_spill_write arm has shots left: the spill
        store's NEXT disk write lands truncated under its final name —
        the crash-mid-write the atomic rename protocol normally rules
        out — so the reload path must catch it by framing."""
        arm = self._serving_arms.get("torn_spill_write")
        if arm is None:
            return False
        if arm.times is not None:
            if arm.times <= 0:
                return False
            arm.times -= 1
        self._fire("torn_spill_write")
        return True

    def host_mem_pressure_active(self):
        """True while the host_mem_pressure arm has shots left — each
        call is one MemoryPressureGuard check that should read a fake
        over-watermark RSS (``times`` bounds how many guard ticks stay
        pressured, so an episode recovers deterministically)."""
        arm = self._serving_arms.get("host_mem_pressure")
        if arm is None:
            return False
        if arm.times is not None:
            if arm.times <= 0:
                return False
            arm.times -= 1
        self._fire("host_mem_pressure")
        return True

    def request_is_stuck(self, request_id):
        """True while the stuck_request arm pins ``request_id`` (persistent
        unless ``times`` bounds it; ``fired`` counts suppressed
        retirements)."""
        arm = self._serving_arms.get("stuck_request")
        if arm is None or arm.request_id != request_id:
            return False
        if arm.times is not None:
            if arm.times <= 0:
                return False
            arm.times -= 1
        self._fire("stuck_request")
        return True
