"""Serving metrics: tokens/s, TTFT percentiles, queue depth, occupancy,
prefill-vs-decode split, prefix-cache hit rate.

Recorded through the SAME ``monitor_from_config`` backends the training
engines use (tensorboard/csv/both), so a serving deployment's dashboards
come from the one construction path — a new monitor backend lights up
here for free. All aggregation is host-side and O(1) per scheduler
iteration (TTFT percentiles sort a bounded sample window at
``snapshot()`` time, not on the serving loop); with no monitor
configured the recorder is still useful as a cheap in-process stats
object (``snapshot()``).
"""

import time
from collections import deque

# TTFT percentile window: newest samples win once full (a long-running
# server's p95 should describe current traffic, not hour-old compiles).
_TTFT_WINDOW = 8192


def _percentile(sorted_samples, q):
    """Nearest-rank percentile over an ascending list (deterministic, no
    interpolation — matches how SLOs are usually stated)."""
    if not sorted_samples:
        return None
    n = len(sorted_samples)
    rank = max(1, -(-q * n // 100))              # ceil(q/100 * n)
    return sorted_samples[min(int(rank), n) - 1]


class ServingMetrics:
    """Aggregates serving counters and forwards gauges to a monitor."""

    def __init__(self, monitor=None):
        self.monitor = monitor
        self.decode_steps = 0
        self.tokens_emitted = 0
        self.requests_completed = 0
        self.requests_timed_out = 0
        self.decode_time_s = 0.0
        # prefill: whole-prompt forwards (batched / chunked); ``tokens``
        # counts positions actually computed, so prefix-cache reuse shows
        # up as the gap between prompt tokens and prefill tokens
        self.prefill_calls = 0
        self.prefill_tokens = 0
        self.prefill_reused_tokens = 0
        self.prefill_time_s = 0.0
        # prefix cache lookups (mirrors the cache's own counters so a
        # snapshot works without reaching into the engine)
        self.prefix_hits = 0
        self.prefix_misses = 0
        # spill tier: promotion hit rate, dropped-corrupt counter, and
        # pull sources for live byte/entry/RSS gauges (engine wires
        # set_spill_sources; snapshot degrades gracefully unwired)
        self.spill_hits = 0
        self.spill_misses = 0
        self.spill_corrupt_total = 0
        self._spill_stats_fn = None
        self._host_rss_mb_fn = None
        # speculative decoding: drafts proposed/accepted across steps and
        # the pool's storage footprint (recorded once, at engine build)
        self.draft_proposed = 0
        self.draft_accepted = 0
        self.kv_pool_bytes = 0
        # paged KV pool: last-seen page occupancy/fragmentation gauges
        # and a per-bucket histogram of admitted prompt lengths
        # {bucket: [count, token_sum, min_len, max_len]}
        self.pages_in_use = 0
        self.page_fragmentation = 0.0
        self._admitted_by_bucket = {}
        # disaggregated prefill/decode handoff (engine calls
        # record_handoff; events beyond these four still count as a
        # dict entry so a new event kind never raises)
        self.handoff_exports = 0
        self.handoff_installs = 0
        self.handoff_dup_installs = 0
        self.handoff_resumes = 0
        self.handoff_reaped = 0
        # TTFT: time from submit() to the request's first token
        self._ttft_sum = 0.0
        self._ttft_count = 0
        self._ttft_max = 0.0
        # deque(maxlen=...) evicts the oldest sample in O(1); the old list
        # did an O(n) pop(0) memmove per TTFT once full
        self._ttft_window = deque(maxlen=_TTFT_WINDOW)
        self._started = time.monotonic()

    # -- recording hooks (engine calls these) ---------------------------
    def record_first_token(self, ttft_s):
        self._ttft_sum += ttft_s
        self._ttft_count += 1
        self._ttft_max = max(self._ttft_max, ttft_s)
        self._ttft_window.append(ttft_s)
        self._record("Serving/ttft_s", ttft_s, self._ttft_count)

    def record_prefill(self, tokens, reused_tokens, requests, prefill_s):
        """One prefill call: ``tokens`` computed this call (suffix only
        on a prefix hit), ``reused_tokens`` seeded from the prefix cache,
        over ``requests`` prompts in ``prefill_s`` seconds."""
        self.prefill_calls += 1
        self.prefill_tokens += tokens
        self.prefill_reused_tokens += reused_tokens
        self.prefill_time_s += prefill_s
        if prefill_s > 0:
            self._record("Serving/prefill_tokens_per_sec",
                         tokens / prefill_s, self.prefill_calls)
        self._record("Serving/prefill_batch", requests, self.prefill_calls)

    def record_prefix_lookup(self, hit):
        if hit:
            self.prefix_hits += 1
        else:
            self.prefix_misses += 1
        lookups = self.prefix_hits + self.prefix_misses
        self._record("Serving/PrefixHitRate",
                     self.prefix_hits / lookups, lookups)

    def record_spill_lookup(self, hit):
        """One spill-tier consult on the counted (acquire) path: ``hit``
        when the returned entry was just promoted out of the spill
        tier — ``Serving/SpillHitRate`` is the fraction of prefix
        lookups the demotion tier saved from a cold re-prefill."""
        if hit:
            self.spill_hits += 1
        else:
            self.spill_misses += 1
        lookups = self.spill_hits + self.spill_misses
        self._record("Serving/SpillHitRate",
                     self.spill_hits / lookups, lookups)

    def record_spill_corrupt(self):
        """A spilled entry failed its checksum/framing on promotion and
        was dropped (the request fell through to a normal prefill)."""
        self.spill_corrupt_total += 1
        self._record("Serving/spill_corrupt_total",
                     self.spill_corrupt_total, self.spill_corrupt_total)

    def set_spill_sources(self, spill_stats_fn=None, host_rss_mb_fn=None):
        """Wire pull sources for the live gauges: ``spill_stats_fn`` ->
        the SpillStore ``stats()`` dict (bytes/entries), and
        ``host_rss_mb_fn`` -> current host RSS in MiB (the guard's
        reader). Both surface in ``snapshot()`` and therefore in the
        ``Serving/Snapshot`` Prometheus exposition."""
        self._spill_stats_fn = spill_stats_fn
        self._host_rss_mb_fn = host_rss_mb_fn

    def record_admission(self, bucket, prompt_len):
        """One admitted prompt: tally its TRUE length (not the padded
        bucket width) under the bucket it was admitted to, building the
        per-bucket admitted-prompt-length histogram."""
        h = self._admitted_by_bucket.get(bucket)
        if h is None:
            self._admitted_by_bucket[bucket] = [
                1, prompt_len, prompt_len, prompt_len]
        else:
            h[0] += 1
            h[1] += prompt_len
            h[2] = min(h[2], prompt_len)
            h[3] = max(h[3], prompt_len)
        self._record(f"Serving/admitted_prompt_len_bucket_{bucket}",
                     prompt_len, self._admitted_by_bucket[bucket][0])

    def record_completion(self):
        self.requests_completed += 1

    def record_timeout(self):
        self.requests_timed_out += 1

    def record_step(self, queue_depth, active_slots, max_slots,
                    tokens_this_step, step_s, accepted_tokens=0,
                    proposed_tokens=0, pages_in_use=0,
                    page_fragmentation=0.0):
        """One decode step. With speculation armed, ``proposed_tokens``
        is k * active lanes and ``accepted_tokens`` how many drafts the
        oracle confirmed — tokens_this_step then exceeds the lane count
        by exactly the accepted drafts (minus early retirements).
        ``pages_in_use``/``page_fragmentation`` come from the paged
        pool's ``occupancy()`` — last-value gauges, not counters."""
        self.decode_steps += 1
        self.tokens_emitted += tokens_this_step
        self.decode_time_s += step_s
        self.pages_in_use = pages_in_use
        self.page_fragmentation = page_fragmentation
        step = self.decode_steps
        self._record("Serving/queue_depth", queue_depth, step)
        self._record("Serving/pages_in_use", pages_in_use, step)
        self._record("Serving/page_fragmentation", page_fragmentation, step)
        self._record("Serving/batch_occupancy",
                     active_slots / max_slots if max_slots else 0.0, step)
        if step_s > 0:
            self._record("Serving/tokens_per_sec",
                         tokens_this_step / step_s, step)
        self._record("Serving/tokens_per_step", tokens_this_step, step)
        if proposed_tokens > 0:
            self.draft_proposed += proposed_tokens
            self.draft_accepted += accepted_tokens
            self._record("Serving/accept_rate",
                         accepted_tokens / proposed_tokens, step)

    def record_handoff(self, event):
        """One KV-handoff lifecycle event: 'export' (prefill side,
        pages snapshotted at retire), 'install' / 'dup_install' (decode
        side, pages landed / idempotent re-send dropped), 'resume'
        (lane activated from installed pages), 'reaped' (orphaned
        claim freed by the TTL reaper)."""
        attr = f"handoff_{event}s" if not event.endswith("ed") \
            else f"handoff_{event}"
        setattr(self, attr, getattr(self, attr, 0) + 1)
        self._record(f"Serving/{attr}", getattr(self, attr), 1)

    def record_kv_pool_bytes(self, nbytes):
        """Pool storage footprint (KV + scales) — a construction-time
        constant, re-recordable if a pool is ever rebuilt."""
        self.kv_pool_bytes = int(nbytes)
        self._record("Serving/kv_pool_bytes", int(nbytes), 1)

    def _record(self, tag, value, step):
        if self.monitor is not None:
            self.monitor.record(tag, value, step)

    # -- reading --------------------------------------------------------
    def avg_ttft_s(self):
        return self._ttft_sum / self._ttft_count if self._ttft_count else None

    def ttft_percentiles(self):
        """(p50, p95) over the recent TTFT window, (None, None) empty."""
        window = sorted(self._ttft_window)
        return _percentile(window, 50), _percentile(window, 95)

    def tokens_per_sec(self):
        """Decode-loop throughput (excludes idle wall time between
        requests — the number a capacity planner wants)."""
        if self.decode_time_s <= 0:
            return None
        return self.tokens_emitted / self.decode_time_s

    def prefill_tokens_per_sec(self):
        if self.prefill_time_s <= 0:
            return None
        return self.prefill_tokens / self.prefill_time_s

    def prefix_hit_rate(self):
        lookups = self.prefix_hits + self.prefix_misses
        return self.prefix_hits / lookups if lookups else None

    def spill_hit_rate(self):
        lookups = self.spill_hits + self.spill_misses
        return self.spill_hits / lookups if lookups else None

    def accept_rate(self):
        """Cumulative draft acceptance rate, None before any
        speculative step (or with speculation disabled)."""
        if self.draft_proposed <= 0:
            return None
        return self.draft_accepted / self.draft_proposed

    def tokens_per_step(self):
        """Mean emitted tokens per decode step — the speculative
        multiplier a capacity planner multiplies lane count by."""
        if self.decode_steps <= 0:
            return None
        return self.tokens_emitted / self.decode_steps

    def snapshot(self):
        p50, p95 = self.ttft_percentiles()
        snap = {
            "decode_steps": self.decode_steps,
            "tokens_emitted": self.tokens_emitted,
            "requests_completed": self.requests_completed,
            "requests_timed_out": self.requests_timed_out,
            "tokens_per_sec": self.tokens_per_sec(),
            "avg_ttft_s": self.avg_ttft_s(),
            "max_ttft_s": self._ttft_max if self._ttft_count else None,
            "ttft_p50_s": p50,
            "ttft_p95_s": p95,
            # prefill-vs-decode token split: prompt positions computed by
            # prefill forwards vs tokens emitted by the decode loop
            "prefill_tokens": self.prefill_tokens,
            "decode_tokens": self.tokens_emitted,
            "prefill_calls": self.prefill_calls,
            "prefill_tokens_per_sec": self.prefill_tokens_per_sec(),
            "prefix_reused_tokens": self.prefill_reused_tokens,
            "prefix_hit_rate": self.prefix_hit_rate(),
            # speculative decoding + pool storage
            "accept_rate": self.accept_rate(),
            "tokens_per_step": self.tokens_per_step(),
            "draft_proposed": self.draft_proposed,
            "draft_accepted": self.draft_accepted,
            "kv_pool_bytes": self.kv_pool_bytes,
            "pages_in_use": self.pages_in_use,
            "page_fragmentation": self.page_fragmentation,
            # disaggregated prefill/decode handoff lifecycle
            "handoff_exports": self.handoff_exports,
            "handoff_installs": self.handoff_installs,
            "handoff_dup_installs": self.handoff_dup_installs,
            "handoff_resumes": self.handoff_resumes,
            "handoff_reaped": self.handoff_reaped,
            # spill tier + memory pressure (pull gauges: live bytes and
            # host RSS are read at snapshot time, not last-recorded)
            "spill_hit_rate": self.spill_hit_rate(),
            "spill_corrupt_total": self.spill_corrupt_total,
            "uptime_s": time.monotonic() - self._started,
        }
        if self._spill_stats_fn is not None:
            try:
                sstats = self._spill_stats_fn() or {}
            except Exception:
                sstats = {}
            snap["spill_bytes"] = sstats.get("bytes", 0)
            snap["spill_disk_bytes"] = sstats.get("disk_bytes", 0)
            snap["spill_entries"] = sstats.get("entries", 0)
        if self._host_rss_mb_fn is not None:
            rss = self._host_rss_mb_fn()
            if rss is not None:
                snap["host_rss_mb"] = rss
        # flattened per-bucket admitted-prompt-length histogram: numeric
        # keys so export_to's gauge filter picks them up unchanged
        for bucket in sorted(self._admitted_by_bucket):
            count, total, lo, hi = self._admitted_by_bucket[bucket]
            snap[f"admitted_prompts_bucket_{bucket}"] = count
            snap[f"admitted_prompt_len_mean_bucket_{bucket}"] = total / count
            snap[f"admitted_prompt_len_min_bucket_{bucket}"] = lo
            snap[f"admitted_prompt_len_max_bucket_{bucket}"] = hi
        return snap

    def export_to(self, registry, name="Serving/Snapshot"):
        """Expose the numeric ``snapshot()`` fields as pull gauges on a
        telemetry registry — rendered live at every ``/metrics`` scrape
        (pushed gauges would be stale between monitor flushes)."""
        registry.gauge_fn(
            name,
            lambda: {k: v for k, v in self.snapshot().items()
                     if isinstance(v, (int, float)) and not isinstance(v, bool)},
            help="live ServingMetrics.snapshot()")
        return registry

    def close(self):
        if self.monitor is not None:
            self.monitor.flush()


# rollout phases in escalation order; the phase gauge exports the index
ROLLOUT_PHASES = ("idle", "staging", "canary", "promoting", "rolling_back",
                  "committed")


class RolloutMetrics:
    """Counters and gauges for the weight-rollout state machine.

    Two lifetimes on purpose: *per-rollout* counters (shadow compares,
    shadow diffs, canary crashes) reset when ``begin_rollout`` starts the
    next attempt — a diff rate must describe THIS canary, not a previous
    one — while *fleet-lifetime* counters (rollouts/rollbacks/commits)
    only ever grow. Exported under ``Rollout/*`` (``Rollout/phase``,
    ``Rollout/shadow_diff_total``, ``Rollout/rollbacks_total``, ...)."""

    def __init__(self, monitor=None):
        self.monitor = monitor
        self.phase = "idle"
        self.target_tag = None
        # lifetime
        self.rollouts_total = 0
        self.rollbacks_total = 0
        self.commits_total = 0
        # per-rollout (reset by begin_rollout)
        self.shadow_compared_total = 0
        self.shadow_diff_total = 0
        self.canary_crashes = 0
        self.last_rollback_reason = None
        self.last_recovery_s = None

    def begin_rollout(self, tag):
        self.rollouts_total += 1
        self.target_tag = str(tag)
        self.shadow_compared_total = 0
        self.shadow_diff_total = 0
        self.canary_crashes = 0
        self.last_rollback_reason = None
        self.last_recovery_s = None
        self.set_phase("staging")

    def set_phase(self, phase):
        if phase not in ROLLOUT_PHASES:
            raise ValueError(f"unknown rollout phase {phase!r}")
        self.phase = phase
        self._record("Rollout/phase", float(ROLLOUT_PHASES.index(phase)),
                     self.rollouts_total)

    def record_shadow(self, matched):
        self.shadow_compared_total += 1
        if not matched:
            self.shadow_diff_total += 1
        self._record("Rollout/shadow_diff_total",
                     float(self.shadow_diff_total),
                     self.shadow_compared_total)

    def record_canary_crash(self):
        self.canary_crashes += 1

    def record_rollback(self, reason):
        self.rollbacks_total += 1
        self.last_rollback_reason = str(reason)
        self._record("Rollout/rollbacks_total",
                     float(self.rollbacks_total), self.rollouts_total)

    def record_commit(self):
        self.commits_total += 1

    def shadow_diff_rate(self):
        if self.shadow_compared_total <= 0:
            return 0.0
        return self.shadow_diff_total / self.shadow_compared_total

    def _record(self, tag, value, step):
        if self.monitor is not None:
            self.monitor.record(tag, value, step)

    def snapshot(self):
        return {
            "phase": float(ROLLOUT_PHASES.index(self.phase)),
            "rollouts_total": float(self.rollouts_total),
            "rollbacks_total": float(self.rollbacks_total),
            "commits_total": float(self.commits_total),
            "shadow_compared_total": float(self.shadow_compared_total),
            "shadow_diff_total": float(self.shadow_diff_total),
            "shadow_diff_rate": float(self.shadow_diff_rate()),
            "canary_crashes": float(self.canary_crashes),
            "last_recovery_s": float(self.last_recovery_s or 0.0),
        }

    def export_to(self, registry, name="Rollout"):
        """Pull gauges under ``Rollout/*`` so the SLO engine and the
        fleet collector can alert on a stuck or flapping rollout."""
        registry.gauge_fn(name, self.snapshot,
                          help="weight-rollout state machine counters")
        return registry
