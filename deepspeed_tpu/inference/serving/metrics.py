"""Serving metrics: tokens/s, TTFT, queue depth, batch occupancy.

Recorded through the SAME ``monitor_from_config`` backends the training
engines use (tensorboard/csv/both), so a serving deployment's dashboards
come from the one construction path — a new monitor backend lights up
here for free. All aggregation is host-side and O(1) per scheduler
iteration; with no monitor configured the recorder is still useful as a
cheap in-process stats object (``snapshot()``).
"""

import time


class ServingMetrics:
    """Aggregates serving counters and forwards gauges to a monitor."""

    def __init__(self, monitor=None):
        self.monitor = monitor
        self.decode_steps = 0
        self.tokens_emitted = 0
        self.requests_completed = 0
        self.requests_timed_out = 0
        self.decode_time_s = 0.0
        # TTFT: time from submit() to the request's first token
        self._ttft_sum = 0.0
        self._ttft_count = 0
        self._ttft_max = 0.0
        self._started = time.monotonic()

    # -- recording hooks (engine calls these) ---------------------------
    def record_first_token(self, ttft_s):
        self._ttft_sum += ttft_s
        self._ttft_count += 1
        self._ttft_max = max(self._ttft_max, ttft_s)
        self._record("Serving/ttft_s", ttft_s, self._ttft_count)

    def record_completion(self):
        self.requests_completed += 1

    def record_timeout(self):
        self.requests_timed_out += 1

    def record_step(self, queue_depth, active_slots, max_slots,
                    tokens_this_step, step_s):
        self.decode_steps += 1
        self.tokens_emitted += tokens_this_step
        self.decode_time_s += step_s
        step = self.decode_steps
        self._record("Serving/queue_depth", queue_depth, step)
        self._record("Serving/batch_occupancy",
                     active_slots / max_slots if max_slots else 0.0, step)
        if step_s > 0:
            self._record("Serving/tokens_per_sec",
                         tokens_this_step / step_s, step)

    def _record(self, tag, value, step):
        if self.monitor is not None:
            self.monitor.record(tag, value, step)

    # -- reading --------------------------------------------------------
    def avg_ttft_s(self):
        return self._ttft_sum / self._ttft_count if self._ttft_count else None

    def tokens_per_sec(self):
        """Decode-loop throughput (excludes idle wall time between
        requests — the number a capacity planner wants)."""
        if self.decode_time_s <= 0:
            return None
        return self.tokens_emitted / self.decode_time_s

    def snapshot(self):
        return {
            "decode_steps": self.decode_steps,
            "tokens_emitted": self.tokens_emitted,
            "requests_completed": self.requests_completed,
            "requests_timed_out": self.requests_timed_out,
            "tokens_per_sec": self.tokens_per_sec(),
            "avg_ttft_s": self.avg_ttft_s(),
            "max_ttft_s": self._ttft_max if self._ttft_count else None,
            "uptime_s": time.monotonic() - self._started,
        }

    def close(self):
        if self.monitor is not None:
            self.monitor.flush()
