"""Degraded-mode ladder: shed capability before shedding requests.

ZeRO-Infinity's design principle — walk down a resource hierarchy
instead of failing — applied to overload. When the fleet is saturated
and there is no scale-up headroom left, the engine/router pair climbs a
small ladder of *capability* concessions, one rung at a time, and walks
back down the same way once pressure clears:

====  ==============  ====================================================
rung  name            effect
====  ==============  ====================================================
0     healthy         full service
1     spec_off        speculative decoding disabled (k -> 0). Safe at any
                      moment: drafts are verified against the oracle
                      forward, so turning the drafter off changes
                      throughput, never output bits.
2     budget_shrink   rung 1 + prefix-cache inserts paused and the
                      admission queue budget halved — less host RAM/work
                      per admitted request, earlier backpressure.
3     class_shed      rung 2 + the router sheds the configured request
                      classes at the door (``FleetOverloadError``) so the
                      protected classes keep their latency.
====  ==============  ====================================================

The ladder itself is a tiny hysteresis state machine: ``update(pressure)``
escalates one rung after ``escalate_after_s`` of sustained pressure and
recovers one rung after ``recover_after_s`` of sustained quiet — never
two rungs at once, so a pressure blip cannot slam the fleet to rung 3
and a recovery overshoot cannot flap. Every transition is edge-triggered:
one ``fleet/degrade_rung`` telemetry instant per change, not per step.

Stdlib-only on purpose: the router imports this module and the router
must never pay a jax import. Telemetry is imported lazily (the package
is stdlib-only too) and only when it is already loaded in-process, so a
bare Router keeps its import graph unchanged.
"""

import sys
import threading
import time

from deepspeed_tpu.inference.serving.config import DegradeConfig

RUNGS = ("healthy", "spec_off", "budget_shrink", "class_shed")
MAX_RUNG = len(RUNGS) - 1


def rung_name(rung):
    return RUNGS[max(0, min(int(rung), MAX_RUNG))]


class DegradeLadder:
    """Hysteresis state machine over the degrade rungs.

    ``update(pressure)`` is the automatic driver (call it once per
    engine step / autoscaler tick — host-only, a few comparisons);
    ``set_rung`` is the external override (the autoscaler pushing the
    fleet to a rung, a test pinning one). Both are edge-triggered
    through the same ``_change`` path, so the telemetry story is
    identical no matter who moved the ladder.
    """

    def __init__(self, config=None, on_change=None, name="engine",
                 clock=time.monotonic):
        self.config = config or DegradeConfig(enabled=True)
        self.name = str(name)
        self.rung = 0
        self._on_change = on_change
        self._clock = clock
        self._lock = threading.Lock()
        self._pressure_since = None
        self._quiet_since = None
        self.transitions = 0            # lifetime rung changes (tests/bench)

    # -- automatic driver ------------------------------------------------
    def update(self, pressure, now=None):
        """One observation of the pressure signal; returns the (possibly
        changed) rung. Escalation and recovery both move ONE rung per
        sustained window — the window clock re-arms at each change."""
        now = self._clock() if now is None else now
        with self._lock:
            if pressure:
                self._quiet_since = None
                if self._pressure_since is None:
                    self._pressure_since = now
                if (self.rung < MAX_RUNG
                        and now - self._pressure_since
                        >= self.config.escalate_after_s):
                    self._change(self.rung + 1, "pressure")
                    self._pressure_since = now
            else:
                self._pressure_since = None
                if self._quiet_since is None:
                    self._quiet_since = now
                if (self.rung > 0
                        and now - self._quiet_since
                        >= self.config.recover_after_s):
                    self._change(self.rung - 1, "recovered")
                    self._quiet_since = now
            return self.rung

    # -- external override -----------------------------------------------
    def set_rung(self, rung, reason="forced"):
        """Jump to ``rung`` (clamped). Resets the hysteresis clocks so
        the automatic driver doesn't immediately undo the override."""
        rung = max(0, min(int(rung), MAX_RUNG))
        with self._lock:
            self._pressure_since = None
            self._quiet_since = None
            if rung != self.rung:
                self._change(rung, reason)
            return self.rung

    # -- internals ---------------------------------------------------------
    def _change(self, new, reason):
        # caller holds the lock
        old = self.rung
        self.rung = new
        self.transitions += 1
        self._note(old, new, reason)
        if self._on_change is not None:
            self._on_change(old, new, reason)

    def _note(self, old, new, reason):
        """One edge-triggered ``fleet/degrade_rung`` instant per change.
        Lazy like the supervisor's: only when telemetry is already
        loaded in-process, so the router's import graph stays jax- and
        telemetry-free."""
        if "deepspeed_tpu.telemetry" not in sys.modules:
            return
        try:
            from deepspeed_tpu import telemetry
            telemetry.instant(
                "fleet/degrade_rung", cat="fleet",
                args={"ladder": self.name, "from": old, "to": new,
                      "from_name": rung_name(old), "to_name": rung_name(new),
                      "reason": reason})
        except Exception:
            pass                        # telemetry must never break serving

    def export_gauges(self, registry):
        """``Fleet/degrade_rung`` pull gauge (the SLO engine's and the
        chaos harness' convergence signal). Idempotent."""
        registry.gauge_fn(
            "Fleet/degrade_rung", lambda: float(self.rung),
            help="current degraded-mode ladder rung (0 = healthy)")
        return registry
