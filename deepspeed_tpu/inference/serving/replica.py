"""One serving-fleet replica: a ServingEngine behind a line-JSON socket.

The worker half of the fleet tier (router.py is the front-door). A
replica wraps ONE :class:`ServingEngine` in a TCP server speaking the
router's line-delimited JSON protocol, and owns the per-replica halves
of the robustness story:

- **idempotent submission**: requests are keyed by the router's
  idempotency key. A re-submitted key (the router re-routing after a
  wobble, or re-attaching after its own socket died) does NOT create a
  second generation — it attaches to the existing :class:`_Flight` and
  replays tokens from the requested ``from`` index. Greedy decoding is
  deterministic, so a DIFFERENT replica recomputing the same key yields
  the same bits; the ``from`` replay just skips what the router already
  delivered.
- **graceful drain**: SIGTERM (the supervisor's polite recycle, the
  ``PreemptionHandler`` signal contract) flips the engine's draining
  flag — new keys are rejected with ``{"rejected": "draining"}`` so the
  router re-routes them, while accepted work keeps decoding to
  completion (retries of ACCEPTED keys still attach, draining or not).
  When ``engine.pending()`` hits zero (or ``drain_timeout_s`` passes)
  the process exits ``EXIT_PREEMPTED`` so the supervisor restarts it
  without backoff.
- **fault arms**: the engine's :class:`ServingFaultInjector` fleet arms
  act here — ``kill_replica`` fires inside the decode step (hard
  death), ``slow_replica`` delays every socket reply, and
  ``reject_admission`` bounces submissions before they reach the
  engine.
- **health**: ``{"op": "health"}`` on the socket answers the same facts
  the telemetry ``/healthz`` endpoint serves (queue depth, active
  lanes, draining, loop liveness) plus ``process_cpu_s`` and
  ``tokens_total`` so the fleet bench can compute CPU-time-normalized
  throughput on core-starved machines. When the engine has a telemetry
  server (``DSTPU_TELEMETRY_PORT``), a "replica" provider is registered
  there too.

``replica_main()`` is the supervised worker entry point: it reads
``DSTPU_REPLICA_PORT`` / ``DSTPU_REPLICA_CONFIG``, builds a
deterministic model (``init_gpt2(cfg, seed)`` — every replica holds
bitwise-identical params), serves until SIGTERM, drains, and exits by
the supervisor's exit-code contract.
"""

import argparse
import json
import os
import queue
import signal
import socket
import sys
import threading
import time
from collections import OrderedDict

from deepspeed_tpu.inference.serving.handoff import (
    HandoffError,
    HandoffReceiver,
    HandoffSender,
)
from deepspeed_tpu.inference.serving.scheduler import (
    EngineDrainingError,
    QueueFullError,
    RequestTimeoutError,
)
from deepspeed_tpu.inference.serving.router import (
    PROTOCOL_VERSION,
    REPLICA_ROLES,
    read_line,
    send_line,
)

REPLICA_PORT_ENV = "DSTPU_REPLICA_PORT"
REPLICA_CONFIG_ENV = "DSTPU_REPLICA_CONFIG"

# completed flights kept for duplicate-submit replay before eviction
_FLIGHT_CACHE = 1024


class _Flight:
    """Idempotency record for one keyed request.

    Tokens fan out to every attached connection queue as the engine
    emits them; late attachments replay the prefix they ask for. The
    flight outlives its connections — a router whose socket died
    re-attaches by key and loses nothing."""

    def __init__(self, key):
        self.key = key
        self.lock = threading.Lock()
        self.tokens = []
        self.done = False
        self.error = None               # terminal error doc, or None
        self._queues = []

    def attach(self, start):
        """Subscribe from token index ``start``; returns a Queue of
        ("t", i, token) frames followed by one ("end",) frame."""
        q = queue.Queue()
        with self.lock:
            for i in range(max(0, int(start)), len(self.tokens)):
                q.put(("t", i, self.tokens[i]))
            if self.done:
                q.put(("end",))
            else:
                self._queues.append(q)
        return q

    def emit(self, token):
        with self.lock:
            i = len(self.tokens)
            self.tokens.append(int(token))
            for q in self._queues:
                q.put(("t", i, token))

    def finish(self, error_doc=None):
        with self.lock:
            self.done = True
            self.error = error_doc
            for q in self._queues:
                q.put(("end",))
            self._queues = []


def _error_doc(exc):
    doc = {"error": str(exc), "etype": type(exc).__name__}
    if isinstance(exc, RequestTimeoutError):
        doc["detail"] = {
            "request_id": exc.request_id, "timeout_s": exc.timeout_s,
            "phase": exc.phase, "tokens_done": exc.tokens_done}
    return doc


class ReplicaServer:
    """Line-JSON socket front on one ServingEngine (one op/connection)."""

    def __init__(self, engine, host="127.0.0.1", port=0, injector=None,
                 drain_timeout_s=30.0, role="mixed", handoff_config=None):
        role = str(role or "mixed")
        if role not in REPLICA_ROLES:
            raise ValueError(
                f"role must be one of {REPLICA_ROLES}, got {role!r}")
        self.engine = engine
        self.injector = injector if injector is not None else engine.injector
        self.drain_timeout_s = float(drain_timeout_s)
        self.role = role
        # handoff plumbing is always built (it is cheap and stateless
        # until used): a mixed replica may be the decode target of a
        # prefill worker, and a prefill worker only sends
        self._handoff_sender = HandoffSender(
            config=handoff_config, injector=self.injector)
        self._handoff_receiver = HandoffReceiver(
            handoff_config,
            allocate_fn=engine.handoff_claim,
            install_fn=engine.handoff_install,
            free_fn=engine.handoff_release,
            on_event=self._handoff_event)
        self._flights = OrderedDict()       # key -> _Flight
        self._flights_lock = threading.Lock()
        self._tokens_total = 0
        self._active_conns = 0              # submit handlers mid-stream
        self._accept_thread = None
        self._closing = threading.Event()
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, int(port)))
        self._lsock.listen(64)
        self.host, self.port = self._lsock.getsockname()[:2]
        if engine.telemetry_server is not None:
            engine.telemetry_server.add_health_provider(
                "replica", self._replica_health)

    # -- lifecycle -------------------------------------------------------
    def start(self, idle_sleep_s=0.001):
        self.engine.start(idle_sleep_s=idle_sleep_s)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="replica-accept", daemon=True)
        self._accept_thread.start()
        return self

    def begin_drain(self):
        """Stop admitting NEW keys (engine raises EngineDrainingError and
        the socket answers ``rejected: draining``); accepted work keeps
        decoding. The SIGTERM half of the drain sequence."""
        self.engine.begin_drain()

    def drain_and_stop(self):
        """Block until in-flight work finishes (or drain_timeout_s),
        then stop the loop. True = drained clean, False = timed out."""
        self.begin_drain()
        deadline = time.monotonic() + self.drain_timeout_s
        while self.engine.pending() > 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        clean = self.engine.pending() == 0
        # let in-stream connections flush their terminal frames: exiting
        # with a done-but-unsent frame would turn a clean drain into a
        # router-visible EOF (a pointless failure retry)
        while self._active_conns > 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        self.engine.stop()
        return clean

    def close(self):
        self._closing.set()
        try:
            # shutdown first: close() alone doesn't wake a thread blocked
            # in accept(), and the kernel socket would keep accepting
            self._lsock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._lsock.close()
        except OSError:
            pass
        if self._accept_thread is not None:
            self._accept_thread.join(2.0)
            self._accept_thread = None
        self.engine.close()

    # -- health ----------------------------------------------------------
    def _replica_health(self):
        eng = self.engine
        # the health probe doubles as the orphan reaper's heartbeat:
        # the router probes every replica on a TTL, so expired handoff
        # claims are freed even on an otherwise-idle decode worker
        self._handoff_receiver.reap()
        with self._flights_lock:
            flights = len(self._flights)
        doc = dict(eng._loop_health())
        doc.update({
            "port": self.port,
            "role": self.role,
            "flights": flights,
            "tokens_total": self._tokens_total,
            "process_cpu_s": time.process_time(),
            "pid": os.getpid(),
            # the chaos harness's zero-leak invariant reads these
            "kv_pool": eng.occupancy(),
            "handoff_pending": self._handoff_receiver.pending(),
            # the affinity test's evidence: hits survive scale-out
            "prefix_cache": eng.prefix_stats(),
            # spill tier + memory-pressure guard (memtier chaos reads it)
            "memtier": eng.memtier_stats()})
        return doc

    def _handoff_event(self, name):
        if name == "reaped":
            self.engine.metrics.record_handoff("reaped")

    # -- socket plumbing -------------------------------------------------
    def _accept_loop(self):
        while not self._closing.is_set():
            try:
                conn, _ = self._lsock.accept()
            except OSError:
                return                  # listener closed
            threading.Thread(target=self._serve_conn, args=(conn,),
                             name="replica-conn", daemon=True).start()

    def _reply(self, conn, doc):
        """Send one frame, honoring the slow_replica arm's delay."""
        if self.injector is not None:
            delay = self.injector.reply_delay_s()
            if delay > 0:
                time.sleep(delay)
        send_line(conn, doc)

    def _serve_conn(self, conn):
        try:
            with conn:
                conn.settimeout(30.0)
                # ONE buffered stream per connection: the handoff op's
                # binary page frames follow the claim line on the same
                # socket, so bytes the line reader buffered must stay
                # readable (a second makefile would lose them)
                stream = conn.makefile("rb")
                op = read_line(stream)
                if op is None:
                    return
                kind = op.get("op")
                if kind == "submit":
                    self._active_conns += 1
                    try:
                        self._handle_submit(conn, op)
                    finally:
                        self._active_conns -= 1
                elif kind == "handoff":
                    self._handoff_receiver.handle(
                        conn, stream, op, self._handoff_reply)
                elif kind == "health":
                    self._reply(conn, self._replica_health())
                elif kind == "drain":
                    self.begin_drain()
                    self._reply(conn, {"draining": True,
                                       "pending": self.engine.pending()})
                elif kind == "degrade":
                    rung = self.engine.set_degrade_rung(
                        int(op.get("rung", 0)),
                        reason=str(op.get("reason", "fleet")))
                    self._reply(conn, {"rung": rung})
                elif kind == "inject":
                    self._handle_inject(conn, op)
                else:
                    self._reply(conn, {"error": f"unknown op {kind!r}",
                                       "etype": "ValueError"})
        except (OSError, ValueError):
            pass                        # peer went away mid-reply

    def _handoff_reply(self, conn, doc):
        """Handoff-op replies, plus the kill-decode-post-ack arm: the
        injected death fires AFTER the ack hit the wire — the prefill
        side believes the transfer landed, then the resume target
        disappears."""
        self._reply(conn, doc)
        if doc.get("acked") and self.injector is not None:
            self.injector.maybe_kill_post_ack()

    # -- the inject op (the chaos harness's remote arm) ------------------
    def _handle_inject(self, conn, op):
        """Arm/disarm a serving fault point over the socket so the chaos
        harness can slow/reject/kill a LIVE replica without reaching into
        its process. ``{"op": "inject", "point": null}`` disarms all;
        any other keys ride through as arm kwargs."""
        if self.injector is None:
            self._reply(conn, {"error": "replica built without injector",
                               "etype": "RuntimeError"})
            return
        point = op.get("point")
        try:
            if point is None or point == "disarm":
                self.injector.disarm_serving(op.get("only"))
                self._reply(conn, {"disarmed": True})
                return
            kwargs = {k: v for k, v in op.items() if k not in ("op", "point")}
            self.injector.arm_serving(str(point), **kwargs)
            self._reply(conn, {"armed": str(point)})
        except (ValueError, TypeError) as e:
            self._reply(conn, _error_doc(e))

    # -- the submit op ---------------------------------------------------
    def _handle_submit(self, conn, op):
        key = str(op.get("key", ""))
        start = int(op.get("from", 0))
        if not key:
            self._reply(conn, {"error": "submit without key",
                               "etype": "ValueError"})
            return
        if op.get("handoff_key"):
            self._handle_resume(conn, op)
            return
        if op.get("handoff"):
            self._handle_submit_handoff(conn, op)
            return
        if self.role == "decode" and not op.get("force"):
            # role is a scheduling policy, not a capability: the router
            # learns/refreshes this endpoint's role from the rejection
            # and re-picks; a deliberate degraded-mode route carries
            # "force" and is served. Retries of accepted keys attach.
            with self._flights_lock:
                accepted = key in self._flights
            if not accepted:
                self._reply(conn, {"rejected": "wrong_role",
                                   "role": self.role})
                return
        flight, created = self._flight_for(key, op, conn)
        if flight is None:
            return                      # rejection/error already sent
        self._stream_flight(conn, flight, start)

    def _stream_flight(self, conn, flight, start):
        """Drain a flight's frames to the connection: tokens, then ONE
        terminal doc — the flight's error/terminal doc if set (a timeout
        doc, a ``handoff_done``/``handoff_failed`` verdict), else plain
        ``done``."""
        q = flight.attach(start)
        while True:
            frame = q.get()
            if frame[0] == "end":
                if flight.error is not None:
                    self._reply(conn, flight.error)
                else:
                    self._reply(conn, {"done": True,
                                       "n": len(flight.tokens)})
                return
            _, i, token = frame
            self._reply(conn, {"t": token, "i": i})

    # -- disaggregated handoff: hop 1 (prefill side) ---------------------
    def _handle_submit_handoff(self, conn, op):
        """Prefill-only submit: run prefill, stream the first token the
        moment it exists (TTFT ends BEFORE any page transfer), then ship
        the exported pages to the decode worker named in
        ``op["handoff"]`` and reply ``handoff_done`` (the router's cue
        to resume on the decode side) or ``handoff_failed`` (its cue to
        fall back to a plain route). Flights are keyed by the
        per-attempt handoff key, NEVER the request key — a 1-token
        hop-1 flight must not satisfy a later full re-route."""
        ho = dict(op.get("handoff") or {})
        hkey = str(ho.get("key") or "")
        if not hkey or not ho.get("host") or not ho.get("port"):
            self._reply(conn, {"error": "handoff without host/port/key",
                               "etype": "ValueError"})
            return
        fkey = "ho1:" + hkey
        with self._flights_lock:
            flight = self._flights.get(fkey)
        if flight is None:
            if self.injector is not None \
                    and self.injector.admission_rejected():
                self._reply(conn, {"rejected": "injected"})
                return
            flight = _Flight(fkey)
            try:
                req = self.engine.submit_handoff(
                    op.get("prompt") or [],
                    reserve_new_tokens=int(op.get("max_new_tokens") or 1),
                    eos_token_id=op.get("eos_token_id"),
                    timeout_s=op.get("timeout_s"),
                    stream_cb=lambda _rid, tok: self._emit(flight, tok),
                    age_s=float(op.get("age_s", 0.0)))
            except EngineDrainingError:
                self._reply(conn, {"rejected": "draining"})
                return
            except QueueFullError:
                self._reply(conn, {"rejected": "queue_full"})
                return
            except (ValueError, TypeError) as e:
                self._reply(conn, _error_doc(e))
                return
            self._register_flight(fkey, flight)
            threading.Thread(
                target=self._await_handoff, args=(flight, req, ho, op),
                name=f"handoff-{hkey[:8]}", daemon=True).start()
        self._stream_flight(conn, flight, int(op.get("from", 0)))

    def _await_handoff(self, flight, req, ho, op):
        """Hop-1 completion driver: wait for the prefill-only request to
        retire, then run the claim→transfer→ack protocol against the
        decode worker and publish the verdict as the flight's terminal
        doc."""
        try:
            tokens = req.future.result()
        except Exception as e:          # timeout/terminal: plain error
            flight.finish(_error_doc(e))
            return
        first = int(tokens[0])
        eos = op.get("eos_token_id")
        max_new = int(op.get("max_new_tokens") or 1)
        if max_new <= 1 or (eos is not None and first == int(eos)):
            flight.finish()             # complete at its first token
            return
        payload = getattr(req, "export_payload", None)
        if payload is None:
            exc = getattr(req, "export_error", None)
            flight.finish({"handoff_failed": True, "key": ho.get("key"),
                           "etype": "HandoffError",
                           "error": f"lane export missing: {exc}",
                           "n": len(flight.tokens)})
            return
        meta, frames = payload
        meta = dict(meta)
        prompt = op.get("prompt") or []
        meta["reserve_tokens"] = min(len(prompt) + max_new,
                                     self.engine.max_seq_len)
        meta["first_token"] = first
        meta["prompt_len"] = len(prompt)
        try:
            self._handoff_sender.send(
                str(ho["host"]), int(ho["port"]), str(ho["key"]),
                meta, frames)
        except (HandoffError, OSError) as e:
            flight.finish({"handoff_failed": True, "key": ho.get("key"),
                           "etype": type(e).__name__, "error": str(e),
                           "n": len(flight.tokens)})
            return
        flight.finish({"handoff_done": True, "key": ho.get("key"),
                       "n": len(flight.tokens)})

    # -- disaggregated handoff: hop 2 (decode side) ----------------------
    def _handle_resume(self, conn, op):
        """Resume a request whose pages an earlier handoff installed:
        take the installed claim, activate the lane, and stream tokens
        from index 1 (index 0 — the first token — was delivered by the
        prefill worker; the flight is pre-seeded with it so the done
        count covers the whole generation)."""
        hkey = str(op.get("handoff_key"))
        fkey = "ho2:" + hkey
        with self._flights_lock:
            flight = self._flights.get(fkey)
        if flight is None:
            taken = self._handoff_receiver.take(hkey)
            if taken is None:
                # unknown/unfinished/reaped claim: the router re-routes
                # the whole request as a plain submit, losing nothing
                self._reply(conn, {"rejected": "handoff_unknown"})
                return
            slot, meta = taken
            flight = _Flight(fkey)
            first = int(meta.get("first_token",
                                 op.get("first_token", 0)))
            flight.tokens = [first]     # index 0, delivered by hop 1
            try:
                req = self.engine.resume_handoff(
                    slot, op.get("prompt") or [], first,
                    max_new_tokens=op.get("max_new_tokens"),
                    eos_token_id=op.get("eos_token_id"),
                    timeout_s=op.get("timeout_s"),
                    stream_cb=lambda _rid, tok: self._emit(flight, tok),
                    age_s=float(op.get("age_s", 0.0)))
            except Exception as e:      # resume failed pre-activation:
                self._handoff_receiver.restore(hkey, slot, meta)
                self._reply(conn, _error_doc(e))
                return
            self._register_flight(fkey, flight)
            threading.Thread(target=self._await, args=(flight, req.future),
                             name=f"resume-{hkey[:8]}", daemon=True).start()
        self._stream_flight(conn, flight, int(op.get("from", 1)))

    def _register_flight(self, key, flight):
        with self._flights_lock:
            self._flights[key] = flight
            while len(self._flights) > _FLIGHT_CACHE:
                old_key, old = next(iter(self._flights.items()))
                if not old.done:
                    break               # never evict live work
                self._flights.pop(old_key)

    def _flight_for(self, key, op, conn):
        """Existing flight for ``key``, or a freshly-submitted one.
        Returns (flight, created); (None, False) after replying with a
        rejection/terminal error. Injected/draining rejections apply
        only to NEW keys: a retry of accepted work always attaches."""
        with self._flights_lock:
            flight = self._flights.get(key)
            if flight is not None:
                self._flights.move_to_end(key)
                return flight, False
        if self.injector is not None and self.injector.admission_rejected():
            self._reply(conn, {"rejected": "injected"})
            return None, False
        flight = _Flight(key)
        try:
            future = self.engine.submit(
                op.get("prompt") or [],
                max_new_tokens=op.get("max_new_tokens"),
                eos_token_id=op.get("eos_token_id"),
                timeout_s=op.get("timeout_s"),
                stream_cb=lambda _rid, tok: self._emit(flight, tok),
                age_s=float(op.get("age_s", 0.0)))
        except EngineDrainingError:
            self._reply(conn, {"rejected": "draining"})
            return None, False
        except QueueFullError:
            self._reply(conn, {"rejected": "queue_full"})
            return None, False
        except (ValueError, TypeError) as e:
            self._reply(conn, _error_doc(e))
            return None, False
        # registering after engine.submit is race-free: the router runs
        # one attempt per request at a time, so no concurrent FIRST
        # submit for this key exists; tokens can't be missed because
        # emission goes through the flight from token zero.
        self._register_flight(key, flight)
        threading.Thread(target=self._await, args=(flight, future),
                         name=f"flight-{key[:8]}", daemon=True).start()
        return flight, True

    def _emit(self, flight, token):
        self._tokens_total += 1
        flight.emit(token)

    def _await(self, flight, future):
        try:
            future.result()
        except Exception as e:          # terminal verdict rides the doc
            flight.finish(_error_doc(e))
            return
        flight.finish()


def _build_engine(spec):
    """Deterministic engine from a replica-config spec: every replica
    built from the same spec holds bitwise-identical params, which is
    what makes cross-replica retry bitwise-safe."""
    from deepspeed_tpu.inference.serving.engine import ServingEngine
    from deepspeed_tpu.models.gpt2 import GPT2Config, init_gpt2

    model = dict(spec.get("model") or {})
    model.setdefault("hidden_dropout_prob", 0.0)
    model.setdefault("attention_probs_dropout_prob", 0.0)
    cfg = GPT2Config(**model)
    _, params = init_gpt2(cfg, batch_size=1, seq_len=8,
                          seed=int(spec.get("seed", 0)))
    injector = None
    if spec.get("chaos"):
        # chaos-harness replicas carry an (unarmed) injector so the
        # "inject" socket op can arm fault points at runtime; normal
        # fleet replicas stay injector-free (an injector claims full
        # lanes in _alloc_tokens, which changes packing behavior)
        from deepspeed_tpu.inference.serving.fault_injection import (
            ServingFaultInjector,
        )
        injector = ServingFaultInjector()
    return ServingEngine.from_config(
        params, cfg, dict(spec.get("ds_config") or {}),
        rank=int(os.environ.get("RANK", "0")),
        injector=injector)


def replica_main(argv=None):
    """Supervised fleet-worker entry point.

    Config comes from ``--config`` / ``DSTPU_REPLICA_CONFIG`` (a JSON
    file: ``{"model": {...GPT2Config kwargs...}, "seed": 0,
    "ds_config": {...}}``); the serving port from ``--port`` /
    ``DSTPU_REPLICA_PORT``. Prints one ``{"ready": true, "port": N}``
    line to stdout once listening (the launcher/bench reads it), then
    serves until SIGTERM -> drain -> ``EXIT_PREEMPTED``."""
    from deepspeed_tpu.launcher.supervisor import EXIT_CLEAN, EXIT_PREEMPTED

    parser = argparse.ArgumentParser(description="serving-fleet replica")
    parser.add_argument("--config",
                        default=os.environ.get(REPLICA_CONFIG_ENV))
    parser.add_argument(
        "--port", type=int,
        default=int(os.environ.get(REPLICA_PORT_ENV, "0")))
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument(
        "--role", default=None, choices=list(REPLICA_ROLES),
        help="disaggregated-serving role (default: spec['role'] or mixed)")
    args = parser.parse_args(argv)
    if not args.config:
        parser.error(f"--config or {REPLICA_CONFIG_ENV} is required")
    with open(args.config) as f:
        spec = json.load(f)

    engine = _build_engine(spec)
    fleet = dict(spec.get("ds_config", {}).get("fleet") or {})
    handoff_config = None
    if fleet.get("handoff") is not None:
        from deepspeed_tpu.runtime.config import _get_fleet_handoff
        handoff_config = _get_fleet_handoff(fleet)
    server = ReplicaServer(
        engine, host=args.host, port=args.port,
        drain_timeout_s=float(fleet.get("drain_timeout_s", 30.0)),
        role=args.role or spec.get("role") or "mixed",
        handoff_config=handoff_config)

    # PreemptionHandler's signal discipline, serving-shaped: the handler
    # only flips a flag; the main thread notices and drains. check() is
    # the TRAINING drain (checkpoint + exit) so the replica runs its own.
    term = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: term.set())
    signal.signal(signal.SIGINT, lambda *_: term.set())

    server.start()
    print(json.dumps({"ready": True, "port": server.port,
                      "pid": os.getpid(), "role": server.role,
                      "v": PROTOCOL_VERSION}),
          flush=True)
    try:
        while not term.is_set():
            term.wait(0.1)
        drained = server.drain_and_stop()
        print(json.dumps({"drained": bool(drained)}), flush=True)
        return EXIT_PREEMPTED
    finally:
        server.close()
    return EXIT_CLEAN


if __name__ == "__main__":
    sys.exit(replica_main())
