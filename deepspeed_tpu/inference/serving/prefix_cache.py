"""Prefix KV cache: skip recomputing shared prompt prefixes entirely.

Serving traffic repeats prompt *prefixes* — system prompts, few-shot
preambles, multi-turn histories. Their keys/values are a pure function
of the token prefix, so a request whose prompt starts with a
previously-served prefix can seed its KV cache from memory and prefill
only the suffix.

Design (host-side, no jax):

- a **token trie** indexes every stored prompt; lookup walks the query
  prompt token by token and returns the LONGEST match against any
  stored entry (a stored prompt's KV covers every prefix of itself —
  the match slices ``entry.k[:, :, :match_len]``);
- entries are **ref-counted**: the engine acquires a ref when a request
  seeds from an entry and releases it at retirement (any path — EOS,
  length, deadline, stuck-request reap), so eviction can never pull KV
  out from under an in-flight admission;
- **LRU eviction under a byte budget**: inserts evict
  least-recently-used *unreferenced* entries until the new entry fits;
  an entry that can never fit (bigger than the whole budget) is
  rejected;
- **hit/miss/evict counters** feed ``Serving/PrefixHitRate``.

Entries hold NUMPY arrays (shape [L, nh, P, hd]): host RAM is the cheap
pool, and the engine assembles the seeded device cache in one transfer
per admission batch — a deliberate host-device copy traded against
recomputing the prefix.

Memory tiering (the ZeRO-Offload / ZeRO-Infinity hierarchy brought to
serving): with a ``SpillStore`` attached, eviction DEMOTES entries
instead of destroying them — the already-quantized bytes move into a
host-RAM tier of crc32-framed blobs under its own byte budget, whose
own LRU overflow demotes once more to an optional disk tier written
with the checkpoint discipline (tmp -> fsync -> rename). A later
lookup that would miss the live trie but hits a spilled prefix
verifies the checksum and PROMOTES the entry back — one host decode
instead of re-prefilling thousands of shared tokens. A corrupt or torn
blob is dropped (counted, listener-notified), never an error: the
request falls through to a normal suffix prefill. ``MemoryPressureGuard``
watches host RSS against a watermark and sheds the spill tier first,
pauses live inserts second, and climbs the fleet ``DegradeLadder``
last, so host memory pressure becomes a degrade rung instead of an
OOM kill.
"""

import io
import json
import os
import threading
from collections import OrderedDict

from deepspeed_tpu.inference.serving.handoff import (
    HandoffFrameError,
    HandoffSizeError,
    read_frame,
    write_frame,
)
from deepspeed_tpu.inference.serving.kv_pool import (
    export_entry_frames,
    import_entry_frames,
)

# One spill blob is a handful of frames; entries are bounded by the live
# tier's budget, so this cap only guards against an insane length prefix
# from a corrupted header — not a tuning knob.
SPILL_MAX_FRAME_BYTES = 1 << 30


class PrefixEntry:
    """One stored prompt's KV plus its bookkeeping. In an int8-pool
    engine ``k``/``v`` are int8 with per-(layer, head) fp32 scales
    (``k_scale``/``v_scale``, None otherwise) — quartering the bytes an
    entry charges against the budget, dequantized at seed time."""

    __slots__ = ("tokens", "k", "v", "k_scale", "v_scale", "impl",
                 "nbytes", "refs", "last_used", "from_spill")

    def __init__(self, tokens, k, v, k_scale=None, v_scale=None,
                 impl="dense"):
        self.tokens = tokens                    # tuple[int]
        self.k = k                              # np [L, nh, P, hd]
        self.v = v
        self.k_scale = k_scale                  # np [L, nh, 1, 1] | None
        self.v_scale = v_scale
        # Attention backend that produced this KV. Flash is math-equal to
        # dense but layers >= 2 see low-bit hidden-state drift, and the
        # sparse window attends to different keys outright — seeding one
        # backend's lane from another's entry would break the per-backend
        # bitwise oracle, so lookups are segregated by impl.
        self.impl = impl
        self.nbytes = int(k.nbytes) + int(v.nbytes)
        if k_scale is not None:
            self.nbytes += int(k_scale.nbytes) + int(v_scale.nbytes)
        self.refs = 0
        self.last_used = 0
        # set when a lookup just promoted this entry out of the spill
        # tier; consumed by the first counted acquire() so SpillHitRate
        # attributes exactly one hit per promotion
        self.from_spill = False


class _Node:
    __slots__ = ("children", "covering")

    def __init__(self):
        self.children = {}                      # token -> _Node
        self.covering = set()                   # entries passing through


class _ByteSink:
    """Adapter so the handoff codec's ``write_frame`` (which expects a
    socket-like ``sendall``) can frame into a host buffer."""

    __slots__ = ("buf",)

    def __init__(self):
        self.buf = bytearray()

    def sendall(self, data):
        self.buf += data


def encode_spill_blob(entry):
    """Serialize a ``PrefixEntry`` into one self-describing blob: a JSON
    meta frame followed by the entry's array frames, each length-prefixed
    and crc32'd by the PR 17 handoff codec — the integrity story the
    handoff lane already proved, reused byte-for-byte."""
    meta, frames = export_entry_frames(entry.k, entry.v,
                                       entry.k_scale, entry.v_scale)
    meta["impl"] = entry.impl
    meta["tokens"] = list(entry.tokens)
    sink = _ByteSink()
    write_frame(sink, json.dumps(meta).encode("utf-8"),
                max_bytes=SPILL_MAX_FRAME_BYTES)
    for payload in frames:
        write_frame(sink, payload, max_bytes=SPILL_MAX_FRAME_BYTES)
    return bytes(sink.buf)


def decode_spill_blob(blob):
    """Rebuild a ``PrefixEntry`` from ``encode_spill_blob`` output,
    verifying every frame's length prefix and crc32. Raises
    ``HandoffFrameError``/``HandoffSizeError``/``ValueError`` on any
    truncation, bit flip, or shape/byte-count disagreement — the caller
    (``SpillStore.take``) turns every failure into a dropped entry,
    never an error to the serving path."""
    stream = io.BytesIO(blob)
    meta = json.loads(
        read_frame(stream, max_bytes=SPILL_MAX_FRAME_BYTES).decode("utf-8"))
    n_frames = 4 if meta.get("scales") else 2
    frames = [read_frame(stream, max_bytes=SPILL_MAX_FRAME_BYTES)
              for _ in range(n_frames)]
    if stream.read(1):
        raise HandoffFrameError("trailing bytes after spill entry frames")
    k, v, k_scale, v_scale = import_entry_frames(meta, frames)
    tokens = tuple(int(t) for t in meta["tokens"])
    if not tokens:
        raise ValueError("spill entry carries an empty token key")
    return PrefixEntry(tokens, k, v, k_scale=k_scale, v_scale=v_scale,
                       impl=str(meta["impl"]))


class _SpillRecord:
    __slots__ = ("nbytes", "blob", "path")

    def __init__(self, nbytes, blob=None, path=None):
        self.nbytes = int(nbytes)
        self.blob = blob            # bytearray (RAM tier) | None
        self.path = path            # final file path (disk tier) | None


class SpillStore:
    """Demotion tier for evicted prefix entries: crc32-framed blobs in
    host RAM under ``budget_bytes``, whose own LRU overflow demotes to
    an optional disk directory (atomic tmp/fsync/rename writes — a
    reader never sees a torn file under its final name unless the write
    itself was injected torn, which the framing then catches on load).

    Integrity contract: ``take()`` either returns a bitwise-verified
    entry or drops the record and reports ``spill_corrupt`` — it NEVER
    raises to the serving path and never serves unverified bytes.
    """

    def __init__(self, budget_bytes, spill_dir=None, listener=None):
        if budget_bytes < 1:
            raise ValueError(
                f"budget_bytes must be >= 1, got {budget_bytes}")
        self.budget_bytes = int(budget_bytes)
        self.spill_dir = spill_dir
        self._listener = listener
        # key (impl,)+tokens -> _SpillRecord, LRU order (oldest first)
        self._records = OrderedDict()
        self._lock = threading.RLock()
        self._seq = 0               # unique disk filenames
        self.ram_bytes = 0
        self.disk_bytes = 0
        self.demotions = 0          # entries accepted from the live tier
        self.disk_demotions = 0     # RAM records pushed to the disk tier
        self.promotions = 0         # records handed back via take()
        self.corrupt_dropped = 0    # failed verification on take()
        self.rejections = 0         # blobs that could not be kept at all
        self.sheds = 0
        # fault surface: a truthy return makes the NEXT disk write land
        # torn (truncated, under its final name — simulating a crash
        # mid-write without the atomic rename discipline). Wired to
        # ``ServingFaultInjector.torn_spill_write`` by the engine.
        self.torn_write_hook = None
        if spill_dir is not None:
            os.makedirs(spill_dir, exist_ok=True)

    # -- store / lookup -------------------------------------------------
    def put(self, entry):
        """Demote ``entry`` into the tier. Returns True when stored
        (RAM), False when it could not be kept (bigger than the whole
        budget and no disk tier, or a failed disk write)."""
        blob = encode_spill_blob(entry)
        key = (entry.impl,) + entry.tokens
        with self._lock:
            self._discard_locked(key)
            if len(blob) > self.budget_bytes:
                # never fits in RAM: straight to disk or gone
                if self._write_disk_locked(key, blob):
                    self.demotions += 1
                    return True
                self.rejections += 1
                return False
            while self.ram_bytes + len(blob) > self.budget_bytes:
                victim = next((k for k, r in self._records.items()
                               if r.blob is not None), None)
                if victim is None:
                    break
                self._demote_to_disk_locked(victim)
            rec = _SpillRecord(len(blob), blob=bytearray(blob))
            self._records[key] = rec
            self.ram_bytes += rec.nbytes
            self.demotions += 1
            return True

    def match(self, tokens, impl="dense"):
        """Longest stored key produced by ``impl`` that is a prefix of
        ``tokens``: (match_len, key) or (0, None). Pure — verification
        and removal happen in ``take``."""
        toks = tuple(int(t) for t in tokens)
        best_len, best_key = 0, None
        with self._lock:
            for key in self._records:
                if key[0] != impl:
                    continue
                stored = key[1:]
                n = len(stored)
                if n > best_len and n <= len(toks) and toks[:n] == stored:
                    best_len, best_key = n, key
        return best_len, best_key

    def take(self, key):
        """Remove ``key``'s record, verify every frame checksum, and
        return the rebuilt ``PrefixEntry`` — or None when the record is
        corrupt/torn/missing (dropped + counted + listener-notified;
        the caller falls through to a normal prefill)."""
        with self._lock:
            rec = self._records.pop(key, None)
            if rec is None:
                return None
            blob = self._load_locked(rec)
        if blob is None:
            self._note_corrupt()
            return None
        try:
            entry = decode_spill_blob(bytes(blob))
        except (HandoffFrameError, HandoffSizeError, ValueError, KeyError):
            self._note_corrupt()
            return None
        if (entry.impl,) + entry.tokens != key:
            # decoded cleanly but describes a different prefix: treat a
            # lying-but-self-consistent blob exactly like a torn one
            self._note_corrupt()
            return None
        with self._lock:
            self.promotions += 1
        return entry

    def discard(self, key):
        """Drop ``key``'s record without verification (e.g. the live
        tier just re-inserted the same prefix)."""
        with self._lock:
            self._discard_locked(key)

    def shed(self):
        """Drop every record, both tiers (the first memory-pressure
        response and the chaos ``host_mem_pressure`` action). Returns
        how many records were shed."""
        with self._lock:
            n = len(self._records)
            for key in list(self._records):
                self._discard_locked(key)
            if n:
                self.sheds += 1
            return n

    # -- fault surface ---------------------------------------------------
    def corrupt_one(self):
        """Flip one payload byte in the most-recently-stored record (RAM
        blob mutated in place; disk file rewritten) — the
        ``corrupt_spill_entry`` fault arm. Returns the corrupted key or
        None when the tier is empty. The flipped byte sits past both
        frame headers, so the next ``take`` fails its crc32, not its
        length prefix."""
        with self._lock:
            for key in reversed(self._records):
                rec = self._records[key]
                blob = self._peek_locked(rec)
                if blob is None:
                    continue
                flipped = bytearray(blob)
                flipped[len(flipped) // 2] ^= 0xFF
                if rec.blob is not None:
                    rec.blob = flipped
                else:
                    try:
                        with open(rec.path, "wb") as f:
                            f.write(bytes(flipped))
                    except OSError:
                        continue
                return key
            return None

    # -- internals -------------------------------------------------------
    def _note_corrupt(self):
        with self._lock:
            self.corrupt_dropped += 1
        if self._listener is not None:
            self._listener("spill_corrupt")

    def _discard_locked(self, key):
        rec = self._records.pop(key, None)
        if rec is None:
            return
        if rec.blob is not None:
            self.ram_bytes -= rec.nbytes
        else:
            self.disk_bytes -= rec.nbytes
            try:
                os.remove(rec.path)
            except OSError:
                pass

    def _peek_locked(self, rec):
        """Read a record's bytes WITHOUT touching accounting or removing
        anything (the fault surface mutates records in place)."""
        if rec.blob is not None:
            return rec.blob
        try:
            with open(rec.path, "rb") as f:
                return f.read()
        except OSError:
            return None

    def _load_locked(self, rec):
        if rec.blob is not None:
            self.ram_bytes -= rec.nbytes
            return rec.blob
        self.disk_bytes -= rec.nbytes
        try:
            with open(rec.path, "rb") as f:
                blob = f.read()
        except OSError:
            blob = None
        try:
            os.remove(rec.path)
        except OSError:
            pass
        return blob

    def _demote_to_disk_locked(self, key):
        rec = self._records.pop(key)
        self.ram_bytes -= rec.nbytes
        if self._write_disk_locked(key, bytes(rec.blob)):
            self.disk_demotions += 1

    def _write_disk_locked(self, key, blob):
        if self.spill_dir is None:
            return False
        self._seq += 1
        path = os.path.join(self.spill_dir, f"spill-{self._seq:08d}.bin")
        torn = self.torn_write_hook is not None and self.torn_write_hook()
        try:
            if torn:
                # injected crash mid-write: a truncated file appears
                # under its FINAL name — exactly what the atomic rename
                # protocol prevents — so reload must catch it by framing
                with open(path, "wb") as f:
                    f.write(blob[:max(1, len(blob) // 2)])
            else:
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(blob)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
        except OSError:
            return False
        rec = _SpillRecord(len(blob), path=path)
        self._records[key] = rec
        self.disk_bytes += rec.nbytes
        return True

    # -- stats -----------------------------------------------------------
    def __len__(self):
        with self._lock:
            return len(self._records)

    def stats(self):
        with self._lock:
            ram = sum(1 for r in self._records.values()
                      if r.blob is not None)
            return {
                "entries": len(self._records),
                "ram_entries": ram,
                "disk_entries": len(self._records) - ram,
                "bytes": self.ram_bytes,
                "disk_bytes": self.disk_bytes,
                "budget_bytes": self.budget_bytes,
                "demotions": self.demotions,
                "disk_demotions": self.disk_demotions,
                "promotions": self.promotions,
                "corrupt_dropped": self.corrupt_dropped,
                "rejections": self.rejections,
                "sheds": self.sheds,
            }


def read_host_rss_mb():
    """Resident set size of this process in MiB via ``/proc/self/statm``
    (stdlib only). Returns None where the proc file is unavailable —
    the guard goes inert rather than guessing."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE") / float(1 << 20)
    except (OSError, ValueError, IndexError):
        return None


class MemoryPressureGuard:
    """Host-RSS watchdog that turns memory pressure into staged,
    reversible degradation instead of an OOM kill.

    ``check()`` runs once per engine step. Sustained RSS at or above
    ``watermark_mb`` climbs one LEVEL per sustained window; sustained
    RSS below ``recover_frac * watermark_mb`` descends one level per
    quiet window (the in-between band holds — hysteresis):

    - level 1 ``shed_spill``: drop the spill tier (the cheapest bytes —
      pure opportunistic state);
    - level 2 ``pause_inserts``: the live trie stops growing (lookups,
      promotions, and in-flight refs untouched);
    - level 3 ``degrade``: climb the fleet ``DegradeLadder`` one rung —
      the same spec-off/budget-shrink/class-shed path queue pressure
      takes, so recovery rides the ladder's own hysteresis.

    Windows are counted in CHECKS, not seconds, so tests and chaos
    episodes are deterministic. ``listener(level, rss_mb)`` fires
    edge-triggered on level changes.
    """

    LEVELS = ("healthy", "shed_spill", "pause_inserts", "degrade")

    def __init__(self, watermark_mb, cache=None, ladder=None,
                 read_rss_mb=None, listener=None, recover_frac=0.9,
                 sustain_checks=2, recover_checks=2):
        if watermark_mb <= 0:
            raise ValueError(
                f"watermark_mb must be > 0, got {watermark_mb}")
        if not 0 < recover_frac <= 1:
            raise ValueError(
                f"recover_frac must be in (0, 1], got {recover_frac}")
        self.watermark_mb = float(watermark_mb)
        self.recover_frac = float(recover_frac)
        self.sustain_checks = max(1, int(sustain_checks))
        self.recover_checks = max(1, int(recover_checks))
        self._cache = cache
        self._ladder = ladder
        self._read_rss_mb = read_rss_mb or read_host_rss_mb
        self._listener = listener
        self.level = 0
        self.last_rss_mb = None
        self.escalations = 0
        self.recoveries = 0
        self._over = 0
        self._under = 0

    @property
    def inserts_paused(self):
        return self.level >= 2

    @property
    def level_name(self):
        return self.LEVELS[self.level]

    def check(self):
        """One watchdog tick; returns the (possibly new) level."""
        rss = self._read_rss_mb()
        if rss is None:
            return self.level                   # inert without a signal
        self.last_rss_mb = float(rss)
        if rss >= self.watermark_mb:
            self._over += 1
            self._under = 0
        elif rss <= self.watermark_mb * self.recover_frac:
            self._under += 1
            self._over = 0
        else:
            self._over = 0
            self._under = 0
        if self._over >= self.sustain_checks and self.level < 3:
            self._set_level(self.level + 1)
            self._over = 0                      # next rung needs its own window
        elif self._under >= self.recover_checks and self.level > 0:
            self._set_level(self.level - 1)
            self._under = 0
        return self.level

    def _set_level(self, level):
        up = level > self.level
        self.level = level
        if up:
            self.escalations += 1
            if level == 1 and self._cache is not None:
                self._cache.shed_spill()
            elif level == 3 and self._ladder is not None:
                self._ladder.set_rung(self._ladder.rung + 1,
                                      reason="host_mem_pressure")
        else:
            self.recoveries += 1
            # level 3 -> 2 does NOT force the ladder down: the ladder
            # recovers rung-by-rung on its own hysteresis once the
            # engine's pressure signal clears
        if self._listener is not None:
            self._listener(self.level, self.last_rss_mb)

    def stats(self):
        return {
            "level": self.level,
            "level_name": self.level_name,
            "watermark_mb": self.watermark_mb,
            "rss_mb": self.last_rss_mb,
            "escalations": self.escalations,
            "recoveries": self.recoveries,
            "inserts_paused": self.inserts_paused,
        }


class PrefixKVCache:
    """Trie-indexed, ref-counted, byte-budgeted prompt-prefix KV store,
    optionally backed by a ``SpillStore`` demotion tier."""

    def __init__(self, budget_bytes, spill_budget_bytes=0, spill_dir=None,
                 listener=None):
        if budget_bytes < 1:
            raise ValueError(
                f"budget_bytes must be >= 1, got {budget_bytes}")
        self.budget_bytes = int(budget_bytes)
        self._root = _Node()
        self._by_key = {}                       # tuple[int] -> PrefixEntry
        self._lock = threading.Lock()
        self._clock = 0
        self._listener = listener
        self.total_bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.insert_rejections = 0
        self.spill = (SpillStore(int(spill_budget_bytes),
                                 spill_dir=spill_dir, listener=listener)
                      if spill_budget_bytes > 0 else None)
        self.spill_hits = 0
        self.spill_misses = 0
        self.spill_promotions = 0

    # -- lookup ----------------------------------------------------------
    def match(self, tokens, impl="dense"):
        """Longest stored prefix of ``tokens`` produced by ``impl``:
        (match_len, entry) or (0, None). No hit/miss counters, no refs
        (grouping decisions call this; ``acquire`` is the counted path) —
        but with a spill tier attached a spilled prefix longer than the
        live match IS promoted here, so the length this returns and the
        length a subsequent ``acquire`` sees agree (the engine's bucket
        grouping depends on that: reuse may only GROW between the two)."""
        with self._lock:
            length, entry = self._lookup_locked(tokens, impl)
            return length, entry

    def acquire(self, tokens, impl="dense"):
        """Counted lookup: returns (match_len, entry) and takes a ref on
        the entry so eviction cannot reclaim it while the requester is in
        flight. Release with ``release(entry)``."""
        with self._lock:
            length, entry = self._lookup_locked(tokens, impl)
            if entry is None:
                self.misses += 1
                if self.spill is not None:
                    self.spill_misses += 1
                    self._notify("spill_miss")
                return 0, None
            self.hits += 1
            entry.refs += 1
            self._touch(entry)
            if self.spill is not None:
                if entry.from_spill:
                    entry.from_spill = False
                    self.spill_hits += 1
                    self._notify("spill_hit")
                else:
                    self.spill_misses += 1
                    self._notify("spill_miss")
            return length, entry

    def _lookup_locked(self, tokens, impl):
        length, entry = self._match_locked(tokens, impl)
        if self.spill is None:
            return length, entry
        s_len, s_key = self.spill.match(tokens, impl)
        if s_len <= length:
            return length, entry
        promoted = self.spill.take(s_key)
        if promoted is None:
            # corrupt/torn — already dropped + counted by the store;
            # serve whatever the live tier had
            return length, entry
        if not self._index_locked(promoted):
            # no room in the live tier even after demoting LRU entries:
            # put it back (unverified-state-free: it re-encodes freshly)
            # and serve the live result
            self.spill.put(promoted)
            return length, entry
        self.spill_promotions += 1
        promoted.from_spill = True
        return len(promoted.tokens), promoted

    def _match_locked(self, tokens, impl):
        node, depth, best = self._root, 0, (0, None)
        for tok in tokens:
            node = node.children.get(int(tok))
            if node is None:
                break
            depth += 1
            here = [e for e in node.covering if e.impl == impl]
            if here:
                # MRU entry covering this depth (any of them has
                # identical KV for positions < depth)
                best = (depth, max(here, key=lambda e: e.last_used))
        return best

    def release(self, entry):
        with self._lock:
            if entry.refs < 1:
                raise ValueError("release() without a matching acquire()")
            entry.refs -= 1

    # -- insert / evict --------------------------------------------------
    def insert(self, tokens, k, v, k_scale=None, v_scale=None,
               impl="dense"):
        """Store ``tokens``' KV ([L, nh, len(tokens), hd] numpy pair,
        optionally int8 + per-head scales — see PrefixEntry). Entries are
        keyed by (impl, tokens): the same prompt served under two
        backends stores two entries. Returns the entry, the existing
        entry when the exact (impl, prompt) is already stored, or None
        when it cannot fit even after evicting every unreferenced
        entry."""
        key = tuple(int(t) for t in tokens)
        if not key:
            raise ValueError("cannot insert an empty prefix")
        with self._lock:
            existing = self._by_key.get((impl,) + key)
            if existing is not None:
                self._touch(existing)
                return existing
            entry = PrefixEntry(key, k, v, k_scale=k_scale, v_scale=v_scale,
                                impl=impl)
            if not self._index_locked(entry):
                self.insert_rejections += 1
                return None
            if self.spill is not None:
                # a stale spilled twin of this exact prefix is now
                # strictly worse than the live entry — drop it
                self.spill.discard((impl,) + key)
            return entry

    def _index_locked(self, entry):
        """Budget-check + trie-index ``entry``; shared by insert and
        spill promotion. False when it cannot fit."""
        if entry.nbytes > self.budget_bytes:
            return False
        if not self._make_room_locked(entry.nbytes):
            return False
        node = self._root
        for tok in entry.tokens:
            node = node.children.setdefault(tok, _Node())
            node.covering.add(entry)
        self._by_key[(entry.impl,) + entry.tokens] = entry
        self.total_bytes += entry.nbytes
        self._touch(entry)
        return True

    def _make_room_locked(self, need):
        """Evict LRU unreferenced entries until ``need`` bytes fit."""
        while self.total_bytes + need > self.budget_bytes:
            victims = [e for e in self._by_key.values() if e.refs == 0]
            if not victims:
                return False
            self._evict_locked(min(victims, key=lambda e: e.last_used))
        return True

    def _evict_locked(self, entry, demote=True):
        del self._by_key[(entry.impl,) + entry.tokens]
        self.total_bytes -= entry.nbytes
        node, path = self._root, []
        for tok in entry.tokens:
            node = node.children[tok]
            node.covering.discard(entry)
            path.append((tok, node))
        # prune now-dead trie branches (leaf upward)
        for (tok, node), (_, parent) in zip(
                reversed(path), reversed([(None, self._root)] + path[:-1])):
            if not node.covering and not node.children:
                del parent.children[tok]
        self.evictions += 1
        if demote and self.spill is not None:
            self.spill.put(entry)

    def evict_unreferenced(self):
        """Drop every unreferenced entry from the live tier (the
        ``evict_under_decode`` fault arm and the pool-pressure relief
        path — in-flight lanes already copied their KV, so this must be
        output-invisible). Entries demote to the spill tier when one is
        attached. Returns how many were evicted."""
        with self._lock:
            victims = [e for e in self._by_key.values() if e.refs == 0]
            for e in victims:
                self._evict_locked(e)
            return len(victims)

    # -- spill surface ---------------------------------------------------
    def shed_spill(self):
        """Drop the whole spill tier (memory-pressure relief). Returns
        how many records were shed; 0 without a spill tier."""
        return self.spill.shed() if self.spill is not None else 0

    def corrupt_spilled(self):
        """Fault surface for the ``corrupt_spill_entry`` arm: flip a
        byte in one spilled blob. Returns the corrupted key or None."""
        return self.spill.corrupt_one() if self.spill is not None else None

    def _touch(self, entry):
        self._clock += 1
        entry.last_used = self._clock

    def _notify(self, event):
        if self._listener is not None:
            self._listener(event)

    # -- stats -----------------------------------------------------------
    def hit_rate(self):
        lookups = self.hits + self.misses
        return self.hits / lookups if lookups else 0.0

    def spill_hit_rate(self):
        lookups = self.spill_hits + self.spill_misses
        return self.spill_hits / lookups if lookups else 0.0

    @property
    def referenced(self):
        with self._lock:
            return sum(1 for e in self._by_key.values() if e.refs > 0)

    def __len__(self):
        return len(self._by_key)

    def stats(self):
        with self._lock:
            out = {
                "entries": len(self._by_key),
                "bytes": self.total_bytes,
                "budget_bytes": self.budget_bytes,
                "referenced": sum(
                    1 for e in self._by_key.values() if e.refs > 0),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "insert_rejections": self.insert_rejections,
                "hit_rate": self.hit_rate(),
            }
            if self.spill is not None:
                out["spill"] = self.spill.stats()
                out["spill_hits"] = self.spill_hits
                out["spill_misses"] = self.spill_misses
                out["spill_promotions"] = self.spill_promotions
                out["spill_hit_rate"] = self.spill_hit_rate()
            return out
