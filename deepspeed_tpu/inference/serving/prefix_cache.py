"""Prefix KV cache: skip recomputing shared prompt prefixes entirely.

Serving traffic repeats prompt *prefixes* — system prompts, few-shot
preambles, multi-turn histories. Their keys/values are a pure function
of the token prefix, so a request whose prompt starts with a
previously-served prefix can seed its KV cache from memory and prefill
only the suffix.

Design (host-side, no jax):

- a **token trie** indexes every stored prompt; lookup walks the query
  prompt token by token and returns the LONGEST match against any
  stored entry (a stored prompt's KV covers every prefix of itself —
  the match slices ``entry.k[:, :, :match_len]``);
- entries are **ref-counted**: the engine acquires a ref when a request
  seeds from an entry and releases it at retirement (any path — EOS,
  length, deadline, stuck-request reap), so eviction can never pull KV
  out from under an in-flight admission;
- **LRU eviction under a byte budget**: inserts evict
  least-recently-used *unreferenced* entries until the new entry fits;
  an entry that can never fit (bigger than the whole budget) is
  rejected;
- **hit/miss/evict counters** feed ``Serving/PrefixHitRate``.

Entries hold NUMPY arrays (shape [L, nh, P, hd]): host RAM is the cheap
pool, and the engine assembles the seeded device cache in one transfer
per admission batch — a deliberate host-device copy traded against
recomputing the prefix.
"""

import threading


class PrefixEntry:
    """One stored prompt's KV plus its bookkeeping. In an int8-pool
    engine ``k``/``v`` are int8 with per-(layer, head) fp32 scales
    (``k_scale``/``v_scale``, None otherwise) — quartering the bytes an
    entry charges against the budget, dequantized at seed time."""

    __slots__ = ("tokens", "k", "v", "k_scale", "v_scale", "impl",
                 "nbytes", "refs", "last_used")

    def __init__(self, tokens, k, v, k_scale=None, v_scale=None,
                 impl="dense"):
        self.tokens = tokens                    # tuple[int]
        self.k = k                              # np [L, nh, P, hd]
        self.v = v
        self.k_scale = k_scale                  # np [L, nh, 1, 1] | None
        self.v_scale = v_scale
        # Attention backend that produced this KV. Flash is math-equal to
        # dense but layers >= 2 see low-bit hidden-state drift, and the
        # sparse window attends to different keys outright — seeding one
        # backend's lane from another's entry would break the per-backend
        # bitwise oracle, so lookups are segregated by impl.
        self.impl = impl
        self.nbytes = int(k.nbytes) + int(v.nbytes)
        if k_scale is not None:
            self.nbytes += int(k_scale.nbytes) + int(v_scale.nbytes)
        self.refs = 0
        self.last_used = 0


class _Node:
    __slots__ = ("children", "covering")

    def __init__(self):
        self.children = {}                      # token -> _Node
        self.covering = set()                   # entries passing through


class PrefixKVCache:
    """Trie-indexed, ref-counted, byte-budgeted prompt-prefix KV store."""

    def __init__(self, budget_bytes):
        if budget_bytes < 1:
            raise ValueError(
                f"budget_bytes must be >= 1, got {budget_bytes}")
        self.budget_bytes = int(budget_bytes)
        self._root = _Node()
        self._by_key = {}                       # tuple[int] -> PrefixEntry
        self._lock = threading.Lock()
        self._clock = 0
        self.total_bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.insert_rejections = 0

    # -- lookup ----------------------------------------------------------
    def match(self, tokens, impl="dense"):
        """Longest stored prefix of ``tokens`` produced by ``impl``:
        (match_len, entry) or (0, None). Pure — no counters, no refs
        (grouping decisions call this; ``acquire`` is the counted
        path)."""
        with self._lock:
            return self._match_locked(tokens, impl)

    def _match_locked(self, tokens, impl):
        node, depth, best = self._root, 0, (0, None)
        for tok in tokens:
            node = node.children.get(int(tok))
            if node is None:
                break
            depth += 1
            here = [e for e in node.covering if e.impl == impl]
            if here:
                # MRU entry covering this depth (any of them has
                # identical KV for positions < depth)
                best = (depth, max(here, key=lambda e: e.last_used))
        return best

    def acquire(self, tokens, impl="dense"):
        """Counted lookup: returns (match_len, entry) and takes a ref on
        the entry so eviction cannot reclaim it while the requester is in
        flight. Release with ``release(entry)``."""
        with self._lock:
            length, entry = self._match_locked(tokens, impl)
            if entry is None:
                self.misses += 1
                return 0, None
            self.hits += 1
            entry.refs += 1
            self._touch(entry)
            return length, entry

    def release(self, entry):
        with self._lock:
            if entry.refs < 1:
                raise ValueError("release() without a matching acquire()")
            entry.refs -= 1

    # -- insert / evict --------------------------------------------------
    def insert(self, tokens, k, v, k_scale=None, v_scale=None,
               impl="dense"):
        """Store ``tokens``' KV ([L, nh, len(tokens), hd] numpy pair,
        optionally int8 + per-head scales — see PrefixEntry). Entries are
        keyed by (impl, tokens): the same prompt served under two
        backends stores two entries. Returns the entry, the existing
        entry when the exact (impl, prompt) is already stored, or None
        when it cannot fit even after evicting every unreferenced
        entry."""
        key = tuple(int(t) for t in tokens)
        if not key:
            raise ValueError("cannot insert an empty prefix")
        with self._lock:
            existing = self._by_key.get((impl,) + key)
            if existing is not None:
                self._touch(existing)
                return existing
            entry = PrefixEntry(key, k, v, k_scale=k_scale, v_scale=v_scale,
                                impl=impl)
            if entry.nbytes > self.budget_bytes:
                self.insert_rejections += 1
                return None
            if not self._make_room_locked(entry.nbytes):
                self.insert_rejections += 1
                return None
            node = self._root
            for tok in key:
                node = node.children.setdefault(tok, _Node())
                node.covering.add(entry)
            self._by_key[(impl,) + key] = entry
            self.total_bytes += entry.nbytes
            self._touch(entry)
            return entry

    def _make_room_locked(self, need):
        """Evict LRU unreferenced entries until ``need`` bytes fit."""
        while self.total_bytes + need > self.budget_bytes:
            victims = [e for e in self._by_key.values() if e.refs == 0]
            if not victims:
                return False
            self._evict_locked(min(victims, key=lambda e: e.last_used))
        return True

    def _evict_locked(self, entry):
        del self._by_key[(entry.impl,) + entry.tokens]
        self.total_bytes -= entry.nbytes
        node, path = self._root, []
        for tok in entry.tokens:
            node = node.children[tok]
            node.covering.discard(entry)
            path.append((tok, node))
        # prune now-dead trie branches (leaf upward)
        for (tok, node), (_, parent) in zip(
                reversed(path), reversed([(None, self._root)] + path[:-1])):
            if not node.covering and not node.children:
                del parent.children[tok]
        self.evictions += 1

    def evict_unreferenced(self):
        """Drop every unreferenced entry (the ``evict_under_decode``
        fault arm — in-flight lanes already copied their KV, so this must
        be output-invisible). Returns how many were evicted."""
        with self._lock:
            victims = [e for e in self._by_key.values() if e.refs == 0]
            for e in victims:
                self._evict_locked(e)
            return len(victims)

    def _touch(self, entry):
        self._clock += 1
        entry.last_used = self._clock

    # -- stats -----------------------------------------------------------
    def hit_rate(self):
        lookups = self.hits + self.misses
        return self.hits / lookups if lookups else 0.0

    @property
    def referenced(self):
        with self._lock:
            return sum(1 for e in self._by_key.values() if e.refs > 0)

    def __len__(self):
        return len(self._by_key)

    def stats(self):
        with self._lock:
            return {
                "entries": len(self._by_key),
                "bytes": self.total_bytes,
                "budget_bytes": self.budget_bytes,
                "referenced": sum(
                    1 for e in self._by_key.values() if e.refs > 0),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "insert_rejections": self.insert_rejections,
                "hit_rate": self.hit_rate(),
            }
