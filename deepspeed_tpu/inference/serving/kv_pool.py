"""Slot-based KV-cache pool for continuous-batching inference.

One device-resident cache pair shaped ``[L, MaxSlots, nh, S_max, hd]``
holds every in-flight request's keys/values; a *slot* is one lane of the
MaxSlots axis. The pool is the reason admission never recompiles: the
arrays' shapes are fixed at construction, so a request joining or
retiring only changes *which* lanes the (single compiled) decode step
treats as active — never the program.

Slot hygiene contract (relied on by the engine, proved in
``tests/unit/test_serving.py``):

- installing a prefilled request overwrites the ENTIRE lane
  (``[L, nh, S_max, hd]``), so whatever a previous occupant left behind
  can never be read by the new one;
- while a slot is inactive, the masked decode step may keep writing
  garbage k/v at the lane's stale position — harmless, because lanes are
  computed independently (vmap) and the causal mask hides positions
  beyond any reader's own counter.

Host-side bookkeeping (free list, per-slot position counters, occupancy
stats) is plain Python/numpy: it runs once per scheduler iteration, not
per token-lane.

Storage dtype (``kv_cache_dtype``): the pool can hold its lanes in the
model's compute dtype ("fp32", the default — bitwise-transparent), in
bfloat16 ("bf16" — half the bytes, cast at use), or in int8 with
per-(slot, head) symmetric fp32 scales ("int8" — quarter the bytes,
dequantized at use inside the decode/verify reads). Scales are set once
at install time from the prefilled lane's amax and kept FIXED while the
lane decodes (new tokens clip into the install range), so re-storing an
untouched lane is a bitwise no-op and the engine's requantize step never
perturbs prior tokens.
"""

import numpy as np

import jax
import jax.numpy as jnp

from ..quantization import quantize_kv

KV_CACHE_DTYPES = ("fp32", "bf16", "int8")


class PoolExhaustedError(RuntimeError):
    """allocate() found no free slot. The scheduler treats this as "keep
    the request queued", never as a hard failure — it is an error type so
    direct pool users cannot mistake -1 style sentinels for a slot id."""


def _install_slot(pool_k, pool_v, new_k, new_v, slot):
    """Copy a prefilled single-request cache ([L, 1, nh, S_max, hd]) into
    lane ``slot`` of the pool. ``slot`` is a traced scalar: installing
    into different slots reuses one compiled program. The cast covers the
    "bf16" storage mode and is a no-op (elided by XLA) when the incoming
    dtype already matches the pool's."""
    pool_k = jax.lax.dynamic_update_index_in_dim(
        pool_k, new_k[:, 0].astype(pool_k.dtype), slot, axis=1)
    pool_v = jax.lax.dynamic_update_index_in_dim(
        pool_v, new_v[:, 0].astype(pool_v.dtype), slot, axis=1)
    return pool_k, pool_v


def _install_slot_int8(pool_k, pool_v, k_scale, v_scale, new_k, new_v, slot):
    """int8-mode install: quantize the prefilled lane ([L, nh, S_max, hd])
    with fresh per-(layer, head) scales and overwrite both the lane and
    its scale rows — a reallocated slot never inherits the previous
    occupant's scale range."""
    qk, sk = quantize_kv(new_k[:, 0])
    qv, sv = quantize_kv(new_v[:, 0])
    pool_k = jax.lax.dynamic_update_index_in_dim(pool_k, qk, slot, axis=1)
    pool_v = jax.lax.dynamic_update_index_in_dim(pool_v, qv, slot, axis=1)
    k_scale = jax.lax.dynamic_update_index_in_dim(k_scale, sk, slot, axis=1)
    v_scale = jax.lax.dynamic_update_index_in_dim(v_scale, sv, slot, axis=1)
    return pool_k, pool_v, k_scale, v_scale


# Donate the pool buffers: the install is an in-place lane overwrite, the
# old pool is dead the moment the new one exists. (Scales are donated too
# in the int8 path — the install REPLACES the slot's scale rows, so the
# old scale array is equally dead.)
_install_slot_jit = jax.jit(_install_slot, donate_argnums=(0, 1))
_install_slot_int8_jit = jax.jit(_install_slot_int8,
                                 donate_argnums=(0, 1, 2, 3))


class KVCachePool:
    """Fixed-capacity KV-cache slots plus their host-side bookkeeping."""

    def __init__(self, n_layers, max_slots, n_heads, max_seq_len, head_dim,
                 dtype=jnp.float32, kv_cache_dtype="fp32"):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if max_seq_len < 2:
            raise ValueError(f"max_seq_len must be >= 2, got {max_seq_len}")
        if kv_cache_dtype not in KV_CACHE_DTYPES:
            raise ValueError(
                f"kv_cache_dtype must be one of {KV_CACHE_DTYPES}, "
                f"got {kv_cache_dtype!r}")
        self.n_layers = int(n_layers)
        self.max_slots = int(max_slots)
        self.n_heads = int(n_heads)
        self.max_seq_len = int(max_seq_len)
        self.head_dim = int(head_dim)
        # ``dtype`` is the model's COMPUTE dtype ("fp32" mode stores it
        # directly); quantized modes store narrower and dequant at use.
        self.compute_dtype = dtype
        self.kv_cache_dtype = kv_cache_dtype
        shape = (self.n_layers, self.max_slots, self.n_heads,
                 self.max_seq_len, self.head_dim)
        storage = {"fp32": dtype, "bf16": jnp.bfloat16,
                   "int8": jnp.int8}[kv_cache_dtype]
        self.k = jnp.zeros(shape, storage)
        self.v = jnp.zeros(shape, storage)
        if kv_cache_dtype == "int8":
            # one symmetric scale per (layer, slot, head); keepdims shape
            # broadcasts directly against the lane in dequantize_kv
            sshape = (self.n_layers, self.max_slots, self.n_heads, 1, 1)
            self.k_scale = jnp.ones(sshape, jnp.float32)
            self.v_scale = jnp.ones(sshape, jnp.float32)
        else:
            self.k_scale = None
            self.v_scale = None
        # lowest-index-first allocation keeps slot assignment deterministic
        # for a given arrival order (the oracle tests replay schedules)
        self._free = sorted(range(self.max_slots), reverse=True)
        # per-slot NEXT write/read position (== tokens cached so far)
        self.positions = np.zeros(self.max_slots, np.int32)
        self.allocations = 0
        self.frees = 0
        self.peak_in_use = 0

    # -- slot lifecycle -------------------------------------------------
    @property
    def slots_in_use(self):
        return self.max_slots - len(self._free)

    @property
    def free_slots(self):
        return len(self._free)

    def allocate(self):
        """Claim the lowest free slot; PoolExhaustedError when full."""
        if not self._free:
            raise PoolExhaustedError(
                f"all {self.max_slots} KV-cache slots are in use")
        slot = self._free.pop()
        self.allocations += 1
        self.peak_in_use = max(self.peak_in_use, self.slots_in_use)
        self.positions[slot] = 0
        return slot

    def free(self, slot):
        if not 0 <= slot < self.max_slots:
            raise ValueError(f"slot {slot} outside [0, {self.max_slots})")
        if slot in self._free:
            raise ValueError(f"slot {slot} is already free (double free)")
        self.frees += 1
        self.positions[slot] = 0
        self._free.append(slot)
        self._free.sort(reverse=True)

    def install(self, new_k, new_v, slot, position):
        """Install a prefilled request cache into ``slot`` and set its
        position counter (= prompt length: the next decode write index)."""
        if not 0 <= position < self.max_seq_len:
            raise ValueError(
                f"position {position} outside [0, {self.max_seq_len})")
        if self.kv_cache_dtype == "int8":
            (self.k, self.v, self.k_scale,
             self.v_scale) = _install_slot_int8_jit(
                self.k, self.v, self.k_scale, self.v_scale,
                new_k, new_v, slot)
        else:
            self.k, self.v = _install_slot_jit(
                self.k, self.v, new_k, new_v, slot)
        self.positions[slot] = position

    def install_lane(self, batch_k, batch_v, lane, slot, position):
        """Install lane ``lane`` of a BATCHED prefill result
        ([L, B, nh, S_max, hd]) into ``slot``. Reuses the single-lane
        install program (the lane slice is a static index, the slot stays
        traced), so batched admission adds no install compiles."""
        self.install(batch_k[:, lane:lane + 1], batch_v[:, lane:lane + 1],
                     slot, position)

    def advance(self, slot):
        """Bump a slot's position after a decode step wrote its token.
        Clamped at the last cache index: a (injected-fault) runaway
        request keeps overwriting the final position instead of relying
        on XLA's silent OOB-scatter clamping."""
        self.positions[slot] = min(self.positions[slot] + 1,
                                   self.max_seq_len - 1)

    # -- stats ----------------------------------------------------------
    def nbytes(self):
        """Device bytes held by the pool's KV storage (+ scales in int8
        mode) — the number ``Serving/kv_pool_bytes`` reports, and the one
        that halves/quarters when kv_cache_dtype narrows."""
        total = self.k.nbytes + self.v.nbytes
        if self.k_scale is not None:
            total += self.k_scale.nbytes + self.v_scale.nbytes
        return int(total)

    def occupancy(self):
        """Occupancy snapshot for metrics/debugging."""
        in_use = self.slots_in_use
        return {
            "max_slots": self.max_slots,
            "in_use": in_use,
            "free": self.free_slots,
            "utilization": in_use / self.max_slots,
            "allocations": self.allocations,
            "frees": self.frees,
            "peak_in_use": self.peak_in_use,
            "cached_tokens": int(self.positions.sum()),
            "kv_cache_dtype": self.kv_cache_dtype,
            "pool_bytes": self.nbytes(),
        }
