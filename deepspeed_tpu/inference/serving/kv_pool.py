"""Paged KV-cache pool for continuous-batching inference.

The pool stores keys/values as FIXED-SIZE PAGES of ``page_tokens``
positions each — ``[L, n_pages, nh, page_tokens, hd]`` — instead of one
contiguous ``S_max`` stripe per slot. A *slot* is still one admission
lane (the engine's compiled programs are shaped by ``max_slots``), but a
lane's tokens now live wherever its PAGE TABLE points: ``page_tables``
is a host-side ``[max_slots, pages_per_lane]`` int32 map from a lane's
logical page index to a physical page, uploaded to the device only when
lane membership changes. The jitted decode/prefill programs gather and
scatter BY PAGE INDEX, so:

- the bucket ladder extends into 16k–64k without paying
  ``MaxSlots x S_max`` bytes up front — short requests claim few pages,
  long requests claim many, all against ONE shared ``pool_tokens``
  budget (the ZeRO-Infinity tiering shape: fixed-size units under a
  single budget, no fragmentation classes);
- slot churn moves host integers around, never recompiles (shapes are
  fixed at construction, exactly as before).

Physical page 0 is the NULL page: it is never allocated, page-table
rows are zeroed on free, and every jitted scatter routes inactive /
out-of-range writes to it. A freed lane's masked decode step may keep
writing garbage — it lands on the null page, so a page reallocated to a
new request can never be corrupted by its previous owner. That plus
install overwriting every mapped page preserves the old slot-hygiene
contract verbatim.

``page_tokens`` always DIVIDES ``max_seq_len`` (``resolve_page_tokens``
falls back to the gcd), so a full lane is exactly ``pages_per_lane``
pages and gathering a lane's pages back-to-back reproduces the old
contiguous ``[nh, S_max, hd]`` stripe bit-for-bit — which is how the
dense decode programs stay bitwise-identical to the contiguous pool.

Storage dtype (``kv_cache_dtype``): "fp32" stores the compute dtype,
"bf16" halves the bytes, "int8" quarters them with per-(layer, slot,
head) symmetric fp32 scales. Scales stay PER-LANE (pages are never
shared between lanes), set once at install from the prefilled lane's
amax and fixed while the lane decodes — re-storing an untouched row is
a bitwise no-op, as before.
"""

import numpy as np

import jax
import jax.numpy as jnp

from ..generation import DEFAULT_PAGE_TOKENS, resolve_page_tokens
from ..quantization import quantize_kv
from deepspeed_tpu.parallel.mesh import mp_world_size
from deepspeed_tpu.parallel.sharding_registry import serving_sharding

KV_CACHE_DTYPES = ("fp32", "bf16", "int8")


class PoolExhaustedError(RuntimeError):
    """allocate() found no free slot or not enough free pages. The
    scheduler treats this as "keep the request queued", never as a hard
    failure — it is an error type so direct pool users cannot mistake
    -1 style sentinels for a slot id."""


class PageStateError(ValueError):
    """A page/slot lifecycle violation: freeing a slot that is already
    free, installing into a slot that was never allocated, or raw-
    installing over a live lane under a DIFFERENT handoff key. Named so
    the disaggregated handoff path can distinguish a state-machine bug
    from silent free-list corruption (the failure mode it replaces).
    Subclasses ValueError so pre-existing double-free callers keep
    their except clauses."""


def _install_pages(pool_k, pool_v, new_k, new_v, dest_pages, page_tokens):
    """Scatter a prefilled single-request cache ([L, 1, nh, S, hd],
    S >= pages_per_lane * page_tokens) into the pool's pages at
    ``dest_pages`` [pages_per_lane] (traced — any page assignment reuses
    one compiled program). Unallocated logical pages carry dest 0 and
    land harmlessly on the null page. The cast covers the "bf16" storage
    mode and is a no-op when dtypes already match."""
    L, _, nh, _, hd = new_k.shape
    mp = dest_pages.shape[0]
    span = mp * page_tokens

    def paged(buf):
        lane = buf[:, 0, :, :span]                       # [L, nh, span, hd]
        pages = lane.reshape(L, nh, mp, page_tokens, hd)
        return jnp.moveaxis(pages, 2, 1)                 # [L, mp, nh, pt, hd]

    pool_k = pool_k.at[:, dest_pages].set(paged(new_k).astype(pool_k.dtype))
    pool_v = pool_v.at[:, dest_pages].set(paged(new_v).astype(pool_v.dtype))
    return pool_k, pool_v


def _install_pages_int8(pool_k, pool_v, k_scale, v_scale, new_k, new_v,
                        dest_pages, slot, page_tokens):
    """int8-mode install: quantize the prefilled lane with fresh
    per-(layer, head) scales, page it, and overwrite both the mapped
    pages and the lane's scale rows — a reallocated slot never inherits
    the previous occupant's scale range."""
    L, _, nh, _, hd = new_k.shape
    mp = dest_pages.shape[0]
    span = mp * page_tokens

    def quant_paged(buf):
        q, s = quantize_kv(buf[:, 0, :, :span])          # [L, nh, span, hd]
        pages = q.reshape(L, nh, mp, page_tokens, hd)
        return jnp.moveaxis(pages, 2, 1), s

    qk, sk = quant_paged(new_k)
    qv, sv = quant_paged(new_v)
    pool_k = pool_k.at[:, dest_pages].set(qk)
    pool_v = pool_v.at[:, dest_pages].set(qv)
    k_scale = jax.lax.dynamic_update_index_in_dim(k_scale, sk, slot, axis=1)
    v_scale = jax.lax.dynamic_update_index_in_dim(v_scale, sv, slot, axis=1)
    return pool_k, pool_v, k_scale, v_scale


# Donate the pool buffers: the install is an in-place page overwrite, the
# old pool is dead the moment the new one exists. (Scales are donated too
# in the int8 path — the install REPLACES the slot's scale rows.)
_install_pages_jit = jax.jit(_install_pages, donate_argnums=(0, 1),
                             static_argnums=(5,))
_install_pages_int8_jit = jax.jit(_install_pages_int8,
                                  donate_argnums=(0, 1, 2, 3),
                                  static_argnums=(8,))


# -- host entry frame export / import (prefix-cache spill tier) ---------
def export_entry_frames(k, v, k_scale=None, v_scale=None):
    """Serialize a host-side KV entry (numpy ``[L, nh, P, hd]`` pair in
    its STORAGE dtype — fp32/bf16/int8 — plus optional per-(layer, head)
    fp32 scales) into ``(meta, frames)``: raw ``bytes`` payloads the
    spill tier can frame/checksum individually, and the meta dict
    ``import_entry_frames`` needs to rebuild the arrays bit-for-bit.
    The generalization of ``export_lane``'s tobytes/frombuffer discipline
    to entries that never lived in the pool."""
    meta = {
        "dtype": str(np.dtype(k.dtype)),
        "shape": list(k.shape),
        "scales": k_scale is not None,
    }
    frames = [k.tobytes(), v.tobytes()]
    if k_scale is not None:
        meta["scale_shape"] = list(k_scale.shape)
        frames.append(np.ascontiguousarray(k_scale, np.float32).tobytes())
        frames.append(np.ascontiguousarray(v_scale, np.float32).tobytes())
    return meta, frames


def import_entry_frames(meta, frames):
    """Inverse of ``export_entry_frames``: rebuild ``(k, v, k_scale,
    v_scale)`` from a meta dict and its byte frames. Raises ValueError
    when a frame's byte count disagrees with the advertised shape/dtype
    (a framing-level corruption the crc missed structurally)."""
    dtype = np.dtype(str(meta["dtype"]))
    shape = tuple(int(d) for d in meta["shape"])
    expect = dtype.itemsize * int(np.prod(shape))
    if len(frames[0]) != expect or len(frames[1]) != expect:
        raise ValueError(
            f"entry frame carries {len(frames[0])}/{len(frames[1])} bytes "
            f"but shape {shape} x {dtype} needs {expect}")
    k = np.frombuffer(frames[0], dtype).reshape(shape)
    v = np.frombuffer(frames[1], dtype).reshape(shape)
    k_scale = v_scale = None
    if meta.get("scales"):
        sshape = tuple(int(d) for d in meta["scale_shape"])
        sexpect = 4 * int(np.prod(sshape))
        if len(frames[2]) != sexpect or len(frames[3]) != sexpect:
            raise ValueError(
                f"scale frame carries {len(frames[2])}/{len(frames[3])} "
                f"bytes but shape {sshape} x float32 needs {sexpect}")
        k_scale = np.frombuffer(frames[2], np.float32).reshape(sshape)
        v_scale = np.frombuffer(frames[3], np.float32).reshape(sshape)
    return k, v, k_scale, v_scale


class KVCachePool:
    """Fixed-capacity paged KV storage plus its host-side allocator."""

    def __init__(self, n_layers, max_slots, n_heads, max_seq_len, head_dim,
                 dtype=jnp.float32, kv_cache_dtype="fp32",
                 page_tokens=None, pool_tokens=None, mesh=None,
                 registry=None):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        if max_seq_len < 2:
            raise ValueError(f"max_seq_len must be >= 2, got {max_seq_len}")
        if kv_cache_dtype not in KV_CACHE_DTYPES:
            raise ValueError(
                f"kv_cache_dtype must be one of {KV_CACHE_DTYPES}, "
                f"got {kv_cache_dtype!r}")
        self.n_layers = int(n_layers)
        self.max_slots = int(max_slots)
        self.n_heads = int(n_heads)
        self.max_seq_len = int(max_seq_len)
        self.head_dim = int(head_dim)
        # ``dtype`` is the model's COMPUTE dtype ("fp32" mode stores it
        # directly); quantized modes store narrower and dequant at use.
        self.compute_dtype = dtype
        self.kv_cache_dtype = kv_cache_dtype
        self.page_tokens = resolve_page_tokens(
            page_tokens or DEFAULT_PAGE_TOKENS, self.max_seq_len)
        self.pages_per_lane = self.max_seq_len // self.page_tokens
        # Shared token budget across all lanes. The default keeps the old
        # every-lane-can-be-full capacity; a smaller budget is where the
        # paged layout beats the contiguous MaxSlots x S_max footprint
        # (long and short requests share it instead of each reserving
        # S_max). Floor of one full lane so a single max-length request
        # always fits.
        if pool_tokens is None:
            pool_tokens = self.max_slots * self.max_seq_len
        if int(pool_tokens) < 1:
            raise ValueError(f"pool_tokens must be >= 1, got {pool_tokens}")
        self.pool_tokens = max(int(pool_tokens),
                               self.pages_per_lane * self.page_tokens)
        self.n_data_pages = self.pool_tokens // self.page_tokens
        n_pages = self.n_data_pages + 1                  # + null page 0
        shape = (self.n_layers, n_pages, self.n_heads,
                 self.page_tokens, self.head_dim)
        storage = {"fp32": dtype, "bf16": jnp.bfloat16,
                   "int8": jnp.int8}[kv_cache_dtype]
        # Tensor-parallel pool: the heads dim splits over the mesh's
        # `model` axis (specs resolved through the sharding registry —
        # the single source both engines consume). mesh=None keeps the
        # single-device layout byte-identical.
        self.mesh = mesh
        self.kv_sharding = None
        self.replicated_sharding = None
        if mesh is not None:
            mp = mp_world_size(mesh)
            if self.n_heads % mp != 0:
                raise ValueError(
                    f"n_heads={self.n_heads} not divisible by the mesh's "
                    f"model axis size {mp}; the KV pool shards heads")
            self.kv_sharding = serving_sharding(mesh, "serving/kv_pool",
                                                registry=registry)
            self.replicated_sharding = serving_sharding(
                mesh, "serving/lane_state", registry=registry)
            self.k = jnp.zeros(shape, storage, device=self.kv_sharding)
            self.v = jnp.zeros(shape, storage, device=self.kv_sharding)
        else:
            self.k = jnp.zeros(shape, storage)
            self.v = jnp.zeros(shape, storage)
        if kv_cache_dtype == "int8":
            # one symmetric scale per (layer, slot, head) — per LANE, not
            # per page: pages are never shared across lanes, and keeping
            # the old shape keeps dequantize_kv broadcasting unchanged
            sshape = (self.n_layers, self.max_slots, self.n_heads, 1, 1)
            if mesh is not None:
                scale_sh = serving_sharding(mesh, "serving/kv_scale",
                                            registry=registry)
                self.k_scale = jnp.ones(sshape, jnp.float32,
                                        device=scale_sh)
                self.v_scale = jnp.ones(sshape, jnp.float32,
                                        device=scale_sh)
            else:
                self.k_scale = jnp.ones(sshape, jnp.float32)
                self.v_scale = jnp.ones(sshape, jnp.float32)
        else:
            self.k_scale = None
            self.v_scale = None
        # lowest-index-first allocation keeps slot/page assignment
        # deterministic for a given arrival order (oracle tests replay
        # schedules)
        self._free = sorted(range(self.max_slots), reverse=True)
        self._free_pages = sorted(range(1, n_pages), reverse=True)
        # logical->physical page map per lane; 0 (the null page) means
        # unmapped. The engine mirrors this to the device only on churn.
        self.page_tables = np.zeros((self.max_slots, self.pages_per_lane),
                                    np.int32)
        self._lane_pages = [[] for _ in range(self.max_slots)]
        # handoff idempotency: key -> slot for lanes installed via
        # install_raw(); a re-sent handoff under a live key is a no-op
        self._handoff_keys = {}
        self._slot_handoff_key = {}
        # per-slot NEXT write/read position (== tokens cached so far)
        self.positions = np.zeros(self.max_slots, np.int32)
        self.allocations = 0
        self.frees = 0
        self.peak_in_use = 0
        self.peak_pages_in_use = 0

    def host_put(self, x, dtype=None, sharded=False):
        """Sharding-aware host->device placement: on a mesh, commit to
        the registry-resolved sharding (replicated lane state, or the
        pool's heads-sharded layout when ``sharded``) instead of the
        default device — a default-device put on a >1-device mesh would
        force a reshard inside the next jitted step."""
        arr = np.asarray(x, dtype) if dtype is not None else np.asarray(x)
        if self.mesh is None:
            return jnp.asarray(arr)
        target = self.kv_sharding if sharded else self.replicated_sharding
        return jax.device_put(arr, target)

    # -- slot lifecycle -------------------------------------------------
    @property
    def slots_in_use(self):
        return self.max_slots - len(self._free)

    @property
    def free_slots(self):
        return len(self._free)

    @property
    def pages_in_use(self):
        return self.n_data_pages - len(self._free_pages)

    @property
    def free_pages(self):
        return len(self._free_pages)

    def _pages_needed(self, n_tokens):
        if n_tokens is None:
            n_tokens = self.max_seq_len
        n_tokens = min(max(int(n_tokens), 1), self.max_seq_len)
        return -(-n_tokens // self.page_tokens)

    def can_allocate(self, n_tokens=None):
        """True iff allocate(n_tokens) would succeed right now."""
        return (bool(self._free)
                and self._pages_needed(n_tokens) <= len(self._free_pages))

    def allocate(self, n_tokens=None):
        """Claim the lowest free slot plus enough pages for ``n_tokens``
        positions (default: a full ``max_seq_len`` lane — the contiguous
        pool's behavior). PoolExhaustedError when out of slots or pages;
        the pool is untouched on failure, so callers can requeue."""
        if not self._free:
            raise PoolExhaustedError(
                f"all {self.max_slots} KV-cache slots are in use")
        need = self._pages_needed(n_tokens)
        if need > len(self._free_pages):
            raise PoolExhaustedError(
                f"KV page pool exhausted: need {need} pages, "
                f"{len(self._free_pages)} of {self.n_data_pages} free "
                f"({self.page_tokens} tokens/page)")
        slot = self._free.pop()
        pages = [self._free_pages.pop() for _ in range(need)]
        self.page_tables[slot] = 0
        self.page_tables[slot, :need] = pages
        self._lane_pages[slot] = pages
        self.allocations += 1
        self.peak_in_use = max(self.peak_in_use, self.slots_in_use)
        self.peak_pages_in_use = max(self.peak_pages_in_use,
                                     self.pages_in_use)
        self.positions[slot] = 0
        return slot

    def free(self, slot):
        if not 0 <= slot < self.max_slots:
            raise ValueError(f"slot {slot} outside [0, {self.max_slots})")
        if slot in self._free:
            raise PageStateError(
                f"slot {slot} is already free (double free)")
        key = self._slot_handoff_key.pop(slot, None)
        if key is not None:
            self._handoff_keys.pop(key, None)
        self.frees += 1
        self.positions[slot] = 0
        # zero the table row BEFORE returning pages: the freed lane's
        # masked decode writes must route to the null page from the next
        # uploaded table on, never to a page someone else now owns
        self.page_tables[slot] = 0
        self._free_pages.extend(self._lane_pages[slot])
        self._free_pages.sort(reverse=True)
        self._lane_pages[slot] = []
        self._free.append(slot)
        self._free.sort(reverse=True)

    def lane_tokens(self, slot):
        """Token capacity actually backed by this lane's pages."""
        return len(self._lane_pages[slot]) * self.page_tokens

    def install(self, new_k, new_v, slot, position):
        """Install a prefilled request cache ([L, 1, nh, S, hd] with
        S >= max_seq_len) into ``slot``'s pages and set its position
        counter (= prompt length: the next decode write index)."""
        if not 0 <= position < self.max_seq_len:
            raise ValueError(
                f"position {position} outside [0, {self.max_seq_len})")
        if slot in self._free:
            raise PageStateError(
                f"install into slot {slot} which is not allocated")
        dest = self.host_put(self.page_tables[slot], jnp.int32)
        if self.kv_cache_dtype == "int8":
            (self.k, self.v, self.k_scale,
             self.v_scale) = _install_pages_int8_jit(
                self.k, self.v, self.k_scale, self.v_scale,
                new_k, new_v, dest, slot, self.page_tokens)
        else:
            self.k, self.v = _install_pages_jit(
                self.k, self.v, new_k, new_v, dest, self.page_tokens)
        self.positions[slot] = position

    def install_lane(self, batch_k, batch_v, lane, slot, position):
        """Install lane ``lane`` of a BATCHED prefill result
        ([L, B, nh, S, hd]) into ``slot``. Reuses the single-lane
        install program (the lane slice is a static index; the dest
        pages and slot stay traced), so batched admission adds no
        install compiles."""
        self.install(batch_k[:, lane:lane + 1], batch_v[:, lane:lane + 1],
                     slot, position)

    # -- raw page export / install (disaggregated handoff) --------------
    def export_lane(self, slot):
        """Snapshot a live lane's pages AS STORED (storage dtype bytes,
        no dequant — the transfer must be bitwise) into host memory.
        Returns ``(meta, frames)``: ``frames`` is one ``bytes`` payload
        per logical page (k-page bytes then v-page bytes, fixed length),
        plus one trailing scales frame in int8 mode; ``meta`` carries
        everything install_raw() needs to rebuild the lane bit-for-bit
        on another pool with the same geometry."""
        if slot in self._free:
            raise PageStateError(
                f"export from slot {slot} which is not allocated")
        pages = self._lane_pages[slot]
        idx = np.asarray(pages, np.int32)
        lane_k = np.asarray(self.k[:, idx])   # [L, n, nh, pt, hd]
        lane_v = np.asarray(self.v[:, idx])
        frames = [lane_k[:, i].tobytes() + lane_v[:, i].tobytes()
                  for i in range(len(pages))]
        meta = {
            "pages": len(pages),
            "position": int(self.positions[slot]),
            "page_tokens": self.page_tokens,
            "kv_cache_dtype": self.kv_cache_dtype,
            "page_nbytes": len(frames[0]) if frames else 0,
            "scales": self.k_scale is not None,
        }
        if self.k_scale is not None:
            sk = np.asarray(self.k_scale[:, slot], np.float32)
            sv = np.asarray(self.v_scale[:, slot], np.float32)
            frames.append(sk.tobytes() + sv.tobytes())
        return meta, frames

    def install_raw(self, slot, meta, frames, handoff_key=None):
        """Install exported pages into an allocated ``slot`` WITHOUT
        re-quantizing — the bytes land in storage exactly as the sender
        stored them, so the resumed lane is bit-identical to the lane
        the prefill worker built. Idempotent under ``handoff_key``: a
        re-sent handoff whose key is already live returns False and
        touches nothing (never double-installs); installing over a live
        lane registered under a DIFFERENT key raises PageStateError."""
        if slot in self._free:
            raise PageStateError(
                f"install_raw into slot {slot} which is not allocated")
        if handoff_key is not None and handoff_key in self._handoff_keys:
            return False                         # idempotent re-send
        held = self._slot_handoff_key.get(slot)
        if held is not None and held != handoff_key:
            raise PageStateError(
                f"slot {slot} already holds handoff key {held!r}; "
                f"refusing install over a live lane under "
                f"{handoff_key!r}")
        n = int(meta["pages"])
        if meta["kv_cache_dtype"] != self.kv_cache_dtype:
            raise PageStateError(
                f"handoff dtype {meta['kv_cache_dtype']!r} does not "
                f"match pool dtype {self.kv_cache_dtype!r}")
        if n > len(self._lane_pages[slot]):
            raise PageStateError(
                f"handoff carries {n} pages but slot {slot} has only "
                f"{len(self._lane_pages[slot])} allocated")
        position = int(meta["position"])
        if not 0 <= position < self.max_seq_len:
            raise ValueError(
                f"position {position} outside [0, {self.max_seq_len})")
        storage = np.dtype(self.k.dtype)
        pshape = (self.n_layers, self.n_heads, self.page_tokens,
                  self.head_dim)
        half = storage.itemsize * int(np.prod(pshape))
        ks, vs = [], []
        for payload in frames[:n]:
            ks.append(np.frombuffer(payload[:half], storage)
                      .reshape(pshape))
            vs.append(np.frombuffer(payload[half:], storage)
                      .reshape(pshape))
        dest = np.asarray(self._lane_pages[slot][:n], np.int32)
        lane_k = np.stack(ks, axis=1)            # [L, n, nh, pt, hd]
        lane_v = np.stack(vs, axis=1)
        self.k = self.k.at[:, dest].set(self.host_put(lane_k, sharded=True))
        self.v = self.v.at[:, dest].set(self.host_put(lane_v, sharded=True))
        if meta.get("scales"):
            if self.k_scale is None:
                raise PageStateError(
                    "handoff carries scales but pool is not int8")
            sshape = (self.n_layers, self.n_heads, 1, 1)
            shalf = 4 * int(np.prod(sshape))
            sbuf = frames[n]
            sk = np.frombuffer(sbuf[:shalf], np.float32).reshape(sshape)
            sv = np.frombuffer(sbuf[shalf:], np.float32).reshape(sshape)
            self.k_scale = self.k_scale.at[:, slot].set(self.host_put(sk))
            self.v_scale = self.v_scale.at[:, slot].set(self.host_put(sv))
        self.positions[slot] = position
        if handoff_key is not None:
            self._handoff_keys[handoff_key] = slot
            self._slot_handoff_key[slot] = handoff_key
        return True

    def handoff_slot(self, handoff_key):
        """Slot currently holding ``handoff_key``, or None."""
        return self._handoff_keys.get(handoff_key)

    def advance(self, slot):
        """Bump a slot's position after a decode step wrote its token.
        Clamped at the last cache index: a (injected-fault) runaway
        request keeps overwriting the final position instead of relying
        on silent OOB-scatter behavior."""
        self.positions[slot] = min(self.positions[slot] + 1,
                                   self.max_seq_len - 1)

    # -- stats ----------------------------------------------------------
    def nbytes(self):
        """Device bytes held by the pool's KV storage (+ scales in int8
        mode) — the number ``Serving/kv_pool_bytes`` reports, and the one
        that halves/quarters when kv_cache_dtype narrows."""
        total = self.k.nbytes + self.v.nbytes
        if self.k_scale is not None:
            total += self.k_scale.nbytes + self.v_scale.nbytes
        return int(total)

    def contiguous_equiv_bytes(self):
        """Bytes the OLD contiguous layout ([L, MaxSlots, nh, S_max, hd]
        per cache side, same storage dtype) would spend for the same
        slot count — the footprint the paged pool beats when
        ``pool_tokens`` undercuts ``max_slots * max_seq_len``."""
        itemsize = {"fp32": jnp.dtype(self.compute_dtype).itemsize,
                    "bf16": 2, "int8": 1}[self.kv_cache_dtype]
        elems = (self.n_layers * self.max_slots * self.n_heads
                 * self.max_seq_len * self.head_dim)
        total = 2 * elems * itemsize
        if self.k_scale is not None:
            total += self.k_scale.nbytes + self.v_scale.nbytes
        return int(total)

    def occupancy(self):
        """Occupancy snapshot for metrics/debugging."""
        in_use = self.slots_in_use
        covered = self.pages_in_use * self.page_tokens
        return {
            "max_slots": self.max_slots,
            "in_use": in_use,
            "free": self.free_slots,
            "utilization": in_use / self.max_slots,
            "allocations": self.allocations,
            "frees": self.frees,
            "peak_in_use": self.peak_in_use,
            "cached_tokens": int(self.positions.sum()),
            "kv_cache_dtype": self.kv_cache_dtype,
            "pool_bytes": self.nbytes(),
            "page_tokens": self.page_tokens,
            "pages_total": self.n_data_pages,
            "pages_in_use": self.pages_in_use,
            "pages_free": self.free_pages,
            "peak_pages_in_use": self.peak_pages_in_use,
            # tokens reserved by claimed pages but not (yet) cached —
            # internal fragmentation of the page granularity
            "page_fragmentation": ((covered - int(self.positions.sum()))
                                   / max(covered, 1)),
        }
