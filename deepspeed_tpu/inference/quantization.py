"""Weight-only int8 quantization for the decode path.

Beyond the v0.3.10 reference (DeepSpeed-Inference's INT8 kernels came
later), realized for the TPU decode regime: autoregressive decoding is
HBM-bandwidth-bound (every step streams all weights for one token), so
storing the big matmul kernels in int8 with per-output-channel fp32
scales cuts the streamed bytes ~4x. Dequantization happens AT USE —
``int8 -> f32 * scale`` fuses into the surrounding matmul under XLA, so
nothing is ever materialized in fp32 at rest.

Scope: the per-layer GEMM kernels (qkv, attn_out, ff1, ff2) and the
token embedding. LayerNorms, biases, and the position embedding stay
fp32 (negligible bytes, precision-critical).

    qparams = quantize_for_decode(params)
    tokens = generate(qparams, cfg, prompt, 64)   # same API

The same at-use-dequant design extends to the serving KV-cache pool
(``quantize_kv``/``dequantize_kv``/``requantize_kv``): keys/values are
stored int8 with per-head symmetric fp32 scales and dequantized inside
the decode/verify attention reads, roughly doubling KV slots per byte
of pool versus bf16 (4x versus fp32). Weights are quantized once and
never rewritten; KV is append-mostly, so decode-written tokens are
requantized against the FIXED install-time scales (``requantize_kv``)
instead of rescaling the whole lane every step.
"""

import numpy as np

import jax
import jax.numpy as jnp

_LAYER_KERNELS = ("qkv", "attn_out", "ff1", "ff2")


def quantize_tensor(w, axis=-1):
    """Symmetric per-channel int8: returns {"kernel_q": int8, "scale": f32}
    with ``scale`` shaped to broadcast against the dequantized tensor."""
    w = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=axis, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return {"kernel_q": q, "scale": scale.astype(jnp.float32)}


def dequantize_tensor(qt, dtype=jnp.float32):
    return qt["kernel_q"].astype(dtype) * qt["scale"].astype(dtype)


def maybe_dequant(p, name="kernel", dtype=None):
    """Read a possibly-quantized kernel out of a param block. The decode
    path calls this instead of indexing ``p["kernel"]`` directly.

    ``dtype=None`` keeps the unquantized kernel's NATIVE dtype (a bf16
    checkpoint keeps streaming bf16 bytes — the bandwidth-bound regime
    this module exists for) and dequantizes int8 to fp32."""
    if "kernel_q" in p:
        return dequantize_tensor(p, dtype or jnp.float32)
    w = p[name]
    return w if dtype is None else jnp.asarray(w, dtype)


def embed_rows(wte_blk, token):
    """Gather embedding rows from a possibly-quantized token table
    (per-row dequant of only the gathered rows on the int8 layout)."""
    if "kernel_q" in wte_blk:
        return (wte_blk["kernel_q"][token].astype(jnp.float32)
                * wte_blk["scale"][token])
    return wte_blk["embedding"][token]


def vocab_size(wte_blk):
    return (wte_blk["kernel_q"] if "kernel_q" in wte_blk
            else wte_blk["embedding"]).shape[0]


def logits_table(wte_blk, dtype):
    """The full (tied) output table in ``dtype`` — streamed every step by
    the logits head, so the int8 layout's dequant fuses into that matmul."""
    if "kernel_q" in wte_blk:
        return dequantize_tensor(wte_blk, dtype)
    return wte_blk["embedding"].astype(dtype)


def quantize_kv(kv, axis=(-2, -1)):
    """Symmetric int8 quantization of a KV tensor with per-head scales.

    ``kv`` is ``[..., nh, S, hd]``; the scale reduces over ``axis``
    (sequence and head-dim by default) so each head carries ONE fp32
    scale — the granularity the serving pool stores per (slot, head).
    Returns ``(int8 values, fp32 scale with keepdims)``."""
    kv = jnp.asarray(kv, jnp.float32)
    amax = jnp.max(jnp.abs(kv), axis=axis, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(kv / scale), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_kv(q, scale, dtype=jnp.float32):
    """At-use dequant: ``int8 * scale`` in ``dtype``. Inside a jitted
    attention read this fuses into the consuming contraction, so the
    fp32 view is never materialized at rest."""
    return q.astype(dtype) * scale.astype(dtype)


def requantize_kv(kv, scale):
    """Quantize ``kv`` against FIXED per-head scales (clipping at ±127).

    The serving decode loop appends tokens to an already-quantized lane;
    rescaling the whole lane every step would change the stored value of
    every PRIOR token. Instead the install-time scale is kept and new
    tokens are clipped into its range. Idempotent on entries that came
    from ``dequantize_kv`` with the same scale — ``round(q*s/s) == q``
    exactly, since the fp32 roundtrip error is far below 0.5 ulp of the
    int grid — so re-storing an untouched lane is a bitwise no-op."""
    return jnp.clip(jnp.round(kv.astype(jnp.float32) / scale),
                    -127, 127).astype(jnp.int8)


def quantize_kv_np(kv, axis=(-2, -1)):
    """Host-side (numpy) twin of ``quantize_kv`` for the prefix-cache
    path, which stores entries as host arrays outside any trace."""
    kv = np.asarray(kv, np.float32)
    amax = np.max(np.abs(kv), axis=axis, keepdims=True)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.round(kv / scale), -127, 127).astype(np.int8)
    return q, scale


def dequantize_kv_np(q, scale, dtype=np.float32):
    return np.asarray(q, dtype) * np.asarray(scale, dtype)


def quantize_for_decode(params):
    """Quantize a GPT-2 param tree (models/gpt2.py layout, scan-stacked
    layers) for ``inference.generate``: layer GEMM kernels and the token
    embedding go int8; everything else passes through unchanged."""
    tr = params["params"]["transformer"]
    layers = dict(tr["layers"])
    if len(layers) != 1:
        raise ValueError(
            f"expected the scan-stacked GPT-2 layout (one child under "
            f"'layers'), got {sorted(layers)}")
    (child_name, child), = layers.items()
    child = dict(child)
    for k in _LAYER_KERNELS:
        blk = dict(child[k])
        if "kernel_q" in blk:
            raise ValueError("params are already quantized (kernel_q present)")
        # stacked [L, in, out]: quantize per (layer, out-channel)
        qt = quantize_tensor(blk["kernel"], axis=-2)
        blk.pop("kernel")
        blk.update(qt)
        child[k] = blk
    layers[child_name] = child

    wte = dict(tr["wte"])
    wte.update(quantize_tensor(wte.pop("embedding"), axis=-1))

    new_tr = dict(tr)
    new_tr["layers"] = layers
    new_tr["wte"] = wte
    new_params = dict(params)
    new_params["params"] = dict(params["params"])
    new_params["params"]["transformer"] = new_tr
    return new_params


def quantized_bytes(tree):
    return sum(l.size * l.dtype.itemsize
               for l in jax.tree_util.tree_leaves(tree)
               if hasattr(l, "dtype"))
