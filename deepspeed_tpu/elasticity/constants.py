"""Elasticity config keys/defaults (parity: reference ``deepspeed/elasticity/constants.py``)."""

ELASTICITY = "elasticity"

# Current elasticity schema version supported by this build
ELASTICITY_CURRENT_VERSION = 0.1
LATEST_ELASTICITY_VERSION = 0.1
MINIMUM_DEEPSPEED_VERSION = "0.1.0"

ENABLED = "enabled"
ENABLED_DEFAULT = False

# Maximum acceptable train_batch_size
MAX_ACCEPTABLE_BATCH_SIZE = "max_train_batch_size"
MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT = 2000

# Acceptable micro batch sizes, same as train_micro_batch_size_per_gpu
MICRO_BATCHES = "micro_batch_sizes"
MICRO_BATCHES_DEFAULT = [2, 4, 6]

MIN_GPUS = "min_gpus"
MIN_GPUS_DEFAULT = 1
MAX_GPUS = "max_gpus"
MAX_GPUS_DEFAULT = 10000

MIN_TIME = "min_time"
MIN_TIME_DEFAULT = 0

VERSION = "version"
VERSION_DEFAULT = LATEST_ELASTICITY_VERSION

PREFER_LARGER_BATCH = "prefer_larger_batch"
PREFER_LARGER_BATCH_DEFAULT = True

IGNORE_NON_ELASTIC_BATCH_INFO = "ignore_non_elastic_batch_info"
IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT = False

DEEPSPEED_ELASTICITY_CONFIG = "DEEPSPEED_ELASTICITY_CONFIG"
