"""Typed elasticity config (parity: reference ``deepspeed/elasticity/config.py``)."""

import json

from deepspeed_tpu.elasticity.constants import *


class ElasticityError(Exception):
    """Base exception for elasticity problems."""


class ElasticityConfigError(ElasticityError):
    """Invalid elasticity configuration."""


class ElasticityIncompatibleWorldSize(ElasticityError):
    """Current world size is not valid for the elastic config."""


class ElasticityConfig:
    """Typed view of the ``elasticity`` config section::

        "elasticity": {
          "enabled": true,
          "max_train_batch_size": 2000,
          "micro_batch_sizes": [2,4,6],
          "min_gpus": 1,
          "max_gpus": 10000,
          "min_time": 20,
          "version": 0.1,
          "ignore_non_elastic_batch_info": false,
          "prefer_larger_batch": true
        }
    """

    def __init__(self, param_dict):
        self.enabled = param_dict.get(ENABLED, ENABLED_DEFAULT)
        if self.enabled:
            if MAX_ACCEPTABLE_BATCH_SIZE in param_dict:
                self.max_acceptable_batch_size = param_dict[MAX_ACCEPTABLE_BATCH_SIZE]
            else:
                raise ElasticityConfigError(f"Elasticity config missing {MAX_ACCEPTABLE_BATCH_SIZE}")
            if MICRO_BATCHES in param_dict:
                self.micro_batches = param_dict[MICRO_BATCHES]
            else:
                raise ElasticityConfigError(f"Elasticity config missing {MICRO_BATCHES}")
        else:
            self.max_acceptable_batch_size = param_dict.get(
                MAX_ACCEPTABLE_BATCH_SIZE, MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT
            )
            self.micro_batches = param_dict.get(MICRO_BATCHES, MICRO_BATCHES_DEFAULT)

        if not isinstance(self.micro_batches, list):
            raise ElasticityConfigError(
                f"Elasticity expected value of {MICRO_BATCHES} to be a "
                f"list of micro batches, instead is: {type(self.micro_batches)}, containing: {self.micro_batches}"
            )
        if not all(map(lambda m: isinstance(m, int), self.micro_batches)):
            raise ElasticityConfigError(
                f"Elasticity expected {MICRO_BATCHES} to only contain a list of integers, "
                f"instead contains: {self.micro_batches}"
            )
        if not all(map(lambda m: m > 0, self.micro_batches)):
            raise ElasticityConfigError(
                f"Elasticity expected {MICRO_BATCHES} to only contain positive integers, "
                f"instead contains: {self.micro_batches}"
            )

        self.min_gpus = param_dict.get(MIN_GPUS, MIN_GPUS_DEFAULT)
        self.max_gpus = param_dict.get(MAX_GPUS, MAX_GPUS_DEFAULT)
        if self.min_gpus < 1 or self.max_gpus < 1:
            raise ElasticityConfigError("Elasticity min/max gpus must be > 0, " f"given min_gpus: {self.min_gpus}, max_gpus: {self.max_gpus}")
        if self.max_gpus < self.min_gpus:
            raise ElasticityConfigError(
                "Elasticity min_gpus cannot be greater than max_gpus, "
                f"given min_gpus: {self.min_gpus}, max_gpus: {self.max_gpus}"
            )

        self.min_time = param_dict.get(MIN_TIME, MIN_TIME_DEFAULT)
        if self.min_time < 0:
            raise ElasticityConfigError(f"Elasticity min time needs to be >= 0: given {self.min_time}")

        self.version = param_dict.get(VERSION, VERSION_DEFAULT)
        self.prefer_larger_batch_size = param_dict.get(PREFER_LARGER_BATCH, PREFER_LARGER_BATCH_DEFAULT)
        self.ignore_non_elastic_batch_info = param_dict.get(
            IGNORE_NON_ELASTIC_BATCH_INFO, IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT
        )

    def repr(self):
        return self.__dict__

    def __repr__(self):
        return json.dumps(self.__dict__, sort_keys=True, indent=4)
