from deepspeed_tpu.elasticity.elasticity import (
    compute_elastic_config,
    elasticity_enabled,
    ensure_immutable_elastic_config,
    get_candidate_batch_sizes,
    get_valid_gpus,
    get_best_candidates,
    _get_compatible_gpus_v01,
    HCN_LIST,
)
from deepspeed_tpu.elasticity.resume import compute_elastic_resume
from deepspeed_tpu.elasticity.config import (
    ElasticityConfig,
    ElasticityError,
    ElasticityConfigError,
    ElasticityIncompatibleWorldSize,
)
