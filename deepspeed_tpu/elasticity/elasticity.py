"""Elastic batch-size algebra.

Capability parity with the reference's ``deepspeed/elasticity/elasticity.py``:
compute a total train batch size that stays valid across many accelerator
counts, from ``{max_train_batch_size, micro_batch_sizes, min_gpus, max_gpus}``,
using highly-composite-number candidates (reference elasticity.py:19-171), plus
a consistency check against a scheduler-provided config in the
``DEEPSPEED_ELASTICITY_CONFIG`` env var (reference elasticity.py:207-237).

All functions are pure math — no device code — and are shared by the config
system, the ``ds_elastic`` CLI, and tests.
"""

import json
import os

from deepspeed_tpu.elasticity.config import (
    ElasticityConfig,
    ElasticityConfigError,
    ElasticityError,
    ElasticityIncompatibleWorldSize,
)
from deepspeed_tpu.elasticity.constants import (
    ELASTICITY,
    ENABLED,
    ENABLED_DEFAULT,
    LATEST_ELASTICITY_VERSION,
    MINIMUM_DEEPSPEED_VERSION,
    DEEPSPEED_ELASTICITY_CONFIG,
)
from deepspeed_tpu.utils.logging import logger

# Highly composite numbers list: these have the most divisors of any number
# below them, so a batch built from them divides evenly across the most
# accelerator counts (same candidate-generation idea as the reference).
HCN_LIST = [
    1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840, 1260, 1680,
    2520, 5040, 7560, 10080, 15120, 20160, 25200, 27720, 45360, 50400, 55440,
    83160, 110880, 166320, 221760, 277200, 332640, 498960, 554400, 665280,
    720720, 1081080, 1441440, 2162160, 2882880, 3603600, 4324320, 6486480,
    7207200, 8648640, 10810800, 14414400, 17297280, 21621600, 32432400,
]


def get_candidate_batch_sizes(base_list, max_acceptable_batch_size):
    """For each micro batch, the largest HCN multiple that fits the cap."""
    candidate_batch_size = []
    for base in base_list:
        if base >= max_acceptable_batch_size:
            candidate_batch_size.append(base)
        else:
            value = max_acceptable_batch_size // base
            index = _find_index_nearest_below(HCN_LIST, value)
            candidate_batch_size.append(HCN_LIST[index] * base)
    return list(set(candidate_batch_size))


def _find_index_nearest_below(sorted_list, target):
    """Index of the largest element <= target (list is sorted ascending)."""
    lo, hi = 0, len(sorted_list) - 1
    best = 0
    while lo <= hi:
        mid = (lo + hi) // 2
        if sorted_list[mid] <= target:
            best = mid
            lo = mid + 1
        else:
            hi = mid - 1
    return best


def get_valid_gpus(batch_size, micro_batches, min_valid_gpus, max_valid_gpus):
    """All accelerator counts in range that evenly consume ``batch_size``."""
    valid_gpus = []
    for micro_batch in micro_batches:
        if batch_size % micro_batch == 0:
            max_gpus = batch_size // micro_batch
            if max_gpus >= min_valid_gpus and max_gpus <= max_valid_gpus:
                valid_gpus.append(max_gpus)
            for i in range(1, max_gpus // 2 + 1):
                if max_gpus % i == 0:
                    if i >= min_valid_gpus and i <= max_valid_gpus:
                        valid_gpus.append(i)
    valid_gpus = set(valid_gpus)
    valid_gpus = sorted(list(valid_gpus))
    return valid_gpus


def get_best_candidates(candidate_batch_sizes, micro_batches, min_gpus, max_gpus, prefer_larger):
    max_valid_gpus = 0
    valid_gpus = None
    final_batch_size = int(min(micro_batches))

    for batch_size in candidate_batch_sizes:
        current_valid_gpus = get_valid_gpus(batch_size, micro_batches, min_gpus, max_gpus)
        if len(current_valid_gpus) > max_valid_gpus or (
            len(current_valid_gpus) == max_valid_gpus
            and (
                (prefer_larger and batch_size > final_batch_size)
                or (not prefer_larger and batch_size < final_batch_size)
            )
        ):
            max_valid_gpus = len(current_valid_gpus)
            valid_gpus = current_valid_gpus
            final_batch_size = batch_size

    return final_batch_size, valid_gpus


def _get_compatible_gpus_v01(
    micro_batches, max_acceptable_batch_size, min_gpus=None, max_gpus=None, prefer_larger=True
):
    """Get valid accelerator counts (and the final batch size) for an elastic config.

    Returns (final_batch_size, valid_gpus).
    """
    min_gpus = min_gpus or 1
    max_gpus = max_gpus or max_acceptable_batch_size // min(micro_batches)

    if not all(mb <= max_acceptable_batch_size for mb in micro_batches):
        raise ValueError(
            f"All micro batches must be less than or equal to max_acceptable_batch_size: {max_acceptable_batch_size}"
        )

    # Also consider the LCM of the micro batches as a candidate base: a batch
    # built on it is divisible by every configured micro batch at once.
    lcm = _lcm_list(micro_batches)
    base_list = list(micro_batches)
    if lcm <= max_acceptable_batch_size:
        base_list.append(lcm)

    candidate_batch_sizes = get_candidate_batch_sizes(base_list, max_acceptable_batch_size)
    final_batch, valid_gpus = get_best_candidates(
        candidate_batch_sizes, micro_batches, min_gpus, max_gpus, prefer_larger
    )
    if valid_gpus is None or len(valid_gpus) == 0:
        raise ElasticityError(
            "Unable to find any valid accelerator counts for the given elastic config: "
            f"micro_batches={micro_batches}, max_acceptable_batch_size={max_acceptable_batch_size}, "
            f"min_gpus={min_gpus}, max_gpus={max_gpus}"
        )
    return final_batch, valid_gpus


def _lcm_list(values):
    from math import gcd

    lcm = 1
    for v in values:
        lcm = lcm * v // gcd(lcm, v)
    return lcm


def _parse_version(version_str):
    parts = str(version_str).split(".")
    return tuple(int("".join(c for c in p if c.isdigit()) or 0) for p in parts[:3])


def elasticity_enabled(ds_config):
    if ELASTICITY not in ds_config:
        return False
    return ds_config[ELASTICITY].get(ENABLED, ENABLED_DEFAULT)


def ensure_immutable_elastic_config(runtime_elastic_config_dict):
    """If the resource scheduler exported an elastic config via env, the runtime
    config must match it exactly (reference elasticity.py:207-237)."""
    if DEEPSPEED_ELASTICITY_CONFIG not in os.environ:
        logger.warning(
            f"Unable to find {DEEPSPEED_ELASTICITY_CONFIG} environment variable, "
            "cannot guarantee resource scheduler will scale this job using compatible accelerator counts."
        )
    if DEEPSPEED_ELASTICITY_CONFIG in os.environ:
        scheduler_elastic_config_dict = json.loads(os.environ[DEEPSPEED_ELASTICITY_CONFIG])
        scheduler_elastic_config = ElasticityConfig(scheduler_elastic_config_dict)
        runtime_elastic_config = ElasticityConfig(runtime_elastic_config_dict)
        err_str = (
            "Elastic config '{}={}' seems to have changed since run was launched. "
            "Scheduler saw '{}={}' but runtime now sees '{}={}'"
        )
        if runtime_elastic_config.max_acceptable_batch_size != scheduler_elastic_config.max_acceptable_batch_size:
            raise ElasticityConfigError(
                err_str.format(
                    "max_acceptable_batch_size",
                    runtime_elastic_config.max_acceptable_batch_size,
                    "max_acceptable_batch_size",
                    scheduler_elastic_config.max_acceptable_batch_size,
                    "max_acceptable_batch_size",
                    runtime_elastic_config.max_acceptable_batch_size,
                )
            )
        if runtime_elastic_config.micro_batches != scheduler_elastic_config.micro_batches:
            raise ElasticityConfigError(
                err_str.format(
                    "micro_batches",
                    runtime_elastic_config.micro_batches,
                    "micro_batches",
                    scheduler_elastic_config.micro_batches,
                    "micro_batches",
                    runtime_elastic_config.micro_batches,
                )
            )
        if runtime_elastic_config.version != scheduler_elastic_config.version:
            raise ElasticityConfigError(
                err_str.format(
                    "version",
                    runtime_elastic_config.version,
                    "version",
                    scheduler_elastic_config.version,
                    "version",
                    runtime_elastic_config.version,
                )
            )


def compute_elastic_config(ds_config, target_deepspeed_version, world_size=0):
    """Core elastic-config computation.

    Args:
        ds_config: full config dict containing an ``elasticity`` section.
        target_deepspeed_version: version string of this library (compat check).
        world_size: if nonzero, also validate/choose a micro batch for it.

    Returns:
        (final_batch_size, valid_gpus[, micro_batch_size if world_size given])
    """
    if not isinstance(ds_config, dict):
        raise ValueError("Expected ds_config to be a dictionary but received " f"a {type(ds_config)}, containing: {ds_config}")

    if ELASTICITY not in ds_config:
        raise ElasticityConfigError(
            f"'{ELASTICITY}' is missing from config json, please add it if running an elastic training job."
        )

    elastic_config_dict = ds_config[ELASTICITY]
    if not elasticity_enabled(ds_config):
        raise ElasticityError("Elasticity is not enabled, please enable it in the config")

    elastic_config = ElasticityConfig(elastic_config_dict)

    if float(elastic_config.version) > LATEST_ELASTICITY_VERSION:
        raise ElasticityConfigError(
            "Attempting to run elasticity version "
            f"{elastic_config.version} but runtime only supports up "
            f"to {LATEST_ELASTICITY_VERSION}"
        )

    if _parse_version(target_deepspeed_version) < _parse_version(MINIMUM_DEEPSPEED_VERSION):
        raise ElasticityError(
            f"Unable to run elasticity on target deepspeed version of "
            f"{target_deepspeed_version}, currently {MINIMUM_DEEPSPEED_VERSION} is minimum version supported."
        )

    if float(elastic_config.version) == 0.1:
        final_batch_size, valid_gpus = _get_compatible_gpus_v01(
            micro_batches=elastic_config.micro_batches,
            max_acceptable_batch_size=elastic_config.max_acceptable_batch_size,
            min_gpus=elastic_config.min_gpus,
            max_gpus=elastic_config.max_gpus,
            prefer_larger=elastic_config.prefer_larger_batch_size,
        )
        final_batch_size = int(final_batch_size)
    else:
        raise NotImplementedError(f"Unable to find elastic logic for version: {elastic_config.version}")

    if world_size > 0:
        if world_size not in valid_gpus:
            raise ElasticityIncompatibleWorldSize(
                f"World size ({world_size}) is not valid with the current list of valid accelerator counts: {valid_gpus}"
            )
        # Pick the best-fitting micro batch for this world size.
        micro_batch_size = None
        sorted_micro_batches = sorted(elastic_config.micro_batches, reverse=elastic_config.prefer_larger_batch_size)
        for mbsz in sorted_micro_batches:
            if final_batch_size // world_size % mbsz == 0:
                micro_batch_size = mbsz
                break
        assert micro_batch_size is not None, (
            "Unable to find divisible micro batch size"
            f" world_size={world_size}, final_batch_size={final_batch_size}, and "
            f" micro_batches={elastic_config.micro_batches}."
        )
        return final_batch_size, valid_gpus, micro_batch_size

    return final_batch_size, valid_gpus
