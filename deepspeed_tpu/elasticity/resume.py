"""Elastic resume: re-split the batch when a job restarts at a new world size.

The HCN algebra in ``elasticity.py`` picks one *global* train batch size
valid across many accelerator counts. That makes restart-at-a-different-
world-size loss-trajectory-preserving **iff** the restarted run keeps that
global batch and only re-splits it into micro-batch x grad-accumulation x
world. ``compute_elastic_resume`` is that re-split: it validates the new
world size (raising the named ``ElasticityIncompatibleWorldSize``) and
returns the new splits, asserting the global batch did not move.

Pure math, no device code — shared by ``DeepSpeedEngine``'s checkpoint
restore path (see ``_maybe_elastic_resume``) and tests.
"""

from deepspeed_tpu.elasticity.config import ElasticityConfigError
from deepspeed_tpu.elasticity.elasticity import compute_elastic_config
from deepspeed_tpu.utils.logging import logger


def compute_elastic_resume(ds_config, target_deepspeed_version,
                           prev_world_size, new_world_size,
                           saved_train_batch_size=None):
    """Splits for resuming an elastic job at ``new_world_size``.

    Args:
        ds_config: full config dict with an ``elasticity`` section.
        target_deepspeed_version: this library's version (compat check).
        prev_world_size: data-parallel world size the checkpoint was saved
            at (0/None when unknown — validation of the new size still runs).
        new_world_size: data-parallel world size of the restarted job.
        saved_train_batch_size: the global batch recorded in the
            checkpoint, when available; a mismatch against the recomputed
            batch means the elastic config changed between runs and the
            loss trajectory would silently break — that raises
            ``ElasticityConfigError``.

    Returns:
        dict with ``train_batch_size``, ``micro_batch_size``,
        ``gradient_accumulation_steps``, ``valid_gpus``.

    Raises:
        ElasticityIncompatibleWorldSize: ``new_world_size`` cannot consume
            the elastic global batch evenly.
        ElasticityConfigError: the recomputed global batch differs from the
            one the checkpoint was trained with.
    """
    final_batch, valid_gpus, micro_batch = compute_elastic_config(
        ds_config, target_deepspeed_version, world_size=new_world_size
    )
    if saved_train_batch_size is not None and int(saved_train_batch_size) != final_batch:
        raise ElasticityConfigError(
            f"elastic resume would change the global batch: checkpoint was "
            f"trained with train_batch_size={saved_train_batch_size} but the "
            f"current elastic config computes {final_batch} — the elasticity "
            "section changed between runs"
        )
    gas = final_batch // (micro_batch * new_world_size)
    if prev_world_size and prev_world_size != new_world_size:
        logger.info(
            f"[elasticity] resuming at world size {new_world_size} (was "
            f"{prev_world_size}): global batch {final_batch} preserved as "
            f"{micro_batch} micro x {gas} accumulation x {new_world_size} ranks"
        )
    return {
        "train_batch_size": final_batch,
        "micro_batch_size": micro_batch,
        "gradient_accumulation_steps": gas,
        "valid_gpus": valid_gpus,
    }
