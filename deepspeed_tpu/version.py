__version__ = "0.1.0"
git_hash = None
git_branch = None
