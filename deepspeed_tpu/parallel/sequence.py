"""Sequence/context parallelism: ring attention over the mesh.

The reference's long-sequence story is block-sparse attention + activation
checkpointing (SURVEY §2.2: SP/CP absent in v0.3.10) — but long-context is
first-class here: ring attention shards the SEQUENCE across devices and
rotates key/value chunks around the ring with ``ppermute``, overlapping each
hop with the local attention partial. Memory per device is O(S/W * D) and the
full S x S score matrix never exists anywhere — sequences scale linearly with
the ring size.

``ring_attention`` composes with the fused kernel design: each hop's partial
uses the same online-softmax merge the Pallas kernel uses per block, so the
math is exactly flash attention, distributed.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from deepspeed_tpu.parallel.mesh import DATA_AXIS
from deepspeed_tpu.utils.shard_map_compat import shard_map


def _local_attention_partial(q, k, v, bias, q_offset, k_offset, causal):
    """Partial attention of local q against one k/v chunk: returns
    (m, l, acc) for the online-softmax merge. Shapes: q [B,H,Sq,D],
    k/v [B,H,Sk,D], bias [B, Sk]."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhsd,bhtd->bhst", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    s = s + bias[:, None, None, :].astype(jnp.float32)
    if causal:
        Sq, Sk = q.shape[2], k.shape[2]
        q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 0)
        k_pos = k_offset + jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 1)
        s = jnp.where((q_pos >= k_pos)[None, None], s, -1e30)
    m = jnp.max(s, axis=-1, keepdims=True)                      # [B,H,Sq,1]
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhst,bhtd->bhsd", p, v.astype(jnp.float32))
    return m, l, acc


def _merge(carry, part):
    m0, l0, a0 = carry
    m1, l1, a1 = part
    m = jnp.maximum(m0, m1)
    c0 = jnp.exp(m0 - m)
    c1 = jnp.exp(m1 - m)
    return m, l0 * c0 + l1 * c1, a0 * c0 + a1 * c1


def ring_attention_local(q, k, v, bias, axis_name, causal=False):
    """Runs INSIDE shard_map: q,k,v are the local [B,H,S/W,D] sequence shards,
    ``bias`` the local [B, S/W] key bias. Rotates k/v around ``axis_name``.
    """
    W = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    Sc = q.shape[2]
    perm = [(i, (i + 1) % W) for i in range(W)]  # chunks move to the next rank

    m = jnp.full(q.shape[:3] + (1,), -1e30, jnp.float32)
    l = jnp.zeros(q.shape[:3] + (1,), jnp.float32)
    acc = jnp.zeros(q.shape, jnp.float32)
    if hasattr(jax.lax, "pcast"):
        # carry entries must be device-varying over the ring axis from the
        # start (shard_map vma typing): constants start unvarying.
        m, l, acc = (jax.lax.pcast(t, (axis_name,), to="varying") for t in (m, l, acc))

    def body(step, carry):
        m, l, acc, k_cur, v_cur, b_cur = carry
        # chunk currently held arrived from rank (idx - step) mod W
        src = jax.lax.rem(idx - step + W, W)
        part = _local_attention_partial(
            q, k_cur, v_cur, b_cur, q_offset=idx * Sc, k_offset=src * Sc, causal=causal
        )
        m, l, acc = _merge((m, l, acc), part)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        b_nxt = jax.lax.ppermute(b_cur, axis_name, perm)
        return m, l, acc, k_nxt, v_nxt, b_nxt

    m, l, acc, _, _, _ = jax.lax.fori_loop(0, W, body, (m, l, acc, k, v, bias))
    out = jnp.where(l > 0, acc / jnp.where(l > 0, l, 1.0), 0.0)
    return out.astype(q.dtype)


def ring_attention(q, k, v, mask=None, mesh=None, axis_name=DATA_AXIS, causal=False):
    """Driver: shards [B,H,S,D] inputs along ``axis_name`` over ``mesh`` and
    runs the ring. ``mask``: additive [B,S] (or [B,1,1,S]) key bias."""
    B, H, S, D = q.shape
    if mesh is None:
        import deepspeed_tpu.parallel.mesh as mesh_lib

        mesh = mesh_lib.create_mesh()
    W = mesh.shape[axis_name]
    assert S % W == 0, f"seq len {S} must divide ring size {W}"
    if mask is None:
        bias = jnp.zeros((B, S), jnp.float32)
    elif mask.ndim == 4:
        bias = mask[:, 0, 0, :].astype(jnp.float32)
    else:
        bias = mask.astype(jnp.float32)

    seq = PartitionSpec(None, None, axis_name, None)
    bseq = PartitionSpec(None, axis_name)
    fn = shard_map(
        functools.partial(ring_attention_local, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(seq, seq, seq, bseq),
        out_specs=seq,
    )
    return fn(q, k, v, bias)
