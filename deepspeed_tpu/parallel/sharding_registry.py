"""Shared sharding-spec registry: ONE ordered regex -> PartitionSpec rule
table over param-tree paths, consumed by BOTH engines.

The reference scatters distribution decisions across process groups and
per-engine heuristics; DeepCompile's argument (PAPERS.md) is that they
belong in one compiler-visible layer. This module is that layer for the
TPU port: an ordered ``match_partition_rules``-style rule table (first
match wins, exactly like the EasyLM/levanter exemplars in SNIPPETS.md)
resolves every placement the repo makes —

- the serving engine's tensor-parallel params, its paged KV pool
  ``[L, n_pages+1, nh, page_tokens, hd]`` (sharded over heads on the
  ``model`` axis), and its replicated host-uploaded lane state;
- the ZeRO train engine's flat-shard/overlap-pin placements
  (``runtime/zero/sharded_optimizer.py`` resolves through
  ``train_sharding`` instead of ad-hoc spec literals).

jaxlint JL011 treats the ``*_PARTITION_RULES`` dict literals below as
the canonical table: a PartitionSpec literal elsewhere that disagrees
with the registry rule for the same tree path is a finding, so spec
truth cannot fork per engine.

Named failure modes are real exceptions, not silent resharding:
``UnmatchedPathError`` (a leaf no rule matches, unless the registry was
built with ``replicate_unmatched=True``) and ``UnknownAxisError`` (a
rule names an axis the mesh does not define).
"""

import re

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    create_mesh,
)


class ShardingRegistryError(ValueError):
    """Base class for registry failures (a ValueError: bad rule tables
    are configuration errors)."""


class UnmatchedPathError(ShardingRegistryError):
    """A param-tree path matched no rule and ``replicate_unmatched`` is
    off — the registry refuses to guess a placement."""


class UnknownAxisError(ShardingRegistryError):
    """A rule's PartitionSpec names a mesh axis the target mesh (or
    configured ``mesh_shape``) does not define."""


# ---------------------------------------------------------------------------
# Canonical rule tables.
#
# Keys are ordered regexes searched against '/'-joined tree paths; first
# match wins. jaxlint harvests these dict literals as the canonical
# spec registry (names ending in _PARTITION_RULES), so keep every
# project-wide placement here rather than inline at the use site.
# ---------------------------------------------------------------------------

# Serving/tensor-parallel rules for the GPT-2 scanned-layer tree
# (stacked leaves: kernels [L, in, out], biases [L, dim]).  Megatron
# split: column-parallel qkv/ff1 (output dim over `model`), row-parallel
# attn_out/ff2 (input dim over `model`), everything else replicated.
# The non-param `serving/*` paths are the engine's device buffers:
# the paged KV pool and its quant scales shard the heads dim, lane
# state uploads replicate.
SERVING_PARTITION_RULES = {
    r"(qkv|ff1)/(kernel|kernel_q)$": PartitionSpec(None, None, MODEL_AXIS),
    r"(qkv|ff1)/(bias|scale)$": PartitionSpec(None, MODEL_AXIS),
    r"(attn_out|ff2)/(kernel|kernel_q)$": PartitionSpec(None, MODEL_AXIS, None),
    r"^serving/kv_pool$": PartitionSpec(None, None, MODEL_AXIS, None, None),
    r"^serving/kv_scale$": PartitionSpec(None, None, MODEL_AXIS, None, None),
    r"^serving/prefill_kv$": PartitionSpec(None, None, MODEL_AXIS, None, None),
    r"^serving/lane_state$": PartitionSpec(),
    r".*": PartitionSpec(),
}

# ZeRO train-engine placements: the 1/world flat master+grad shards
# split over `data`, the overlap-tap grad buckets and gathered params
# pin replicated, ZeRO-3 stacked leaves split their leading dim.
TRAIN_PARTITION_RULES = {
    r"^zero/flat_shard$": PartitionSpec(DATA_AXIS),
    r"^zero/grad_bucket$": PartitionSpec(),
    r"^zero/gathered$": PartitionSpec(),
    r"^zero3/stacked_leading$": PartitionSpec(DATA_AXIS),
}


def tree_path_str(path):
    """'/'-joined key path for a ``tree_map_with_path`` entry."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _spec_axes(spec):
    """Flat tuple of axis names a PartitionSpec mentions."""
    axes = []
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            axes.extend(entry)
        else:
            axes.append(entry)
    return tuple(axes)


class ShardingRegistry:
    """Ordered first-match-wins regex -> PartitionSpec rule table.

    ``rules`` is a dict (insertion-ordered) or iterable of
    ``(pattern, PartitionSpec)`` pairs. Patterns are ``re.search``-ed
    against '/'-joined tree paths. Scalar leaves are always replicated
    regardless of the matching rule (a 0-d array admits no partitioned
    dim). Unmatched paths raise :class:`UnmatchedPathError` unless
    ``replicate_unmatched`` is set.
    """

    def __init__(self, rules, replicate_unmatched=False, name="registry"):
        if isinstance(rules, dict):
            rules = rules.items()
        self.rules = []
        for pattern, spec in rules:
            if not isinstance(spec, PartitionSpec):
                spec = PartitionSpec(*spec)
            self.rules.append((pattern, re.compile(pattern), spec))
        self.replicate_unmatched = bool(replicate_unmatched)
        self.name = name

    # -- validation ---------------------------------------------------

    def axes(self):
        """All axis names any rule mentions."""
        out = []
        for _, _, spec in self.rules:
            for ax in _spec_axes(spec):
                if ax not in out:
                    out.append(ax)
        return tuple(out)

    def validate_axes(self, mesh_axes):
        """Raise :class:`UnknownAxisError` if any rule names an axis
        outside ``mesh_axes`` (an iterable of axis names or a Mesh)."""
        if hasattr(mesh_axes, "axis_names"):
            mesh_axes = mesh_axes.axis_names
        known = tuple(mesh_axes)
        for pattern, _, spec in self.rules:
            for ax in _spec_axes(spec):
                if ax not in known:
                    raise UnknownAxisError(
                        f"{self.name}: rule {pattern!r} names axis "
                        f"{ax!r} but the mesh defines only {known}"
                    )
        return self

    # -- resolution ---------------------------------------------------

    def spec_for(self, path, ndim=None):
        """First-match PartitionSpec for a '/'-joined tree path.

        ``ndim=0`` (scalar leaf) always resolves replicated. A spec
        longer than ``ndim`` is a rule/leaf rank mismatch and raises
        :class:`ShardingRegistryError`.
        """
        if ndim == 0:
            return PartitionSpec()
        for pattern, rx, spec in self.rules:
            if rx.search(path):
                if ndim is not None and len(spec) > ndim:
                    raise ShardingRegistryError(
                        f"{self.name}: rule {pattern!r} spec {spec} has "
                        f"{len(spec)} entries but leaf '{path}' has only "
                        f"{ndim} dims"
                    )
                return spec
        if self.replicate_unmatched:
            return PartitionSpec()
        raise UnmatchedPathError(
            f"{self.name}: no rule matches param-tree path '{path}' "
            f"(set replicate_unmatched=True to default to replication)"
        )

    def specs(self, tree):
        """Pytree of PartitionSpecs mirroring ``tree``."""
        return jax.tree_util.tree_map_with_path(
            lambda path, leaf: self.spec_for(
                tree_path_str(path), ndim=np.ndim(leaf)),
            tree,
        )

    def shardings(self, mesh, tree):
        """Pytree of NamedShardings for ``tree`` over ``mesh``."""
        self.validate_axes(mesh)
        return jax.tree_util.tree_map(
            lambda spec: NamedSharding(mesh, spec), self.specs(tree),
            is_leaf=lambda x: isinstance(x, PartitionSpec),
        )

    # -- placement ----------------------------------------------------

    def make_shard_fns(self, mesh, tree):
        """Pytree of per-leaf callables placing a leaf on the mesh per
        its matched rule (the EasyLM ``make_shard_fns`` shape)."""
        return jax.tree_util.tree_map(
            lambda sh: (lambda leaf, _sh=sh: jax.device_put(leaf, _sh)),
            self.shardings(mesh, tree),
            is_leaf=lambda x: isinstance(x, NamedSharding),
        )

    def make_gather_fns(self, mesh, tree):
        """Pytree of per-leaf callables gathering a leaf back to a
        fully-replicated array on the mesh (bitwise round-trip partner
        of :meth:`make_shard_fns`)."""
        replicated = NamedSharding(mesh, PartitionSpec())
        return jax.tree_util.tree_map(
            lambda _sh: (lambda leaf: jax.device_put(leaf, replicated)),
            self.shardings(mesh, tree),
            is_leaf=lambda x: isinstance(x, NamedSharding),
        )

    def shard(self, mesh, tree):
        """Place every leaf of ``tree`` per its matched rule."""
        return jax.tree_util.tree_map(
            lambda fn, leaf: fn(leaf), self.make_shard_fns(mesh, tree),
            tree)

    def gather(self, mesh, tree):
        """Gather every leaf of ``tree`` back to replicated."""
        return jax.tree_util.tree_map(
            lambda fn, leaf: fn(leaf), self.make_gather_fns(mesh, tree),
            tree)

    def table(self):
        """Aggregated ordered {pattern: PartitionSpec} view (what the
        jaxlint JL011 cross-check and the docs render)."""
        return {pattern: spec for pattern, _, spec in self.rules}


def match_partition_rules(rules, tree, replicate_unmatched=False):
    """Functional one-shot: pytree of PartitionSpecs for ``tree`` from
    ordered ``rules`` (the SNIPPETS ``match_partition_rules`` shape)."""
    return ShardingRegistry(
        rules, replicate_unmatched=replicate_unmatched).specs(tree)


# ---------------------------------------------------------------------------
# Mesh factory + the two canonical registries.
# ---------------------------------------------------------------------------

def normalize_mesh_shape(mesh_shape):
    """(data, model) ints from a 2-sequence or {axis: size} dict."""
    if mesh_shape is None:
        return 1, 1
    if isinstance(mesh_shape, dict):
        unknown = [k for k in mesh_shape if k not in (DATA_AXIS, MODEL_AXIS)]
        if unknown:
            raise UnknownAxisError(
                f"mesh_shape names unknown axes {unknown!r}; serving "
                f"meshes define ({DATA_AXIS!r}, {MODEL_AXIS!r})"
            )
        data = int(mesh_shape.get(DATA_AXIS, 1))
        model = int(mesh_shape.get(MODEL_AXIS, 1))
    else:
        shape = tuple(int(v) for v in mesh_shape)
        if len(shape) != 2:
            raise ShardingRegistryError(
                f"mesh_shape must be (data, model), got {mesh_shape!r}")
        data, model = shape
    if data < 1 or model < 1:
        raise ShardingRegistryError(
            f"mesh_shape sizes must be >= 1, got ({data}, {model})")
    return data, model


def create_serving_mesh(mesh_shape, devices=None):
    """('pipe','data','model') Mesh for a (data, model) shape over the
    first data*model devices, reusing ``parallel/mesh.py``'s factory so
    axis names/order stay the project-wide constants."""
    data, model = normalize_mesh_shape(mesh_shape)
    devices = list(devices if devices is not None else jax.devices())
    need = data * model
    if len(devices) < need:
        raise ShardingRegistryError(
            f"mesh_shape ({data}, {model}) needs {need} devices, "
            f"have {len(devices)}"
        )
    return create_mesh(data_parallel_size=data, model_parallel_size=model,
                       devices=devices[:need])


def serving_registry(extra_rules=None, replicate_unmatched=True):
    """The canonical serving-side registry. ``extra_rules`` (ordered
    (pattern, spec-elements) pairs, e.g. from ds_config
    ``parallel.partition_rules``) take precedence over the built-ins."""
    rules = list(extra_rules or [])
    rules += list(SERVING_PARTITION_RULES.items())
    return ShardingRegistry(rules, replicate_unmatched=replicate_unmatched,
                            name="serving_registry")


def train_registry():
    """The canonical train/ZeRO-side registry."""
    return ShardingRegistry(TRAIN_PARTITION_RULES, name="train_registry")


_TRAIN = None


def train_spec(path):
    """Registry-resolved PartitionSpec for a named train placement
    (e.g. 'zero/flat_shard')."""
    global _TRAIN
    if _TRAIN is None:
        _TRAIN = train_registry()
    return _TRAIN.spec_for(path)


def train_sharding(mesh, path):
    """NamedSharding for a named train placement over ``mesh``."""
    return NamedSharding(mesh, train_spec(path))


def serving_spec(path, registry=None):
    """Registry-resolved PartitionSpec for a named serving placement
    (e.g. 'serving/kv_pool')."""
    return (registry or serving_registry()).spec_for(path)


def serving_sharding(mesh, path, registry=None):
    """NamedSharding for a named serving placement over ``mesh``."""
    return NamedSharding(mesh, serving_spec(path, registry=registry))
