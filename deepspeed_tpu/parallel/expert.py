"""Mixture-of-Experts with expert parallelism.

Beyond the v0.3.10 reference (which predates DeepSpeed-MoE), but a
reference-family capability users expect: later DeepSpeed made MoE +
expert parallelism a headline feature. Built TPU-first rather than as a
port of that CUDA/torch design:

- **Static-capacity one-hot dispatch** (Switch/GShard): routing becomes
  three einsums (dispatch, expert FFN, combine) over a [tokens, experts,
  capacity] one-hot tensor — all MXU work, no scatter/gather, shapes
  static under jit. Tokens past an expert's capacity are dropped (their
  combine weight is zero), exactly the Switch training recipe.
- **Expert parallelism** = shard the expert dimension of the stacked
  expert params over an existing mesh axis (default ``data`` — the same
  expert-parallel-within-DP layout DeepSpeed-MoE uses) and exchange
  tokens with ONE ``lax.all_to_all`` each way inside ``shard_map``.
  Comm volume per device per direction is O(tokens/W * d_model),
  independent of the expert count.

Two entry points:
- ``MoELayer`` — flax module for the single-program pjit path; pair with
  ``expert_shardings`` to lay its stacked expert params over the mesh and
  let GSPMD partition the dispatch einsums.
- ``expert_parallel_ffn`` — the explicit shard_map + all_to_all program
  (runs INSIDE shard_map), for when the schedule must be pinned rather
  than left to the partitioner.
"""

from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
import flax.linen as nn
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.parallel.mesh import DATA_AXIS, replicated_sharding


# ---------------------------------------------------------------------------
# Routing (Switch-style top-1, static capacity)
# ---------------------------------------------------------------------------

def top1_gating(logits, capacity):
    """Switch top-1 router.

    logits: [T, E] raw router scores. capacity: max tokens per expert.
    Returns (dispatch [T, E, C] one-hot, combine [T, E, C] gate-weighted,
    aux_loss scalar). ``aux_loss`` is the Switch load-balancing loss
    E * sum_e(frac_tokens_e * mean_prob_e); 1.0 at perfect balance.
    """
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                      # [T]
    mask = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)      # [T, E]
    gate = jnp.sum(probs * mask, axis=-1)                        # [T]

    # position of each token within its expert's queue (0-based)
    pos = jnp.cumsum(mask, axis=0) * mask - mask                 # [T, E]
    keep = mask * (pos < capacity)                               # [T, E]
    pos_clamped = jnp.minimum(pos, capacity - 1).astype(jnp.int32)
    slot = jax.nn.one_hot(pos_clamped, capacity, dtype=jnp.float32)  # [T, E, C]

    dispatch = keep[:, :, None] * slot                           # [T, E, C]
    combine = dispatch * gate[:, None, None]                     # [T, E, C]

    frac_tokens = jnp.mean(mask, axis=0)                         # [E]
    mean_prob = jnp.mean(probs, axis=0)                          # [E]
    aux_loss = E * jnp.sum(frac_tokens * mean_prob)
    return dispatch, combine, aux_loss


def _expert_ffn(params, x):
    """Stacked-expert FFN: x [E, C, d] -> [E, C, d] through per-expert
    (w1 [E, d, f], b1 [E, f], w2 [E, f, d], b2 [E, d]). Weights cast to the
    activation dtype so bf16 activations get bf16 MXU operands (f32 master
    params stay f32 in the optimizer — same recipe as the fused layer)."""
    w1 = params["w1"].astype(x.dtype)
    w2 = params["w2"].astype(x.dtype)
    h = jnp.einsum("ecd,edf->ecf", x, w1) + params["b1"].astype(x.dtype)[:, None, :]
    h = jax.nn.gelu(h)
    return jnp.einsum("ecf,efd->ecd", h, w2) + params["b2"].astype(x.dtype)[:, None, :]


def moe_ffn(params, x, capacity):
    """Single-program MoE FFN over flat tokens x [T, d].

    params: {"router": [d, E], "w1": [E, d, f], "b1": [E, f],
             "w2": [E, f, d], "b2": [E, d]}.
    Returns (out [T, d], aux_loss).
    """
    # router math in f32 (softmax numerics); dispatch/FFN/combine stay in
    # x.dtype so bf16 activations keep bf16-MXU throughput on the three
    # big einsums — only the [T,E] gating tensors are ever f32
    logits = x.astype(jnp.float32) @ params["router"]
    dispatch, combine, aux = top1_gating(logits, capacity)
    expert_in = jnp.einsum("td,tec->ecd", x, dispatch.astype(x.dtype))
    expert_out = _expert_ffn(params, expert_in)                  # [E, C, d]
    out = jnp.einsum("ecd,tec->td", expert_out, combine.astype(x.dtype))
    return out.astype(x.dtype), aux


# ---------------------------------------------------------------------------
# Expert parallelism (runs inside shard_map)
# ---------------------------------------------------------------------------

def expert_parallel_ffn(params, x, capacity, axis_name=DATA_AXIS):
    """MoE FFN with the expert dim sharded over ``axis_name``; call INSIDE
    shard_map. Local views: x [T/W, d] (token-sharded), expert params
    [E/W, ...] (expert-sharded), router [d, E] replicated.

    One all_to_all ships each device's [E, C_local, d] dispatch tensor so
    every device holds ALL tokens bound for its local experts; the inverse
    all_to_all ships results back. aux_loss is psum-averaged so every
    device returns the same scalar (routing is computed on local tokens —
    the data-parallel recipe; capacity is per device per expert).
    """
    W = jax.lax.psum(1, axis_name)
    E = params["w1"].shape[0] * W
    assert params["router"].shape[1] == E, (
        f"router scores {params['router'].shape[1]} experts but "
        f"{params['w1'].shape[0]} local x {W} devices = {E}")

    logits = x.astype(jnp.float32) @ params["router"]            # [Tl, E]
    dispatch, combine, aux = top1_gating(logits, capacity)
    aux = jax.lax.pmean(aux, axis_name)

    expert_in = jnp.einsum("td,tec->ecd", x, dispatch.astype(x.dtype))
    # [E, C, d] -> [El, W*C, d]: keep local experts, gather their tokens
    # from every device (tiled all_to_all: split dim 0 W ways, concat the
    # received slices along dim 1)
    expert_in = jax.lax.all_to_all(
        expert_in, axis_name, split_axis=0, concat_axis=1, tiled=True)
    expert_out = _expert_ffn(params, expert_in)                  # [El, W*C, d]
    # inverse: [El, W*C, d] -> [E, C, d]
    expert_out = jax.lax.all_to_all(
        expert_out, axis_name, split_axis=1, concat_axis=0, tiled=True)
    out = jnp.einsum("ecd,tec->td", expert_out, combine.astype(x.dtype))
    return out.astype(x.dtype), aux


def expert_shardings(mesh, params, axis=DATA_AXIS):
    """NamedShardings laying MoE params over ``mesh``: stacked expert
    tensors (leading expert dim) split on ``axis``, everything else (the
    router, and any non-MoE leaves in a larger tree) replicated.

    A leaf shards only when it is one of ``w1/b1/w2/b2`` inside a COMPLETE
    MoE param group — a mapping that also holds ``router`` and all four
    expert tensors as siblings (the tree ``MoELayer``/``moe_ffn`` produce).
    Name alone is not enough: plain dense blocks commonly call their
    weights ``w1``/``w2`` too, and sharding those would split d_model."""
    expert_names = {"w1", "b1", "w2", "b2"}
    moe_group = expert_names | {"router"}

    def is_moe_group(node):
        try:
            keys = set(node.keys())
        except AttributeError:
            return False
        return moe_group <= keys

    def walk(node, inside_group):
        if isinstance(node, dict) or hasattr(node, "keys"):
            grouped = is_moe_group(node)
            return type(node)(
                (k, walk(
                    v,
                    grouped and k in expert_names,
                )) for k, v in node.items())
        if isinstance(node, (list, tuple)):
            return type(node)(walk(v, False) for v in node)
        if inside_group:
            return NamedSharding(
                mesh, PartitionSpec(axis, *([None] * (node.ndim - 1))))
        return replicated_sharding(mesh)

    return walk(params, False)


# ---------------------------------------------------------------------------
# Flax module (single-program pjit path)
# ---------------------------------------------------------------------------

@dataclass
class MoEConfig:
    num_experts: int = 8
    d_model: int = 512
    d_ff: int = 2048
    # capacity = capacity_factor * T / E (Switch's recipe), min 4
    capacity_factor: float = 1.25


class MoELayer(nn.Module):
    """Switch-style MoE FFN block over [B, S, d] activations.

    Returns (out [B, S, d], aux_loss); add ``aux_loss`` (scaled, Switch
    uses 1e-2) to the training loss. Param tree: router [d, E] and stacked
    expert tensors w1/b1/w2/b2 with leading expert dim — shard the expert
    dim over the mesh with ``expert_shardings`` for expert parallelism.
    """
    config: MoEConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        B, S, d = x.shape
        assert d == cfg.d_model, (d, cfg.d_model)
        init = nn.initializers.normal(stddev=0.02)
        params = {
            "router": self.param("router", init, (d, cfg.num_experts), jnp.float32),
            "w1": self.param("w1", init, (cfg.num_experts, d, cfg.d_ff), jnp.float32),
            "b1": self.param("b1", nn.initializers.zeros, (cfg.num_experts, cfg.d_ff), jnp.float32),
            "w2": self.param("w2", init, (cfg.num_experts, cfg.d_ff, d), jnp.float32),
            "b2": self.param("b2", nn.initializers.zeros, (cfg.num_experts, d), jnp.float32),
        }
        T = B * S
        capacity = max(4, int(np.ceil(cfg.capacity_factor * T / cfg.num_experts)))
        out, aux = moe_ffn(params, x.reshape(T, d), capacity)
        return out.reshape(B, S, d), aux
