"""Device-mesh construction: the TPU-native replacement for the reference's
process-group zoo.

The reference builds NCCL process groups per parallel dimension
(``deepspeed/runtime/pipe/topology.py``, ``engine.py:69-85``). Here a single
``jax.sharding.Mesh`` with named axes ``('pipe', 'data', 'model')`` — mirroring
``PipeModelDataParallelTopology`` (pipe/topology.py:246) — carries all of that:
collectives take axis names, shardings are ``PartitionSpec``s over the axes,
and XLA lays collectives onto ICI.

Axis order is (pipe, data, model): model innermost so tensor-parallel
collectives ride the fastest ICI links, data next for reduce-scatter locality,
pipe outermost (lowest-bandwidth traffic).
"""

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

PIPE_AXIS = "pipe"
DATA_AXIS = "data"
MODEL_AXIS = "model"


def create_mesh(data_parallel_size=None, model_parallel_size=1, pipe_parallel_size=1, devices=None):
    """Build the ('pipe','data','model') mesh over the given (or all) devices.

    ``data_parallel_size=None`` means "all remaining devices".
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if data_parallel_size is None:
        assert n % (model_parallel_size * pipe_parallel_size) == 0, (
            f"device count {n} not divisible by model_parallel={model_parallel_size} "
            f"x pipe_parallel={pipe_parallel_size}"
        )
        data_parallel_size = n // (model_parallel_size * pipe_parallel_size)
    expected = data_parallel_size * model_parallel_size * pipe_parallel_size
    assert expected == n, f"mesh wants {expected} devices, have {n}"
    dev_array = np.asarray(devices).reshape(pipe_parallel_size, data_parallel_size, model_parallel_size)
    return Mesh(dev_array, (PIPE_AXIS, DATA_AXIS, MODEL_AXIS))


def data_sharding(mesh, ndim, batch_axis=0):
    """NamedSharding that splits ``batch_axis`` across the data axis."""
    spec = [None] * ndim
    spec[batch_axis] = DATA_AXIS
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated_sharding(mesh):
    return NamedSharding(mesh, PartitionSpec())


def dp_world_size(mesh):
    return mesh.shape[DATA_AXIS]


def mp_world_size(mesh):
    return mesh.shape[MODEL_AXIS]


def pp_world_size(mesh):
    return mesh.shape[PIPE_AXIS]


class MeshMpu:
    """mpu-compatible accessor facade over a mesh (reference honors an external
    Megatron ``mpu`` object everywhere; this is the native equivalent)."""

    def __init__(self, mesh):
        self.mesh = mesh

    def get_model_parallel_world_size(self):
        return mp_world_size(self.mesh)

    def get_data_parallel_world_size(self):
        return dp_world_size(self.mesh)

    def get_pipe_parallel_world_size(self):
        return pp_world_size(self.mesh)

    def get_model_parallel_rank(self):
        return 0  # per-device rank is only meaningful inside shard_map

    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_group(self):
        return MODEL_AXIS

    def get_data_parallel_group(self):
        return DATA_AXIS
