"""Ulysses-style all-to-all sequence parallelism.

Complement to ring attention (``parallel/sequence.py``): instead of rotating
k/v chunks, an ``all_to_all`` re-shards the activations from sequence-sharded
to HEAD-sharded just for the attention core, then back. Comm volume is
O(S*D/W) per device per direction (two all-to-alls), independent of W hops —
the better choice when heads >= ring size and the per-hop latency of the ring
would dominate.

Layout dance (inside shard_map over ``axis_name``; local shapes):
  in:  q,k,v [B, H, S/W, D]   (sequence sharded)
  a2a: -> [B, H/W, S, D]      (heads sharded, full sequence local)
  attention (any kernel — here the fused/flash path on full local sequence)
  a2a: out -> [B, H, S/W, D]  (back to sequence sharded)
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from deepspeed_tpu.ops.transformer.attention import flash_attention
from deepspeed_tpu.parallel.mesh import DATA_AXIS
from deepspeed_tpu.utils.shard_map_compat import shard_map


def _seq_to_heads(x, axis_name, W):
    """[B, H, Sc, D] -> [B, H/W, S, D]: split heads, all_to_all, join seq."""
    B, H, Sc, D = x.shape
    assert H % W == 0, f"heads {H} must divide axis size {W}"
    x = x.reshape(B, W, H // W, Sc, D)
    # split_axis=1 (head groups) becomes the device axis; the device axis
    # reappears at concat_axis=2 as the sequence-chunk index:
    # [B, W, Hw, Sc, D] -> [B, Hw, W, Sc, D] -> [B, Hw, S, D]
    x = jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=False)
    return x.reshape(B, H // W, W * Sc, D)


def _heads_to_seq(x, axis_name, W):
    """[B, H/W, S, D] -> [B, H, S/W, D]: inverse all-to-all."""
    B, Hw, S, D = x.shape
    Sc = S // W
    x = x.reshape(B, Hw, W, Sc, D)
    x = jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=False)
    return x.reshape(B, Hw * W, Sc, D)


def ulysses_attention_local(q, k, v, bias, axis_name, causal=False):
    """Runs INSIDE shard_map: q,k,v local [B, H, S/W, D]; bias local [B, S/W]."""
    W = jax.lax.psum(1, axis_name)
    qh = _seq_to_heads(q, axis_name, W)
    kh = _seq_to_heads(k, axis_name, W)
    vh = _seq_to_heads(v, axis_name, W)
    full_bias = jax.lax.all_gather(bias, axis_name, axis=1, tiled=True)  # [B, S]
    # Fused/flash local attention: on TPU this is the Pallas kernel over the
    # full local sequence (O(S*D) memory — the point of head-sharding), with
    # the dense reference fallback on other backends / unaligned S.
    out = flash_attention(qh, kh, vh, full_bias, causal=causal)
    return _heads_to_seq(out, axis_name, W)


def ulysses_attention(q, k, v, mask=None, mesh=None, axis_name=DATA_AXIS, causal=False):
    """Driver: [B,H,S,D] inputs sequence-sharded along ``axis_name``."""
    B, H, S, D = q.shape
    if mesh is None:
        import deepspeed_tpu.parallel.mesh as mesh_lib

        mesh = mesh_lib.create_mesh()
    W = mesh.shape[axis_name]
    assert S % W == 0 and H % W == 0, (
        f"seq {S} and heads {H} must divide the axis size {W}"
    )
    if mask is None:
        bias = jnp.zeros((B, S), jnp.float32)
    elif mask.ndim == 4:
        bias = mask[:, 0, 0, :].astype(jnp.float32)
    else:
        bias = mask.astype(jnp.float32)

    seq = PartitionSpec(None, None, axis_name, None)
    bseq = PartitionSpec(None, axis_name)
    kwargs = dict(
        mesh=mesh, in_specs=(seq, seq, seq, bseq), out_specs=seq,
    )
    local = functools.partial(ulysses_attention_local, axis_name=axis_name, causal=causal)
    # vma/rep checking must be off for pallas_call (the flash kernel's
    # ShapeDtypeStructs carry no vma annotations)
    fn = shard_map(local, check_rep=False, **kwargs)
    return fn(q, k, v, bias)
