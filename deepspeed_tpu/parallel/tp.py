"""Tensor (model) parallelism: Megatron-style sharding rules over the
``model`` mesh axis.

The reference only *cooperates* with an external Megatron mpu (SURVEY §2.2:
TP is "interface only" — engine.py:514-525, topology model axis). Here TP is
first-class the TPU way: parameters carry ``NamedSharding``s over the
``model`` axis and XLA/GSPMD inserts the (all-reduce/all-gather) collectives
the Megatron forward would issue by hand:

- column-parallel matmuls (qkv, ff1, embedding output) shard their OUTPUT
  feature dim,
- row-parallel matmuls (attention output, ff2) shard their INPUT feature dim
  (XLA emits the psum over ``model`` after the partial matmul),
- embeddings shard the vocab dim.

Rules are (regex over the param path, dim-spec) pairs; the dim-spec names
which dimension takes the ``model`` axis, counted from the TRAILING dims so
scanned layer stacks ([L, ...]-shaped params) match the same rules.
"""

import re

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.parallel.mesh import MODEL_AXIS
from deepspeed_tpu.utils.logging import logger

# (path regex, dim from the END that carries the model axis)
# Column-parallel: shard last dim (outputs). Row-parallel: shard 2nd-to-last
# (inputs). Biases of column-parallel layers shard their only dim.
MEGATRON_RULES = [
    (r"(qkv|query|key|value|[qkv]_proj|up_proj|gate_proj|in_proj|ff1|intermediate|wi|fc1|c_fc)/(kernel|w)$", 1),
    (r"(qkv|query|key|value|[qkv]_proj|up_proj|gate_proj|in_proj|ff1|intermediate|wi|fc1|c_fc)/(bias|b)$", 1),
    (r"(attn_out|attention_out|out_proj|o_proj|down_proj|wo|fc2|ff2|c_proj|output_dense)/(kernel|w)$", 2),
    (r"(word_embeddings|wte|embedding|embed)/(embedding|kernel)$", 2),
]


def _path_str(path):
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def spec_for(path, leaf, rules=MEGATRON_RULES, model_axis_size=None):
    """PartitionSpec for one param: the matched rule's dim-from-end gets the
    model axis; everything else replicated. Dims not divisible by
    ``model_axis_size`` stay replicated (so specs always match what
    ``shard_params`` actually lays out)."""
    s = _path_str(path)
    for pattern, dim_from_end in rules:
        if re.search(pattern, s):
            ndim = leaf.ndim
            if dim_from_end > ndim:
                continue
            dim = ndim - dim_from_end
            if model_axis_size is not None and leaf.shape[dim] % model_axis_size != 0:
                return PartitionSpec()
            spec = [None] * ndim
            spec[dim] = MODEL_AXIS
            return PartitionSpec(*spec)
    return PartitionSpec()


def shard_params(params, mesh, rules=MEGATRON_RULES, log=False):
    """Apply TP shardings to a param pytree (replicated along data/pipe)."""
    axis_size = mesh.shape[MODEL_AXIS]

    def put(path, leaf):
        spec = spec_for(path, leaf, rules, model_axis_size=axis_size)
        if log and spec != PartitionSpec():
            logger.info(f"TP shard {_path_str(path)} {leaf.shape} -> {spec}")
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map_with_path(put, params)


def param_specs(params, rules=MEGATRON_RULES, model_axis_size=None):
    """The PartitionSpec pytree (for pjit in_shardings / checkpoint layouts).
    Pass ``model_axis_size`` to get exactly the layout ``shard_params`` uses."""
    return jax.tree_util.tree_map_with_path(
        lambda p, l: spec_for(p, l, rules, model_axis_size=model_axis_size), params
    )
