"""deepspeed_tpu: a TPU-native large-model training framework.

Capability parity with DeepSpeed v0.3.10 (``deepspeed/__init__.py``), built
idiomatically on JAX/XLA/Pallas/pjit: ``initialize()`` returns an engine that
wraps a user model with data/ZeRO/pipeline/model parallelism over a device
mesh, mixed precision with (dynamic) loss scaling, fused TPU kernels, and
checkpointing.
"""

from deepspeed_tpu.version import __version__, git_branch, git_hash

version = __version__
__git_hash__ = git_hash
__git_branch__ = git_branch


def _parse_version(version_str):
    """major/minor/patch ints (reference __init__.py:24-31)."""
    import re

    m = re.match(r"(\d+)\.(\d+)\.(\d+)", version_str)
    return (int(m.group(1)), int(m.group(2)), int(m.group(3))) if m else (0, 0, 0)


__version_major__, __version_minor__, __version_patch__ = _parse_version(__version__)

# Public surface parity with the reference deepspeed/__init__.py:1-30:
# transformer kernel layer + config, pipeline module machinery, activation
# checkpointing, and the sparse-attention suite are importable from the top.
from deepspeed_tpu.ops.transformer.transformer import (  # noqa: E402
    DeepSpeedTransformerConfig,
    DeepSpeedTransformerLayer,
)
from deepspeed_tpu.runtime.pipe.module import (  # noqa: E402
    LayerSpec,
    PipelineModule,
    TiedLayerSpec,
)
from deepspeed_tpu.runtime.activation_checkpointing import checkpointing  # noqa: E402
from deepspeed_tpu.runtime.config import (  # noqa: E402
    DeepSpeedConfig,
    DeepSpeedConfigError,
)
from deepspeed_tpu.runtime.constants import (  # noqa: E402
    ADAM_OPTIMIZER,
    LAMB_OPTIMIZER,
)
from deepspeed_tpu.runtime.engine import DeepSpeedEngine  # noqa: E402
from deepspeed_tpu.runtime.lr_schedules import add_tuning_arguments  # noqa: E402
from deepspeed_tpu.runtime.pipe.engine import PipelineEngine  # noqa: E402
from deepspeed_tpu.utils.logging import log_dist  # noqa: E402


def initialize(args=None, model=None, optimizer=None, model_parameters=None,
               training_data=None, lr_scheduler=None, mpu=None, dist_init_required=None,
               collate_fn=None, config=None, config_params=None):
    """Initialize the DeepSpeedTPU engine (parity: reference deepspeed/__init__.py:50).

    Arguments mirror the reference. ``model`` is a deepspeed_tpu model spec (a
    flax/``Module``-like object or a ``PipelineModule``); returns a tuple of
    ``(engine, optimizer, training_dataloader, lr_scheduler)``.
    """
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    from deepspeed_tpu.runtime.pipe.engine import PipelineEngine
    from deepspeed_tpu.runtime.pipe.module import PipelineModule
    from deepspeed_tpu.utils.logging import log_dist

    log_dist(f"DeepSpeedTPU info: version={__version__}", ranks=[0])

    if isinstance(model, PipelineModule):
        engine = PipelineEngine(
            args=args,
            model=model,
            optimizer=optimizer,
            model_parameters=model_parameters,
            training_data=training_data,
            lr_scheduler=lr_scheduler,
            mpu=model.mpu(),
            dist_init_required=dist_init_required,
            collate_fn=collate_fn,
            config=config,
            config_params=config_params,
        )
    else:
        engine = DeepSpeedEngine(
            args=args,
            model=model,
            optimizer=optimizer,
            model_parameters=model_parameters,
            training_data=training_data,
            lr_scheduler=lr_scheduler,
            mpu=mpu,
            dist_init_required=dist_init_required,
            collate_fn=collate_fn,
            config=config,
            config_params=config_params,
        )

    return_items = [engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler]
    return tuple(return_items)


def add_config_arguments(parser):
    """Add DeepSpeed-style arguments to an argparse parser
    (parity: reference deepspeed/__init__.py:193 and :142-190)."""
    group = parser.add_argument_group("DeepSpeedTPU", "DeepSpeedTPU configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeedTPU (helper flag for user code, no impact on library behavior)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to DeepSpeedTPU json configuration.")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help="Deprecated enable flag (kept for config compatibility)")
    group.add_argument("--deepscale_config", default=None, type=str,
                       help="Deprecated config path (kept for config compatibility)")
    group.add_argument("--deepspeed_mpi", default=False, action="store_true",
                       help="Run via MPI; this flag will cause distributed env discovery through MPI.")
    return parser


def init_distributed(dist_backend=None, auto_mpi_discovery=True, distributed_port=None,
                     verbose=True, timeout=None, init_method=None):
    from deepspeed_tpu.utils.distributed import init_distributed as _init
    return _init(dist_backend=dist_backend, auto_mpi_discovery=auto_mpi_discovery,
                 distributed_port=distributed_port, verbose=verbose)
