"""Unified communication module over XLA collectives.

The single replacement for the reference's three comm paths —
``torch.distributed`` NCCL process groups, mpi4py custom collectives
(``runtime/custom_collectives.py``), and broadcast-pair p2p
(``runtime/pipe/p2p.py``). Every collective takes a mesh *axis name* instead of
a group handle; inside ``shard_map``/``pjit`` the ops lower to ICI collectives,
and across hosts the same program spans processes via ``jax.distributed``
(DCN for the control plane).

These wrappers are intentionally thin: their value is a stable, reference-shaped
API (all_reduce / all_gather / reduce_scatter / broadcast / p2p) for the engine,
ZeRO, 1-bit Adam, and pipeline code.
"""

import queue
import threading
from enum import Enum

import jax
import jax.numpy as jnp

from deepspeed_tpu.comm.errors import CommError, CommTimeoutError, DeadPeerError  # noqa: F401 — re-exported


class ReduceOp(Enum):
    SUM = "sum"
    AVG = "avg"
    MAX = "max"
    MIN = "min"
    PRODUCT = "product"


def all_reduce(x, axis_name, op=ReduceOp.SUM):
    """psum/pmax/... over a named mesh axis (inside shard_map/pjit)."""
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        out = jax.lax.psum(x, axis_name)
        if op == ReduceOp.AVG:
            out = out / jax.lax.psum(jnp.ones((), x.dtype), axis_name)
        return out
    if op == ReduceOp.MAX:
        return jax.lax.pmax(x, axis_name)
    if op == ReduceOp.MIN:
        return jax.lax.pmin(x, axis_name)
    if op == ReduceOp.PRODUCT:
        # no pprod primitive: gather the per-rank values and reduce locally
        # (XLA fuses this; fine for the scalar/flag uses PRODUCT serves)
        return jnp.prod(jax.lax.all_gather(x, axis_name, axis=0, tiled=False), axis=0)
    raise NotImplementedError(
        f"all_reduce op {op!r} is not supported "
        f"(supported: {', '.join(o.name for o in ReduceOp)})"
    )


def _axis_size(axis_name):
    """Static (python int) size of a named mesh axis at trace time.
    ``psum`` of the literal 1 is constant-folded to the axis size —
    ``jax.lax.axis_size`` does not exist on the pinned jax version."""
    return jax.lax.psum(1, axis_name)


def all_gather(x, axis_name, axis=0, tiled=True):
    """Gather shards along a named axis (reference all_gather over NCCL)."""
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, scatter_dimension=0):
    """Sum-reduce then scatter shards (reference dist.reduce_scatter; ZeRO's
    gradient partitioning primitive)."""
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension, tiled=True)


def broadcast(x, axis_name, root=0):
    """Everyone takes root's value: implemented as a select + psum (cheap on
    ICI; XLA pattern-matches this to a broadcast).

    ``root`` must be a valid index on ``axis_name`` (``0 <= root < axis
    size``): the mask below is simply false everywhere for an out-of-range
    root, which would silently broadcast zeros. The axis size is static at
    trace time, so this is checked eagerly."""
    n = _axis_size(axis_name)
    if not 0 <= int(root) < n:
        raise ValueError(
            f"broadcast root {root} is not a valid index on axis "
            f"'{axis_name}' (size {n})"
        )
    idx = jax.lax.axis_index(axis_name)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return jax.lax.psum(masked, axis_name)


def ppermute_send_recv(x, axis_name, shift=1):
    """Ring shift: rank i's value goes to rank i+shift (mod size). The pipeline
    engine's activation/grad exchange (replacing pipe/p2p.py's broadcast-pair
    trick with the native ICI collective-permute)."""
    n = _axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)


def _deadline_call(fn, timeout_s, what):
    """Run a blocking host-level call with a wall-clock deadline. The
    native collective cannot be cancelled, so the call runs on a daemon
    worker and the caller waits on a result queue: on expiry the worker is
    abandoned and a named ``CommTimeoutError`` surfaces instead of an
    eternal hang (same inversion as the resilience watchdog)."""
    if timeout_s is None or timeout_s <= 0:
        return fn()
    out = queue.Queue(maxsize=1)

    def run():
        try:
            out.put(("ok", fn()))
        except BaseException as e:  # noqa: BLE001 — re-raised on the caller side
            out.put(("err", e))

    threading.Thread(target=run, daemon=True, name=f"comm-deadline:{what}").start()
    try:
        kind, val = out.get(timeout=timeout_s)
    except queue.Empty:
        raise CommTimeoutError(what=what, timeout_s=timeout_s) from None
    if kind == "err":
        raise val
    return val


def _injected_hang():
    """Cluster fault-injection seam (hang_barrier arm); no-op outside
    fault-injection runs. Imported lazily — comm must not depend on the
    runtime package at import time."""
    from deepspeed_tpu.runtime.resilience.cluster_faults import get_active_injector

    inj = get_active_injector()
    if inj is not None:
        inj.maybe_hang_barrier()


def barrier(name="dstpu_barrier", timeout_s=None):
    """Cross-process barrier (reference dist.barrier). Single-process: just
    drain local async dispatch; multi-process: sync all global devices.

    ``timeout_s`` bounds the wait: a barrier a dead/wedged peer never
    joins raises ``CommTimeoutError`` within the deadline instead of
    hanging every surviving host forever. None/0 keeps the old unbounded
    behavior."""

    def sync():
        _injected_hang()
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(name)
        else:
            jax.block_until_ready(jax.device_put(0))

    return _deadline_call(sync, timeout_s, what=f"barrier '{name}'")


# Host-side helpers used outside jit ---------------------------------------

def host_allreduce_scalar(value, timeout_s=None):
    """Cross-process scalar sum using jax.distributed-backed collectives.
    ``timeout_s`` bounds the wait (``CommTimeoutError``), as in
    ``barrier``."""

    def reduce():
        _injected_hang()
        if jax.process_count() == 1:
            return value
        arr = jnp.asarray([value], jnp.float32)
        from jax.experimental import multihost_utils

        return float(multihost_utils.process_allgather(arr).sum())

    return _deadline_call(reduce, timeout_s, what="host_allreduce_scalar")
