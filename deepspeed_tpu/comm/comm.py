"""Unified communication module over XLA collectives.

The single replacement for the reference's three comm paths —
``torch.distributed`` NCCL process groups, mpi4py custom collectives
(``runtime/custom_collectives.py``), and broadcast-pair p2p
(``runtime/pipe/p2p.py``). Every collective takes a mesh *axis name* instead of
a group handle; inside ``shard_map``/``pjit`` the ops lower to ICI collectives,
and across hosts the same program spans processes via ``jax.distributed``
(DCN for the control plane).

These wrappers are intentionally thin: their value is a stable, reference-shaped
API (all_reduce / all_gather / reduce_scatter / broadcast / p2p) for the engine,
ZeRO, 1-bit Adam, and pipeline code.
"""

from enum import Enum

import jax
import jax.numpy as jnp


class ReduceOp(Enum):
    SUM = "sum"
    AVG = "avg"
    MAX = "max"
    MIN = "min"
    PRODUCT = "product"


def all_reduce(x, axis_name, op=ReduceOp.SUM):
    """psum/pmax/... over a named mesh axis (inside shard_map/pjit)."""
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        out = jax.lax.psum(x, axis_name)
        if op == ReduceOp.AVG:
            out = out / jax.lax.psum(jnp.ones((), x.dtype), axis_name)
        return out
    if op == ReduceOp.MAX:
        return jax.lax.pmax(x, axis_name)
    if op == ReduceOp.MIN:
        return jax.lax.pmin(x, axis_name)
    raise NotImplementedError(op)


def all_gather(x, axis_name, axis=0, tiled=True):
    """Gather shards along a named axis (reference all_gather over NCCL)."""
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, scatter_dimension=0):
    """Sum-reduce then scatter shards (reference dist.reduce_scatter; ZeRO's
    gradient partitioning primitive)."""
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension, tiled=True)


def broadcast(x, axis_name, root=0):
    """Everyone takes root's value: implemented as a select + psum (cheap on
    ICI; XLA pattern-matches this to a broadcast)."""
    idx = jax.lax.axis_index(axis_name)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return jax.lax.psum(masked, axis_name)


def ppermute_send_recv(x, axis_name, shift=1):
    """Ring shift: rank i's value goes to rank i+shift (mod size). The pipeline
    engine's activation/grad exchange (replacing pipe/p2p.py's broadcast-pair
    trick with the native ICI collective-permute)."""
    n = jax.lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name, perm)


def barrier(name="dstpu_barrier"):
    """Cross-process barrier (reference dist.barrier). Single-process: just
    drain local async dispatch; multi-process: sync all global devices."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)
    else:
        jax.block_until_ready(jax.device_put(0))


# Host-side helpers used outside jit ---------------------------------------

def host_allreduce_scalar(value):
    """Cross-process scalar sum using jax.distributed-backed collectives."""
    if jax.process_count() == 1:
        return value
    arr = jnp.asarray([value], jnp.float32)
    from jax.experimental import multihost_utils

    return float(multihost_utils.process_allgather(arr).sum())
