"""Named errors for cross-host communication.

The blocking host-level collectives (``barrier``, ``host_allreduce_scalar``)
sit on ``jax.distributed`` primitives that wait forever when a peer is gone
— on a preempted pod that turns one dead host into N hung ones. These
errors are the bounded alternative: a deadline produces a
``CommTimeoutError``, health gossip produces a ``DeadPeerError``, and
either one unwinds the step so the job-level supervisor can restart the
worker (see docs/cluster_resilience.md).
"""


class CommError(RuntimeError):
    """Base class for named communication failures."""


class CommTimeoutError(CommError, TimeoutError):
    """A host-level collective exceeded its deadline (a peer is likely
    dead or wedged). The underlying native call cannot be cancelled; its
    worker thread is abandoned (daemon) and the process is expected to
    exit for a supervised restart."""

    def __init__(self, what, timeout_s):
        self.what = what
        self.timeout_s = timeout_s
        super().__init__(
            f"{what} did not complete within the {timeout_s}s deadline "
            "(peer dead or wedged?)"
        )


class DeadPeerError(CommError):
    """Health gossip declared a peer host dead (stale heartbeat)."""

    def __init__(self, rank, stale_s, timeout_s):
        self.rank = rank
        self.stale_s = stale_s
        self.timeout_s = timeout_s
        super().__init__(
            f"peer rank {rank} has been silent for {stale_s:.1f}s "
            f"(> {timeout_s}s peer timeout) — escalating to coordinated restart"
        )
