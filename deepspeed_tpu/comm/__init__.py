from deepspeed_tpu.comm.comm import (
    all_reduce,
    all_gather,
    reduce_scatter,
    broadcast,
    ppermute_send_recv,
    barrier,
    host_allreduce_scalar,
    ReduceOp,
)
from deepspeed_tpu.comm.errors import CommError, CommTimeoutError, DeadPeerError
from deepspeed_tpu.comm.health import HealthGossip
