from deepspeed_tpu.comm.comm import (
    all_reduce,
    all_gather,
    reduce_scatter,
    broadcast,
    ppermute_send_recv,
    barrier,
    ReduceOp,
)
