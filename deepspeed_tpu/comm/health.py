"""Cross-host health gossip over a shared directory.

Each rank periodically touches its own heartbeat file
(``hb_<rank>``) in a directory every host can see (NFS/GCS-fuse mount —
the same class of storage checkpoints already use); ``check_peers``
reads the *other* ranks' mtimes and raises a named ``DeadPeerError``
once one goes stale. File mtimes instead of a network protocol keeps the
mechanism dead-simple, dependency-free, and — crucially for tests —
fully deterministic on a single CPU host: N processes sharing a tmpdir
gossip exactly like N hosts sharing a mount.

The engine drives this from its step boundary (beat + check once per
optimizer step) when the ``resilience`` config sets ``gossip_dir`` and
``peer_timeout_s``. A raised ``DeadPeerError`` unwinds ``train_batch`` on
every *surviving* host within one peer timeout — that is the coordinated
restart: each worker exits nonzero, each node's supervisor restarts it,
and the restarted job resumes from the last committed checkpoint tag.
"""

import os
import time

from deepspeed_tpu.comm.errors import DeadPeerError


class HealthGossip:
    def __init__(self, gossip_dir, rank, world_size, peer_timeout_s):
        self.gossip_dir = gossip_dir
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.peer_timeout_s = float(peer_timeout_s)
        os.makedirs(gossip_dir, exist_ok=True)
        self._path = os.path.join(gossip_dir, f"hb_{self.rank}")
        # A peer that has not written its first beat yet is measured from
        # our start, so startup skew cannot declare a booting host dead.
        self._started = time.time()
        self.beat()

    def _peer_path(self, rank):
        return os.path.join(self.gossip_dir, f"hb_{rank}")

    def beat(self):
        now = time.time()
        try:
            os.utime(self._path, (now, now))
        except OSError:
            with open(self._path, "a"):
                pass

    def last_seen(self, rank):
        """Seconds since ``rank`` last beat (from our start, if never)."""
        try:
            mtime = os.path.getmtime(self._peer_path(rank))
        except OSError:
            mtime = self._started
        return max(0.0, time.time() - mtime)

    def stale_peers(self):
        """[(rank, stale_s)] for every peer past the timeout."""
        out = []
        for rank in range(self.world_size):
            if rank == self.rank:
                continue
            stale = self.last_seen(rank)
            if stale > self.peer_timeout_s:
                out.append((rank, stale))
        return out

    def check_peers(self):
        """Raise ``DeadPeerError`` for the stalest dead peer, if any."""
        stale = self.stale_peers()
        if stale:
            rank, stale_s = max(stale, key=lambda rs: rs[1])
            raise DeadPeerError(rank=rank, stale_s=stale_s, timeout_s=self.peer_timeout_s)
