"""Banded sink+window block-sparse attention as a Pallas kernel.

The `sparse_xla` seam computes every query with
`generation._attend_window_one`: a (SPARSE_BAND+1)-page window around
the query plus the anchor (sink) page. This module is the fused form of
that band — one kernel instance per query doing both score einsums, the
band mask, the fp32 softmax, and the PV gather in one pass. The window
SLICING stays on the XLA side (a dynamic-slice per lane, exactly like
the existing backend) — the band *math* is the kernel, so the same
entry point serves the contiguous `generate()` caches and the serving
pool's gathered windows.

The XLA fallback is a per-query `lax.map` of the LITERAL shared math
helper (`_band_math`) the kernel body runs — bitwise parity between
Pallas-interpret and the fallback by construction, and per-query
independence makes results bitwise invariant to batching/chunking
(the same argument `_chunk_attend_window` rests on).
"""

import functools

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.generation import (
    _window_base,
    _window_slice_one,
)
from deepspeed_tpu.kernels.registry import KernelProbeError

try:  # pallas ships with jax here, but the tier must import without it
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _PALLAS_IMPORT_ERROR = None
except Exception as _e:  # pragma: no cover - environment-dependent
    pl = None
    pltpu = None
    _PALLAS_IMPORT_ERROR = _e


def _band_math(q, k_win, v_win, k_sink, v_sink, win_valid, sink_valid,
               dtype):
    """One query's band attention — `_attend_window_one`'s math with the
    position masks precomputed by the caller (the kernel builds them
    from 2D iota, the fallback from arange; the VALUES are identical so
    the shared body keeps the two bitwise-equal).

    q [nh, hd]; k_win/v_win [nh, W, hd]; k_sink/v_sink [nh, pt, hd];
    win_valid [1, W] bool (window key pos <= query pos); sink_valid
    [1, pt] bool (sink key pos < window base). Masked -1e30 scores
    underflow to exact-zero probability under the fp32 softmax."""
    hd = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(hd, dtype))
    s_win = jnp.einsum("nd,nwd->nw", q, k_win) * scale           # [nh, W]
    s_win = jnp.where(win_valid, s_win, jnp.asarray(-1e30, s_win.dtype))
    s_sink = jnp.einsum("nd,nsd->ns", q, k_sink) * scale         # [nh, pt]
    s_sink = jnp.where(sink_valid, s_sink, jnp.asarray(-1e30, s_sink.dtype))
    s = jnp.concatenate([s_sink, s_win], axis=-1).astype(jnp.float32)
    m = jnp.max(s, axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    probs = (e / jnp.sum(e, axis=-1, keepdims=True)).astype(dtype)
    v_all = jnp.concatenate([v_sink, v_win], axis=-2)            # [nh,pt+W,hd]
    return jnp.einsum("ns,nsd->nd", probs, v_all)                # [nh, hd]


# -- Pallas implementation ----------------------------------------------------

def _make_kernel(W, pt, dtype):
    def body(pos_ref, base_ref, q_ref, kw_ref, vw_ref, ks_ref, vs_ref,
             out_ref):
        i = pl.program_id(0)
        pos = pos_ref[i]
        base = base_ref[i]
        # TPU needs >=2D iota; [1, W]/[1, pt] broadcast over heads
        kpos_w = base + jax.lax.broadcasted_iota(jnp.int32, (1, W), 1)
        kpos_s = jax.lax.broadcasted_iota(jnp.int32, (1, pt), 1)
        out_ref[...] = _band_math(
            q_ref[...][0], kw_ref[...][0], vw_ref[...][0],
            ks_ref[...][0], vs_ref[...][0],
            kpos_w <= pos, kpos_s < base, dtype)[None]

    return body


def _band_attend_pallas(q, k_win, v_win, k_sink, v_sink, pos, base, dtype,
                        interpret):
    if pl is None:  # pragma: no cover - environment-dependent
        raise KernelProbeError(
            f"pallas unavailable: {_PALLAS_IMPORT_ERROR}")
    N, nh, hd = q.shape
    W = k_win.shape[2]
    pt = k_sink.shape[2]

    def row(i, pos_, base_):
        return (i, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(N,),
        in_specs=[
            pl.BlockSpec((1, nh, hd), row),
            pl.BlockSpec((1, nh, W, hd), lambda i, p, b: (i, 0, 0, 0)),
            pl.BlockSpec((1, nh, W, hd), lambda i, p, b: (i, 0, 0, 0)),
            pl.BlockSpec((1, nh, pt, hd), lambda i, p, b: (i, 0, 0, 0)),
            pl.BlockSpec((1, nh, pt, hd), lambda i, p, b: (i, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, nh, hd), row),
    )
    return pl.pallas_call(
        _make_kernel(W, pt, dtype),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((N, nh, hd), dtype),
        interpret=interpret,
    )(pos, base, q, k_win, v_win, k_sink, v_sink)


# -- XLA fallback / parity oracle ---------------------------------------------

def _band_attend_xla(q, k_win, v_win, k_sink, v_sink, pos, base, dtype):
    """Per-query `lax.map` of the shared band math at the kernel's exact
    block shapes (NOT vmap: unbatched per-query execution keeps the op
    sequence, and therefore the bits, identical to one grid cell)."""
    W = k_win.shape[2]
    pt = k_sink.shape[2]

    def one(args):
        qi, kw, vw, ks, vs, p, b = args
        win_valid = ((b + jnp.arange(W)) <= p)[None, :]
        sink_valid = (jnp.arange(pt) < b)[None, :]
        return _band_math(qi, kw, vw, ks, vs, win_valid, sink_valid, dtype)

    return jax.lax.map(one, (q, k_win, v_win, k_sink, v_sink, pos, base))


# -- public entry points ------------------------------------------------------

def band_attend(q, k_win, v_win, k_sink, v_sink, pos, base, *, dtype,
                impl="pallas", interpret=True):
    """Banded sink+window attention for N independent queries: q
    [N, nh, hd] against window slices k_win/v_win [N, nh, W, hd]
    (tokens [base, base+W) per query) plus the anchor page k_sink/v_sink
    [N, nh, pt, hd] (tokens [0, pt)). ``pos``/``base`` are [N] int32.
    ``impl``/``interpret`` come from the registry's `resolve()` and must
    be static at every jit call site. Returns [N, nh, hd]."""
    pos = pos.astype(jnp.int32)
    base = base.astype(jnp.int32)
    if impl == "pallas":
        return _band_attend_pallas(q, k_win, v_win, k_sink, v_sink, pos,
                                   base, dtype, bool(interpret))
    return _band_attend_xla(q, k_win, v_win, k_sink, v_sink, pos, base,
                            dtype)


def _band_block(qb, pb, cache_k, cache_v, pt, dtype, impl, interpret):
    """One block of queries through the band: qb [B, c, nh, hd] at
    positions pb [B, c] against per-lane caches [B, nh, S, hd]. Window
    slicing is plain XLA (vmapped dynamic-slice, same as the sparse_xla
    seam); the flattened [B*c] queries then run the band kernel."""
    B, c, nh, hd = qb.shape
    base = _window_base(pb, pt)                                  # [B, c]

    def slices(ck, cv, brow):
        return jax.vmap(
            lambda b: _window_slice_one(ck, cv, b, pt))(brow)

    kw, vw, ks, vs = jax.vmap(slices)(cache_k, cache_v, base)
    flat = lambda x: x.reshape((B * c,) + x.shape[2:])
    ctx = band_attend(flat(qb), flat(kw), flat(vw), flat(ks), flat(vs),
                      pb.reshape(B * c), base.reshape(B * c),
                      dtype=dtype, impl=impl, interpret=interpret)
    return ctx.reshape(B, c, nh, hd)


def chunk_band_attend(q, cache_k, cache_v, qpos, page_tokens, dtype,
                      impl="pallas", interpret=True):
    """Whole-chunk band attention: q [B, C, nh, hd] at positions qpos
    [B, C] over the already-written caches [B, nh, S, hd]. When C is a
    multiple of the page size, queries run pt at a time under a lax.scan
    (bounding the materialized window slices to one block — the
    `_chunk_attend_window` memory argument); otherwise (the k+1
    speculative verify chunk) the whole chunk flattens at once. Each
    query slices its OWN canonical window either way, so the per-query
    math is bit-identical to the decode step's regardless of chunking."""
    B, C, nh, hd = q.shape
    pt = int(page_tokens)
    if C % pt == 0 and C > pt:
        nb = C // pt
        q_b = jnp.moveaxis(q.reshape(B, nb, pt, nh, hd), 1, 0)
        p_b = jnp.moveaxis(qpos.reshape(B, nb, pt), 1, 0)

        def block(_, xs):
            qb, pb = xs
            return None, _band_block(qb, pb, cache_k, cache_v, pt, dtype,
                                     impl, interpret)

        _, ctx_b = jax.lax.scan(block, None, (q_b, p_b))
        return jnp.moveaxis(ctx_b, 0, 1).reshape(B, C, nh, hd)
    return _band_block(q, qpos, cache_k, cache_v, pt, dtype, impl,
                       interpret)


# -- registry probe -----------------------------------------------------------

@functools.lru_cache(maxsize=4)
def _probe_case():
    N, nh, pt, hd = 2, 2, 8, 128
    W = 2 * pt
    q = (jnp.arange(N * nh * hd, dtype=jnp.float32)
         .reshape(N, nh, hd) % 7 - 3) / 11.0
    kw = (jnp.arange(N * nh * W * hd, dtype=jnp.float32)
          .reshape(N, nh, W, hd) % 5 - 2) / 7.0
    vw = (jnp.arange(N * nh * W * hd, dtype=jnp.float32)
          .reshape(N, nh, W, hd) % 9 - 4) / 13.0
    ks = kw[:, :, :pt] * 0.5
    vs = vw[:, :, :pt] * 0.25
    pos = jnp.asarray([19, 26], jnp.int32)
    base = jnp.asarray([8, 16], jnp.int32)
    return q, kw, vw, ks, vs, pos, base


def probe(interpret):
    """Execution probe: a tiny band instance through the Pallas path
    must run AND match the XLA fallback."""
    import numpy as np
    q, kw, vw, ks, vs, pos, base = _probe_case()
    got = band_attend(q, kw, vw, ks, vs, pos, base, dtype=jnp.float32,
                      impl="pallas", interpret=interpret)
    want = band_attend(q, kw, vw, ks, vs, pos, base, dtype=jnp.float32,
                       impl="xla")
    if not np.allclose(np.asarray(got), np.asarray(want),
                       rtol=1e-5, atol=1e-5):
        raise KernelProbeError("sparse_attention probe mismatch vs fallback")
