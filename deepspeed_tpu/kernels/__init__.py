"""Hand-fused Pallas kernel tier behind an op_builder-style registry.

The reference ships its native layer as ``csrc/`` CUDA kernels loaded
through ``op_builder``'s "install native, fall back to compatible"
pattern. This package is that layer's TPU port: each kernel declares a
Pallas implementation AND the repo's existing composed-XLA
implementation as its fallback/parity oracle, and a ``KernelRegistry``
probes availability by *executing* a tiny instance at first use:

- TPU backend        -> native Pallas (real custom calls)
- CPU / CI           -> Pallas interpret mode (same kernel body,
                        executed eagerly — what the parity suite pins
                        bitwise against the XLA fallback)
- probe failure      -> the XLA fallback, plus ONE edge-triggered
                        ``jax/kernel_fallback`` telemetry instant and a
                        ``Kernels/fallbacks_total`` counter — never a
                        crash.

Kernels registered here:

- ``decode_attention`` — fused paged decode attention: one kernel per
  lane doing QK, mask, online softmax and V-gather ACROSS THE LANE'S
  PAGE TABLE (scalar-prefetch indexed DMA), consuming int8 KV pages
  directly so dequantization fuses into the matmul.
- ``sparse_attention`` — the banded sink+window block-sparse attention
  behind the ``sparse_xla`` seam (``_attend_window_one``'s exact math).

Selection is resolved ONCE per call site and threaded into the jitted
programs as a static argument (``kernel_impl``), so a selection change
can never serve a stale compiled program. See ``docs/kernels.md``.
"""

from deepspeed_tpu.kernels.registry import (
    KernelProbeError,
    KernelRegistry,
    get_registry,
    record_call,
    registry_snapshot,
    reset_registry,
)
from deepspeed_tpu.kernels.decode_attention import (
    chunk_attend,
    decode_attend,
)
from deepspeed_tpu.kernels.sparse_attention import (
    band_attend,
    chunk_band_attend,
)

# Public backend names the attention_impl seam dispatches through this
# tier (generation.ATTENTION_IMPLS includes both).
KERNEL_IMPLS = ("pallas", "xla")
KERNEL_BACKENDS = {"pallas_decode": "decode_attention",
                   "pallas_sparse": "sparse_attention"}


def kernel_for_backend(attn_impl):
    """Registry kernel name behind an ``attention_impl`` backend name,
    or None for backends that do not route through the tier."""
    return KERNEL_BACKENDS.get(attn_impl)


def resolve(attn_impl, requested=None, interpret=None):
    """Resolve the (kernel_impl, kernel_interpret) static pair for a
    kernel-tier backend name: ``requested`` forces "pallas"/"xla"
    (None = the probe result), ``interpret`` forces interpret mode
    (None = auto: interpret everywhere but on a real TPU backend).
    A forced-but-unavailable "pallas" degrades to "xla" with the
    edge-triggered fallback instant — never a crash."""
    name = kernel_for_backend(attn_impl)
    if name is None:
        return None, False
    return get_registry().resolve(name, requested=requested,
                                  interpret=interpret)


__all__ = [
    "KERNEL_BACKENDS",
    "KERNEL_IMPLS",
    "KernelProbeError",
    "KernelRegistry",
    "band_attend",
    "chunk_attend",
    "chunk_band_attend",
    "decode_attend",
    "get_registry",
    "kernel_for_backend",
    "record_call",
    "registry_snapshot",
    "reset_registry",
    "resolve",
]
