"""Fused paged decode attention: the tier's flagship Pallas kernel.

One kernel instance per lane walks the lane's page table with
scalar-prefetch indexed block loads — QK, causal mask, online softmax,
and V-gather all happen inside the kernel, so the [C, S] score matrix
is never materialized and the paged gather (`pool[tables]` + moveaxis
in the XLA engine) disappears into the kernel's DMA schedule. int8 KV
pages are consumed DIRECTLY: the page is loaded as int8 and the
per-page scale multiplies the f32 dot-product result, so dequantization
fuses into the matmul instead of materializing a dequantized copy
(JL010's promotion rule maps exactly this taint boundary).

The XLA fallback (`_decode_attend_xla`) is a per-lane `lax.map` over a
`lax.scan` of pages sharing the LITERAL block-update helper
(`_page_update`) with the kernel body at identical shapes — that is
what makes the Pallas-interpret vs fallback parity suite a bitwise
check, not an allclose one. Math mirrors `generation._flash_attend`
(same masked online-softmax recurrence), so it is bitwise invariant to
extra fully-masked pages: serving (pool-sized tables) and `generate()`
(total-length cache) emit identical tokens per backend.
"""

import functools

import jax
import jax.numpy as jnp

from deepspeed_tpu.kernels.registry import KernelProbeError

try:  # pallas ships with jax here, but the tier must import without it
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _PALLAS_IMPORT_ERROR = None
except Exception as _e:  # pragma: no cover - environment-dependent
    pl = None
    pltpu = None
    _PALLAS_IMPORT_ERROR = _e


def _attn_scale(hd, dtype, quant):
    """1/sqrt(hd) in the dtype the QK product runs in: compute dtype for
    fp pages (mirrors `_flash_attend`), f32 for int8 pages (the dot runs
    in f32 and the page scale rides along with it)."""
    if quant:
        return 1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32))
    return 1.0 / jnp.sqrt(jnp.asarray(hd, dtype))


def _page_update(qb, kb, vb, valid, m, l, acc, scale, sk=None, sv=None):
    """ONE page of the online-softmax recurrence — shared literally by
    the Pallas kernel body and the XLA fallback so the two are bitwise
    equal by construction.

    qb [C, nh, hd] (compute dtype); kb/vb [nh, pt, hd] (STORAGE dtype —
    fp or int8); valid [C, pt] bool (key pos <= query pos); carry
    m/l [nh, C] f32, acc [nh, C, hd] f32. ``sk``/``sv`` are the page's
    per-head int8 scales [nh] (None for fp pages). Masked keys
    contribute exp(-1e30 - m) == 0 probability and leave the running
    max untouched — the `_flash_attend` invariance argument."""
    if sk is None:
        # fp pages: QK in compute dtype (bf16 storage casts up for free)
        s = jnp.einsum("cnd,npd->ncp", qb, kb.astype(qb.dtype)) * scale
        s = s.astype(jnp.float32)
    else:
        # int8 pages: dot in f32, page scale FUSED after the matmul —
        # no dequantized page copy ever exists
        s = jnp.einsum("cnd,npd->ncp", qb.astype(jnp.float32),
                       kb.astype(jnp.float32)) * (sk[:, None, None] * scale)
    s = jnp.where(valid[None, :, :], s, jnp.asarray(-1e30, jnp.float32))
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))                  # [nh, C]
    p = jnp.exp(s - m_new[..., None]) * valid[None, :, :]        # masked -> 0
    corr = jnp.exp(m - m_new)
    l = l * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("ncp,npd->ncd", p, vb.astype(jnp.float32))
    if sv is not None:
        pv = pv * sv[:, None, None]
    acc = acc * corr[..., None] + pv
    return m_new, l, acc


def _finalize(l, acc, dtype):
    """Close the recurrence: acc [nh, C, hd], l [nh, C] -> [C, nh, hd]."""
    ctx = (acc / jnp.maximum(l, 1e-30)[..., None]).astype(dtype)
    return jnp.swapaxes(ctx, 0, 1)


# -- Pallas implementation ----------------------------------------------------

def _make_kernel(mp, pt, dtype, quant):
    """Kernel body for grid (B, mp): lane b, page-table slot j. The
    page blocks arrive already gathered — the index_map reads the lane's
    page table out of scalar-prefetch memory, so the DMA engine fetches
    `pages[tab[b, j]]` directly (the fused paged V/K-gather)."""

    def body(tab_ref, qpos_ref, *refs):
        if quant:
            (q_ref, k_ref, v_ref, ks_ref, vs_ref,
             out_ref, m_ref, l_ref, acc_ref) = refs
        else:
            q_ref, k_ref, v_ref, out_ref, m_ref, l_ref, acc_ref = refs
        b = pl.program_id(0)
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _init():
            m_ref[...] = jnp.full(m_ref.shape, -1e30, jnp.float32)
            l_ref[...] = jnp.zeros(l_ref.shape, jnp.float32)
            acc_ref[...] = jnp.zeros(acc_ref.shape, jnp.float32)

        qb = q_ref[...][0]                                   # [C, nh, hd]
        kb = k_ref[...][0]                                   # [nh, pt, hd]
        vb = v_ref[...][0]
        C = qb.shape[0]
        hd = qb.shape[-1]
        # TPU needs >=2D iota: key positions for page-table slot j
        kpos = j * pt + jax.lax.broadcasted_iota(jnp.int32, (C, pt), 1)
        qp = qpos_ref[b]                                     # [C] (SMEM)
        valid = kpos <= qp[:, None]                          # [C, pt]
        sk = ks_ref[...][0] if quant else None               # [nh]
        sv = vs_ref[...][0] if quant else None
        m, l, acc = _page_update(
            qb, kb, vb, valid, m_ref[...], l_ref[...], acc_ref[...],
            _attn_scale(hd, dtype, quant), sk, sv)
        m_ref[...] = m
        l_ref[...] = l
        acc_ref[...] = acc

        @pl.when(j == mp - 1)
        def _emit():
            out_ref[...] = _finalize(l_ref[...], acc_ref[...], dtype)[None]

    return body


def _decode_attend_pallas(q, pages_k, pages_v, tables, qpos, pt, dtype,
                          k_scale, v_scale, interpret):
    if pl is None:  # pragma: no cover - environment-dependent
        raise KernelProbeError(
            f"pallas unavailable: {_PALLAS_IMPORT_ERROR}")
    B, C, nh, hd = q.shape
    mp = tables.shape[1]
    quant = k_scale is not None

    def page_idx(b, j, tab, qp):
        # THE fused paged gather: block j of lane b is physical page
        # tab[b, j], resolved from scalar-prefetch memory at DMA time
        return (tab[b, j], 0, 0, 0)

    in_specs = [
        pl.BlockSpec((1, C, nh, hd), lambda b, j, tab, qp: (b, 0, 0, 0)),
        pl.BlockSpec((1, nh, pt, hd), page_idx),
        pl.BlockSpec((1, nh, pt, hd), page_idx),
    ]
    inputs = [q, pages_k, pages_v]
    if quant:
        in_specs += [
            pl.BlockSpec((1, nh), lambda b, j, tab, qp: (tab[b, j], 0)),
            pl.BlockSpec((1, nh), lambda b, j, tab, qp: (tab[b, j], 0)),
        ]
        inputs += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, mp),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, C, nh, hd),
                               lambda b, j, tab, qp: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((nh, C), jnp.float32),                # running max
            pltpu.VMEM((nh, C), jnp.float32),                # denominator
            pltpu.VMEM((nh, C, hd), jnp.float32),            # numerator
        ],
    )
    return pl.pallas_call(
        _make_kernel(mp, pt, dtype, quant),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, C, nh, hd), dtype),
        interpret=interpret,
    )(tables, qpos, *inputs)


# -- XLA fallback / parity oracle ---------------------------------------------

def _decode_attend_xla(q, pages_k, pages_v, tables, qpos, pt, dtype,
                       k_scale, v_scale):
    """Composed-XLA twin of the kernel: `lax.map` over lanes (NOT vmap —
    per-lane execution at the kernel's exact block shapes keeps the op
    sequence, and therefore the bits, identical to one grid row) of a
    `lax.scan` over the lane's page table."""
    B, C, nh, hd = q.shape
    mp = tables.shape[1]
    quant = k_scale is not None
    scale = _attn_scale(hd, dtype, quant)

    def lane(args):
        qb, tab, qp = args                       # [C,nh,hd], [mp], [C]
        m0 = jnp.full((nh, C), -1e30, jnp.float32)
        l0 = jnp.zeros((nh, C), jnp.float32)
        a0 = jnp.zeros((nh, C, hd), jnp.float32)

        def page(carry, xs):
            m, l, acc = carry
            pid, off = xs
            valid = (off + jnp.arange(pt))[None, :] <= qp[:, None]
            sk = k_scale[pid] if quant else None
            sv = v_scale[pid] if quant else None
            m, l, acc = _page_update(qb, pages_k[pid], pages_v[pid],
                                     valid, m, l, acc, scale, sk, sv)
            return (m, l, acc), None

        (_, l, acc), _ = jax.lax.scan(
            page, (m0, l0, a0), (tab, jnp.arange(mp, dtype=jnp.int32) * pt))
        return _finalize(l, acc, dtype)

    return jax.lax.map(lane, (q, tables, qpos))


# -- public entry points ------------------------------------------------------

def decode_attend(q, pages_k, pages_v, tables, qpos, *, page_tokens, dtype,
                  impl="pallas", interpret=True, k_scale=None, v_scale=None):
    """Paged fused attention: q [B, C, nh, hd] at positions qpos [B, C]
    over the page pool pages_k/v [P, nh, pt, hd] through per-lane page
    tables [B, mp]. ``impl``/``interpret`` come from the registry's
    `resolve()` and MUST be static at every jit call site (they pick the
    program). int8 pools pass ``k_scale``/``v_scale`` ([P, nh, 1, 1] or
    [P, nh] f32, per-page per-head) and the dequant fuses into the
    matmul; bf16 pools just cast at load. Returns [B, C, nh, hd]."""
    pt = int(page_tokens)
    assert pages_k.shape[2] == pt, (
        f"pool page size {pages_k.shape[2]} != page_tokens {pt}")
    tables = tables.astype(jnp.int32)
    qpos = qpos.astype(jnp.int32)
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be passed together")
    if k_scale is not None:
        P, nh = pages_k.shape[0], pages_k.shape[1]
        k_scale = k_scale.astype(jnp.float32).reshape(P, nh)
        v_scale = v_scale.astype(jnp.float32).reshape(P, nh)
    if impl == "pallas":
        return _decode_attend_pallas(q, pages_k, pages_v, tables, qpos, pt,
                                     dtype, k_scale, v_scale, bool(interpret))
    return _decode_attend_xla(q, pages_k, pages_v, tables, qpos, pt, dtype,
                              k_scale, v_scale)


def chunk_attend(q, cache_k, cache_v, qpos, page_tokens, dtype,
                 impl="pallas", interpret=True):
    """Contiguous-cache adapter for `generate()`-side callers: caches
    [B, nh, S, hd] (S a multiple of page_tokens) are viewed as per-lane
    page runs with an identity page table, then routed through
    `decode_attend` — so the contiguous path and the serving pool path
    run the SAME kernel and the continuous-vs-generate() oracle holds
    bitwise per backend by construction."""
    B, C, nh, hd = q.shape
    S = cache_k.shape[2]
    pt = int(page_tokens)
    assert S % pt == 0, f"cache length {S} is not a multiple of page {pt}"
    mp = S // pt

    def paged(cache):
        blocks = cache.reshape(B, nh, mp, pt, hd)
        return jnp.moveaxis(blocks, 2, 1).reshape(B * mp, nh, pt, hd)

    tables = jnp.arange(B * mp, dtype=jnp.int32).reshape(B, mp)
    return decode_attend(q, paged(cache_k), paged(cache_v), tables, qpos,
                         page_tokens=pt, dtype=dtype, impl=impl,
                         interpret=interpret)


# -- registry probe -----------------------------------------------------------

@functools.lru_cache(maxsize=4)
def _probe_case():
    B, C, nh, pt, hd, mp, P = 2, 2, 2, 8, 128, 2, 5
    q = (jnp.arange(B * C * nh * hd, dtype=jnp.float32)
         .reshape(B, C, nh, hd) % 7 - 3) / 11.0
    pk = (jnp.arange(P * nh * pt * hd, dtype=jnp.float32)
          .reshape(P, nh, pt, hd) % 5 - 2) / 7.0
    pv = (jnp.arange(P * nh * pt * hd, dtype=jnp.float32)
          .reshape(P, nh, pt, hd) % 9 - 4) / 13.0
    tables = jnp.asarray([[1, 3], [4, 2]], jnp.int32)
    qpos = jnp.asarray([[5, 6], [11, 12]], jnp.int32)
    return q, pk, pv, tables, qpos, pt


def probe(interpret):
    """Execution probe: a tiny paged instance through the Pallas path
    must run AND match the XLA fallback. Any exception (missing pallas,
    lowering failure, wrong numerics) marks the kernel unavailable."""
    import numpy as np
    q, pk, pv, tables, qpos, pt = _probe_case()
    got = decode_attend(q, pk, pv, tables, qpos, page_tokens=pt,
                        dtype=jnp.float32, impl="pallas",
                        interpret=interpret)
    want = decode_attend(q, pk, pv, tables, qpos, page_tokens=pt,
                         dtype=jnp.float32, impl="xla")
    if not np.allclose(np.asarray(got), np.asarray(want),
                       rtol=1e-5, atol=1e-5):
        raise KernelProbeError("decode_attention probe mismatch vs fallback")
