"""op_builder-style availability registry for the Pallas kernel tier.

Mirrors ``ops/op_builder.py``'s "install native, fall back to
compatible" contract, upgraded from *import* probing to *execution*
probing: a kernel is available only if a tiny instance of its Pallas
implementation actually runs on this backend (native on TPU, interpret
mode elsewhere) and matches its XLA fallback. Anything else — missing
pallas, an unsupported primitive, a lowering bug — degrades to the
composed-XLA fallback with ONE edge-triggered ``jax/kernel_fallback``
telemetry instant per kernel, never a crash.

The resolved selection is handed to callers as a plain string
("pallas" / "xla") that they thread into their jitted programs as a
STATIC argument — selection is part of every jit cache key, so a
changed selection can never serve a stale compiled program.
"""

import threading

import numpy as np

from deepspeed_tpu import telemetry

KERNEL_IMPL_CHOICES = ("pallas", "xla")


class KernelProbeError(RuntimeError):
    """A kernel's execution probe failed (carried in the registry's
    snapshot as the fallback reason; never raised out of resolve())."""


class _KernelSpec:
    __slots__ = ("name", "probe_fn", "doc")

    def __init__(self, name, probe_fn, doc=""):
        self.name = name
        self.probe_fn = probe_fn
        self.doc = doc


class KernelRegistry:
    """Availability + selection + telemetry for the kernel tier.

    ``probe(name)`` runs (once, cached) the kernel's tiny execution
    probe; ``resolve(name)`` turns a config request (None = probe
    result) into the ("pallas"|"xla", interpret) static pair;
    ``record_call(name, impl)`` feeds the ``Kernels/<name>/calls``
    counters the serving ``/snapshot`` and SLO rules read."""

    def __init__(self):
        self._specs = {}
        self._probe = {}           # name -> (ok, error-string-or-None)
        self._fallback_emitted = set()
        self._calls = {}           # name -> {"pallas": n, "xla": n}
        self._lock = threading.Lock()

    # -- registration ---------------------------------------------------
    def register(self, name, probe_fn, doc=""):
        """Register a kernel: ``probe_fn(interpret)`` must execute a tiny
        Pallas instance and raise on any failure (its return value is
        ignored — raising IS the unavailability signal)."""
        with self._lock:
            self._specs[name] = _KernelSpec(name, probe_fn, doc)
            self._probe.pop(name, None)
        return self

    def names(self):
        return tuple(sorted(self._specs))

    # -- probing --------------------------------------------------------
    @staticmethod
    def interpret_default():
        """Interpret mode everywhere but a real TPU backend: the same
        kernel body runs under CPU CI (eager, slow, bit-checkable) and
        compiles natively on TPU."""
        import jax
        return jax.default_backend() != "tpu"

    def probe(self, name, interpret=None):
        """(ok, error) for ``name``, cached after the first execution.
        Unknown kernels are simply unavailable (not an error path: the
        resolve contract is fallback, never crash)."""
        with self._lock:
            if name in self._probe:
                return self._probe[name]
        spec = self._specs.get(name)
        if spec is None:
            result = (False, f"unknown kernel {name!r}")
        else:
            try:
                spec.probe_fn(self.interpret_default()
                              if interpret is None else bool(interpret))
                result = (True, None)
            except Exception as e:  # noqa: BLE001 — any failure = fallback
                result = (False, f"{type(e).__name__}: {e}")
        with self._lock:
            self._probe[name] = result
        return result

    def available(self, name):
        return self.probe(name)[0]

    # -- selection ------------------------------------------------------
    def resolve(self, name, requested=None, interpret=None):
        """The (impl, interpret) static pair a call site should thread
        into its jitted programs. ``requested`` is the config's
        ``attention_kernel`` value (None = default to the probe result);
        ``interpret`` the config's ``kernel_interpret`` (None = auto).
        Requesting "pallas" when the probe failed degrades to "xla"
        and emits the edge-triggered fallback instant."""
        if requested is not None and requested not in KERNEL_IMPL_CHOICES:
            raise ValueError(
                f"kernel impl must be one of {KERNEL_IMPL_CHOICES} or None "
                f"(= probe result), got {requested!r}")
        interp = (self.interpret_default() if interpret is None
                  else bool(interpret))
        if requested == "xla":
            return "xla", interp
        ok, err = self.probe(name)
        if ok:
            return "pallas", interp
        self._emit_fallback(name, err)
        return "xla", interp

    def _emit_fallback(self, name, error):
        """ONE instant per failed kernel (edge-triggered), plus a
        registry counter so an SLO rule like
        {"metric": "Kernels/fallbacks_total", "max": 0} can alert on
        any fleet member silently losing its native kernels."""
        with self._lock:
            if name in self._fallback_emitted:
                return
            self._fallback_emitted.add(name)
        telemetry.instant("jax/kernel_fallback", cat="lifecycle",
                          args={"kernel": name, "error": error})
        telemetry.get_registry().counter(
            "Kernels/fallbacks_total",
            help="kernels degraded from Pallas to the XLA fallback").inc()

    # -- telemetry ------------------------------------------------------
    def record_call(self, name, impl="pallas"):
        """Count one dispatch of ``name`` (host-side, at the call sites
        that invoke the kernel-bearing jitted programs)."""
        with self._lock:
            per = self._calls.setdefault(name, {"pallas": 0, "xla": 0})
            per[impl] = per.get(impl, 0) + 1
        telemetry.get_registry().counter(
            f"Kernels/{name}/calls",
            help="kernel-tier program dispatches").inc()

    def snapshot(self):
        """The serving ``/snapshot``'s ``kernels`` section: selection,
        availability, probe error, and call counts per kernel."""
        out = {}
        for name in self.names():
            probed = self._probe.get(name)
            ok, err = probed if probed is not None else (None, None)
            with self._lock:
                calls = dict(self._calls.get(name,
                                             {"pallas": 0, "xla": 0}))
            out[name] = {
                "available": ok,
                "probed": probed is not None,
                "selected": (None if ok is None
                             else ("pallas" if ok else "xla")),
                "interpret": self.interpret_default(),
                "probe_error": err,
                "calls": calls,
            }
        return out

    def export_gauges(self, registry=None):
        """Selected-backend gauges (1.0 = Pallas selected, 0.0 = XLA
        fallback) per kernel, as pull gauges on the shared metrics
        registry — rendered at /metrics scrape next to the counters."""
        reg = registry or telemetry.get_registry()

        def pull():
            vals = {}
            for name, snap in self.snapshot().items():
                sel = snap["selected"]
                if sel is not None:
                    vals[f"{name}/selected_pallas"] = float(sel == "pallas")
                    vals[f"{name}/interpret"] = float(bool(snap["interpret"]))
            return vals

        reg.gauge_fn("Kernels", pull,
                     help="kernel-tier backend selection (1 = Pallas)")

    # -- test hooks -----------------------------------------------------
    def force_probe_result(self, name, ok, error=None):
        """Test hook: pin a probe outcome (e.g. simulate a broken Pallas
        install) without monkeypatching jax internals."""
        with self._lock:
            self._probe[name] = (bool(ok),
                                 None if ok else (error or "forced"))
            if ok:
                self._fallback_emitted.discard(name)

    def reset(self):
        with self._lock:
            self._probe.clear()
            self._fallback_emitted.clear()
            self._calls.clear()


_registry = None
_registry_lock = threading.Lock()


def get_registry():
    """The process-global kernel registry, with the built-in kernels
    registered on first touch."""
    global _registry
    with _registry_lock:
        if _registry is None:
            _registry = KernelRegistry()
            _register_builtin(_registry)
        return _registry


def reset_registry():
    """Drop cached probe results/counters (tests)."""
    global _registry
    with _registry_lock:
        if _registry is not None:
            _registry.reset()


def record_call(name, impl="pallas"):
    get_registry().record_call(name, impl)


def registry_snapshot():
    return get_registry().snapshot()


def _register_builtin(reg):
    # imported lazily: registry.py must stay importable without pallas
    from deepspeed_tpu.kernels import decode_attention, sparse_attention

    reg.register("decode_attention", decode_attention.probe,
                 doc="fused paged decode attention (QK, mask, online "
                     "softmax, V-gather across the page table; int8 "
                     "pages consumed directly)")
    reg.register("sparse_attention", sparse_attention.probe,
                 doc="banded sink+window block-sparse attention "
                     "(the sparse_xla seam's band)")


def _allclose(a, b, rtol=1e-5, atol=1e-5):
    """Probe-side parity check (numpy — probes run outside any trace)."""
    return np.allclose(np.asarray(a), np.asarray(b), rtol=rtol, atol=atol)
