"""Measured config search (the TPU-native DeepSpeed autotuner).

Later DeepSpeed's autotuner (absent from the v0.3.10 reference) launches a
separate experiment JOB per candidate config and harvests metrics files.
On TPU every experiment is a jit compile + a few timed steps of one XLA
program, so the whole search runs in-process: compile each candidate,
time it, rank by throughput, return the winner. Infeasible candidates
(HBM OOM at compile or first execution) are recorded, not fatal — the
same contract as the bench harness's micro-batch OOM ladder.

Two entry points:

- ``autotune(build_fn, candidates, ...)`` — generic: ``build_fn(overrides)
  -> (step_callable, samples_per_step)``. The tuner times
  ``step_callable`` (blocking on its result) and maximizes
  samples/sec.
- ``autotune_engine(model, model_parameters, base_config, batches, ...)``
  — convenience wrapper that deep-merges each candidate's overrides into
  ``base_config``, builds a fresh engine via ``deepspeed_tpu.initialize``,
  and returns ``(best_config, report)``.
"""

import time
from dataclasses import dataclass, field

from deepspeed_tpu.utils.logging import log_dist

# error-text markers of an HBM allocation failure (same set bench.py keys
# its OOM ladder off); anything else is a real error and still recorded,
# so one broken candidate cannot kill a long search
_OOM_MARKERS = (
    "RESOURCE_EXHAUSTED", "Out of memory", "out of memory", "AllocateBuffer",
)


@dataclass
class Candidate:
    """One point in the search space: config overrides + a display label."""

    overrides: dict
    label: str = ""

    def __post_init__(self):
        if not self.label:
            self.label = ",".join(
                f"{k}={v}" for k, v in sorted(_flatten(self.overrides).items()))


def _flatten(d, prefix=""):
    out = {}
    for k, v in d.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "."))
        else:
            out[key] = v
    return out


def deep_merge(base, overrides):
    """Recursive dict merge: ``overrides`` wins, sub-dicts merge."""
    out = dict(base)
    for k, v in overrides.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def default_candidates(micro_batch, remat=True):
    """The ladder that matters on TPU: micro-batch (MXU utilization vs HBM)
    x activation remat (HBM vs recompute FLOPs). Largest-batch/no-remat
    first — the fastest config whenever it fits."""
    cands = []
    rungs = sorted({micro_batch * 2, micro_batch, max(1, micro_batch // 2)},
                   reverse=True)  # dedup: mb=1 collapses two rungs
    for mb in rungs:
        for r in ((False, True) if remat else (False,)):
            cands.append(Candidate({
                "train_micro_batch_size_per_gpu": mb,
                "activation_checkpointing": {"enabled": r},
            }))
    return cands


def _block_on(x):
    import jax

    jax.block_until_ready(x)
    # a data fetch is the only thing that truly waits on some remote
    # backends (see bench.py _timed_chain); a scalar fetch is cheap
    leaves = jax.tree_util.tree_leaves(x)
    if leaves and getattr(leaves[0], "size", 2) == 1:
        float(jax.device_get(leaves[0]))


def autotune(build_fn, candidates, steps=3, warmup=1, verbose=True):
    """Time every candidate; return ``(best_candidate, report)``.

    ``build_fn(overrides) -> (step_callable, samples_per_step)``; the
    callable runs ONE training step and returns a value to block on.
    ``report`` is a list of dicts (label, overrides, ok, compile_s,
    step_ms, samples_per_sec | error, oom) in input order; ``best`` is
    the feasible candidate with the highest samples/sec (None if all
    candidates failed).
    """
    report = []
    step = None
    for cand in candidates:
        # free the previous candidate's engine (params, optimizer state,
        # batches hang off the step closure) BEFORE the next build — two
        # co-resident engines would falsely OOM configs that fit alone
        step = None  # noqa: F841
        entry = {"label": cand.label, "overrides": cand.overrides}
        try:
            t0 = time.perf_counter()
            step, samples = build_fn(cand.overrides)
            _block_on(step())  # compile + first execution
            entry["compile_s"] = round(time.perf_counter() - t0, 2)
            out = None
            for _ in range(max(0, warmup - 1)):
                out = step()
            _block_on(out)  # warmup must not leak into the timed window
            t0 = time.perf_counter()
            for _ in range(steps):
                out = step()
            _block_on(out)
            dt = (time.perf_counter() - t0) / steps
            entry.update(ok=True, step_ms=round(dt * 1000.0, 2),
                         samples_per_sec=round(samples / dt, 2))
        except KeyboardInterrupt:
            raise
        except Exception as e:  # noqa: BLE001 — a candidate must not kill the search
            msg = str(e)
            entry.update(ok=False, error=msg[-500:],
                         oom=any(m in msg for m in _OOM_MARKERS))
        if verbose:
            log_dist(f"autotune {cand.label}: "
                     + (f"{entry['samples_per_sec']} samples/sec "
                        f"({entry['step_ms']} ms/step)" if entry.get("ok")
                        else ("OOM" if entry.get("oom") else "FAILED")),
                     ranks=[0])
        report.append(entry)
    best = None
    for cand, entry in zip(candidates, report):
        if entry.get("ok") and (
                best is None or entry["samples_per_sec"] > best[1]["samples_per_sec"]):
            best = (cand, entry)
    return (best[0] if best else None), report


def autotune_engine(model, model_parameters, base_config, data_fn,
                    candidates=None, steps=3, warmup=1, verbose=True):
    """Search engine configs; returns ``(best_merged_config, report)``.

    ``data_fn(global_batch_size) -> list of argument tuples`` for
    ``engine(*args)`` — a factory, because candidates that move the micro
    batch change the global batch each step consumes. ``candidates``
    defaults to the micro-batch x remat ladder around the base config's
    micro batch.
    """
    import itertools

    import jax
    import jax.numpy as jnp

    import deepspeed_tpu

    if candidates is None:
        base_mb = base_config.get("train_micro_batch_size_per_gpu", 1)
        candidates = default_candidates(base_mb)

    # engines donate their param buffers into the jitted step — every
    # candidate needs a fresh device copy from one host snapshot (which
    # also guarantees identical init across candidates)
    host_params = jax.device_get(model_parameters)

    def build(overrides):
        cfg = deep_merge(base_config, overrides)
        # keep the batch triple consistent when the search moves the
        # micro batch: world size and gas stay, train_batch follows
        cfg.pop("train_batch_size", None)
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model,
            model_parameters=jax.tree_util.tree_map(jnp.asarray, host_params),
            config_params=cfg)
        it = itertools.cycle(data_fn(engine.train_batch_size()))

        def step():
            args = next(it)
            loss = engine(*args)
            engine.backward(loss)
            engine.step()
            return loss

        return step, engine.train_batch_size()

    best, report = autotune(build, candidates, steps=steps, warmup=warmup,
                            verbose=verbose)
    if best is None:
        return None, report
    merged = deep_merge(base_config, best.overrides)
    merged.pop("train_batch_size", None)
    return merged, report
