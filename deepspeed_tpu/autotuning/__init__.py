"""Autotuning: measured search over engine configs (beyond the v0.3.10
reference — later DeepSpeed made ``deepspeed --autotuning`` a headline
feature, spawning experiment jobs per config; on TPU the whole experiment
is one jit-compile + a few steps in-process, so the tuner IS a loop)."""

from deepspeed_tpu.autotuning.tuner import (
    Candidate,
    autotune,
    autotune_engine,
    default_candidates,
)

__all__ = ["Candidate", "autotune", "autotune_engine", "default_candidates"]
