"""Alias package (later DeepSpeed's ``deepspeed.zero`` namespace — the
v0.3.10 reference has no such alias; kept for forward import parity):
``deepspeed_tpu.zero.zero3_sharded_init`` is the ``zero.Init``-shaped
entry point, next to the memory estimators."""

from deepspeed_tpu.runtime.zero import (  # noqa: F401
    ZeroPytreeOptimizer,
    ZeroShardedOptimizer,
    estimate_zero2_model_states_mem_needs,
    estimate_zero_model_states_mem_needs,
    mem_needs_report,
    zero3_param_shardings,
    zero3_sharded_init,
)
