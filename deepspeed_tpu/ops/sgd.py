"""Plain SGD with momentum — the 'torch.optim fallback' slot in the engine's
optimizer matrix (reference engine.py:585-617 falls back to torch.optim)."""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class SGDState(NamedTuple):
    step: jnp.ndarray
    momentum_buf: object


class SGD:
    def __init__(self, lr=1e-3, momentum=0.0, weight_decay=0.0, nesterov=False, **kwargs):
        if kwargs.get("no_decay_names"):
            raise ValueError(
                "no_decay_names is only supported by Adam/AdamW (FusedAdam)")
        self.lr = lr
        self.momentum = momentum
        self.weight_decay = weight_decay
        self.nesterov = nesterov

    def init(self, params):
        zeros = lambda p: jnp.zeros_like(p, dtype=jnp.float32)
        return SGDState(step=jnp.asarray(0, jnp.int32), momentum_buf=jax.tree_util.tree_map(zeros, params))

    def update(self, grads, state, params, lr=None):
        lr = self.lr if lr is None else lr

        def upd(g, buf, p):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if self.weight_decay:
                g = g + self.weight_decay * p32
            buf_new = self.momentum * buf + g if self.momentum else g
            step_dir = g + self.momentum * buf_new if self.nesterov else buf_new
            return (p32 - lr * step_dir).astype(p.dtype), buf_new

        from deepspeed_tpu.ops.utils_op import tree_map_multi

        new_params, new_buf = tree_map_multi(upd, 2, grads, state.momentum_buf, params)
        return new_params, SGDState(step=state.step + 1, momentum_buf=new_buf)

    @property
    def name(self):
        return "sgd"
