"""Op packages (surface parity: reference ``deepspeed/ops/__init__.py``)."""

from deepspeed_tpu.ops import adam, lamb, sparse_attention, transformer
from deepspeed_tpu.ops.transformer import (
    DeepSpeedTransformerConfig,
    DeepSpeedTransformerLayer,
)

# reference: `from .module_inject import replace_module`
from deepspeed_tpu.module_inject import replace_module

# reference: compatible_ops matrix from git_version_info; here the same
# question ("which native ops are actually usable?") is answered live by the
# op builder (built .so vs numpy fallback).
from deepspeed_tpu.ops.op_builder import compatible_ops as __compatible_ops__
