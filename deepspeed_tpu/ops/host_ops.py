"""ctypes bindings for the native host ops (csrc/host_ops.cpp): parallel
flatten/unflatten, block-sparse layout->LUT segmentation, host LAMB.

Each op has a numpy fallback so the library is optional (reference op_builder
semantics: prefer the compiled op, degrade gracefully — builder.py:170-180).
"""

import ctypes
import os

import numpy as np

_LIB = None


def _load():
    global _LIB
    if _LIB is not None:
        return _LIB
    path = os.path.join(os.path.dirname(__file__), "lib", "libdstpu_cpu.so")
    if not os.path.exists(path):
        _LIB = False
        return False
    try:
        lib = ctypes.CDLL(path)
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        fp = ctypes.POINTER(ctypes.c_float)
        fpp = ctypes.POINTER(fp)
        lib.ds_flatten.argtypes = [fpp, i64p, ctypes.c_int64, fp]
        lib.ds_unflatten.argtypes = [fp, i64p, ctypes.c_int64, fpp]
        lib.ds_layout_to_lut.argtypes = [i64p, ctypes.c_int64, ctypes.c_int64,
                                         ctypes.c_int64, ctypes.c_int64, i32p, i32p]
        lib.ds_lamb_step.argtypes = [fp, fp, fp, fp, ctypes.c_int64] + [ctypes.c_float] * 7 + [ctypes.c_int]
        _LIB = lib
    except OSError:
        _LIB = False
    return _LIB


def available():
    return bool(_load())


def flatten_host(arrays):
    """numpy float32 arrays -> one flat float32 vector (native when built)."""
    arrays = [np.ascontiguousarray(a, np.float32) for a in arrays]
    sizes = np.asarray([a.size for a in arrays], np.int64)
    total = int(sizes.sum())
    out = np.empty(total, np.float32)
    lib = _load()
    if lib:
        fp = ctypes.POINTER(ctypes.c_float)
        srcs = (fp * len(arrays))(*[a.ctypes.data_as(fp) for a in arrays])
        lib.ds_flatten(srcs, sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                       len(arrays), out.ctypes.data_as(fp))
    else:
        off = 0
        for a in arrays:
            out[off:off + a.size] = a.ravel()
            off += a.size
    return out


def unflatten_host(flat, shapes):
    """Flat float32 vector -> list of numpy arrays with the given shapes."""
    flat = np.ascontiguousarray(flat, np.float32)
    sizes = np.asarray([int(np.prod(s)) for s in shapes], np.int64)
    outs = [np.empty(s, np.float32) for s in shapes]
    lib = _load()
    if lib:
        fp = ctypes.POINTER(ctypes.c_float)
        dsts = (fp * len(outs))(*[o.ctypes.data_as(fp) for o in outs])
        lib.ds_unflatten(flat.ctypes.data_as(fp),
                         sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                         len(outs), dsts)
    else:
        off = 0
        for o, n in zip(outs, sizes):
            o.ravel()[:] = flat[off:off + n]
            off += n
    return outs


def layout_to_lut_host(layout):
    """[H, Qb, Kb] 0/1 int64 layout -> (lut [H, Qb, maxn] int32, counts).
    Native OpenMP path (reference csrc/sparse_attention/utils.cpp) with a
    numpy fallback."""
    layout = np.ascontiguousarray(layout, np.int64)
    H, Qb, Kb = layout.shape
    counts = layout.sum(-1).astype(np.int32)
    maxn = max(int(counts.max()), 1)
    lib = _load()
    lut = np.zeros((H, Qb, maxn), np.int32)
    counts_out = np.zeros((H, Qb), np.int32)
    if lib:
        lib.ds_layout_to_lut(
            layout.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), H, Qb, Kb, maxn,
            lut.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            counts_out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
    else:
        for h in range(H):
            for q in range(Qb):
                idx = np.nonzero(layout[h, q])[0]
                lut[h, q, : len(idx)] = idx
                counts_out[h, q] = len(idx)
    return lut, counts_out


def lamb_step_host(param, grad, exp_avg, exp_avg_sq, lr, beta1=0.9, beta2=0.999,
                   eps=1e-6, weight_decay=0.0, max_coeff=10.0, min_coeff=0.01, step=1):
    """In-place host LAMB over one flat fp32 tensor (trust-ratio clamped)."""
    lib = _load()
    if lib:
        fp = ctypes.POINTER(ctypes.c_float)
        lib.ds_lamb_step(
            param.ctypes.data_as(fp), grad.ctypes.data_as(fp),
            exp_avg.ctypes.data_as(fp), exp_avg_sq.ctypes.data_as(fp),
            param.size, lr, beta1, beta2, eps, weight_decay, max_coeff, min_coeff, step,
        )
        return param
    m = beta1 * exp_avg + (1 - beta1) * grad
    v = beta2 * exp_avg_sq + (1 - beta2) * grad * grad
    exp_avg[:] = m
    exp_avg_sq[:] = v
    u = m / (np.sqrt(v) + eps) + weight_decay * param
    w_norm = np.linalg.norm(param)
    u_norm = np.linalg.norm(u)
    trust = 1.0
    if w_norm > 0 and u_norm > 0:
        trust = float(np.clip(w_norm / u_norm, min_coeff, max_coeff))
    param -= lr * trust * u
    return param
