"""Block-sparsity layout generators.

Capability parity with the reference's ``deepspeed/ops/sparse_attention/
sparsity_config.py`` (Dense / Fixed / Variable / BigBird / BSLongformer
layouts). A layout is an int array ``[num_heads, num_blocks, num_blocks]``
where 1 marks a block of the attention matrix that is computed. The generators
are pure numpy (layouts are host-side metadata); the TPU kernels consume them
as gather indices / LUTs.

Implementations are written from the pattern definitions (local windows +
global tokens + random blocks, sliding windows a la Longformer/BigBird), not
transcribed.
"""

import random

import numpy as np


class SparsityConfig:
    """Base: carries head count and block size (reference sparsity_config.py:9)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len):
        if seq_len % self.block != 0:
            raise ValueError(
                f"Sequence Length, {seq_len}, must be divisible by the block size {self.block}!"
            )
        num_blocks = seq_len // self.block
        return np.zeros((self.num_heads, num_blocks, num_blocks), dtype=np.int64)

    def check_and_propagate_first_head_layout(self, layout):
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len):
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """All blocks present — dense attention expressed in the same format
    (reference sparsity_config.py:63)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False):
        super().__init__(num_heads, block, different_layout_per_head)

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Fixed pattern (reference sparsity_config.py:94): blocks attend within
    their local window of ``num_local_blocks``; the last ``num_global_blocks``
    of each window are global (attended by all later blocks; with
    ``horizontal_global_attention`` they also attend to everything).
    """

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_local_blocks=4, num_global_blocks=1, attention="bidirectional",
                 horizontal_global_attention=False, num_different_global_patterns=1):
        super().__init__(num_heads, block, different_layout_per_head)
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError(
                f"Number of local blocks, {num_local_blocks}, must be divisible by "
                f"number of global blocks, {num_global_blocks}!"
            )
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError('only "uni/bi-directional" attentions are supported for now!')
        self.attention = attention
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError('only "bi-directional" attentions can support horizontal global attention!')
        self.horizontal_global_attention = horizontal_global_attention
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError(
                "Number of different layouts cannot be more than one when you have set a single layout for all heads!"
            )
        if num_different_global_patterns > (num_local_blocks // num_global_blocks):
            raise ValueError(
                f"Number of layout versions (num_different_global_patterns), {num_different_global_patterns}, "
                f"cannot be larger than number of local window blocks divided by number of global blocks, "
                f"{num_local_blocks // num_global_blocks}!"
            )
        self.num_different_global_patterns = num_different_global_patterns

    def _set_local(self, layout, h):
        num_blocks = layout.shape[1]
        for start in range(0, num_blocks, self.num_local_blocks):
            end = min(start + self.num_local_blocks, num_blocks)
            for r in range(start, end):
                upto = (r + 1) if self.attention == "unidirectional" else end
                layout[h, r, start:upto] = 1
        return layout

    def _global_band(self, h):
        """Which blocks inside each local window are global, for this head's
        pattern version."""
        version = (h // max(1, self.num_heads // self.num_different_global_patterns)
                   ) % self.num_different_global_patterns
        # version v uses the v-th group (from the end) of global blocks
        first = self.num_local_blocks - (version + 1) * self.num_global_blocks
        return first

    def _set_global(self, layout, h):
        num_blocks = layout.shape[1]
        first_g = self._global_band(h)
        for start in range(0, num_blocks, self.num_local_blocks):
            g_lo = start + first_g
            g_hi = min(g_lo + self.num_global_blocks, num_blocks)
            if g_lo >= num_blocks:
                continue
            # vertical: later blocks (or all, if bidirectional) attend to globals
            attend_from = 0 if self.attention == "bidirectional" else g_lo
            if self.attention == "unidirectional":
                layout[h, g_lo:, g_lo:g_hi] = 1
            else:
                layout[h, :, g_lo:g_hi] = 1
            # horizontal: globals attend to everything
            if self.horizontal_global_attention:
                layout[h, g_lo:g_hi, :] = 1
        if self.attention == "unidirectional":
            # keep causality
            tri = np.tril(np.ones((num_blocks, num_blocks), dtype=layout.dtype))
            layout[h] *= tri
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self._set_local(layout, h)
            layout = self._set_global(layout, h)
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """Variable pattern (reference sparsity_config.py:243): user-listed local
    window sizes (last size repeats), explicit global block indices (optionally
    ranges), plus random blocks."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=0, local_window_blocks=None,
                 global_block_indices=None, global_block_end_indices=None,
                 attention="bidirectional", horizontal_global_attention=False):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices if global_block_indices is not None else [0]
        if global_block_end_indices is not None:
            if len(self.global_block_indices) != len(global_block_end_indices):
                raise ValueError(
                    f"Global block start indices length, {len(self.global_block_indices)}, must be same as "
                    f"global block end indices length, {len(global_block_end_indices)}!"
                )
            for _, (start_idx, end_idx) in enumerate(zip(self.global_block_indices, global_block_end_indices)):
                if start_idx >= end_idx:
                    raise ValueError(
                        f"Global block start index, {start_idx}, must be smaller than global block end index, {end_idx}!"
                    )
        self.global_block_end_indices = global_block_end_indices
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError('only "uni/bi-directional" attentions are supported for now!')
        self.attention = attention
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError('only "bi-directional" attentions can support horizontal global attention!')
        self.horizontal_global_attention = horizontal_global_attention

    def _set_random(self, layout, h, num_blocks):
        if self.num_random_blocks == 0:
            return layout
        for r in range(num_blocks):
            rand_cols = random.sample(range(num_blocks), min(self.num_random_blocks, num_blocks))
            for c in rand_cols:
                if self.attention == "bidirectional" or c <= r:
                    layout[h, r, c] = 1
        return layout

    def _set_local(self, layout, h, num_blocks):
        windows = list(self.local_window_blocks)
        start = 0
        w_i = 0
        while start < num_blocks:
            w = windows[min(w_i, len(windows) - 1)]
            end = min(start + w, num_blocks)
            for r in range(start, end):
                upto = (r + 1) if self.attention == "unidirectional" else end
                layout[h, r, start:upto] = 1
            start = end
            w_i += 1
        return layout

    def _set_global(self, layout, h, num_blocks):
        if self.global_block_end_indices is None:
            targets = [(i, i + 1) for i in self.global_block_indices]
        else:
            targets = list(zip(self.global_block_indices, self.global_block_end_indices))
        for lo, hi in targets:
            lo, hi = min(lo, num_blocks), min(hi, num_blocks)
            if lo >= hi:
                continue
            layout[h, :, lo:hi] = 1
            if self.horizontal_global_attention:
                layout[h, lo:hi, :] = 1
        if self.attention == "unidirectional":
            tri = np.tril(np.ones((num_blocks, num_blocks), dtype=layout.dtype))
            layout[h] *= tri
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        num_blocks = layout.shape[1]
        for h in range(self.num_layout_heads):
            layout = self._set_random(layout, h, num_blocks)
            layout = self._set_local(layout, h, num_blocks)
            layout = self._set_global(layout, h, num_blocks)
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird (reference sparsity_config.py:421): random + sliding window +
    global (first/last blocks)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=1, num_sliding_window_blocks=3, num_global_blocks=1):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks

    def _set_random(self, layout, h, num_blocks):
        if num_blocks < self.num_random_blocks:
            raise ValueError(
                f"Number of random blocks, {self.num_random_blocks}, must be smaller than overall number "
                f"of blocks in a row, {num_blocks}!"
            )
        for r in range(num_blocks):
            rand_cols = random.sample(range(num_blocks), self.num_random_blocks)
            layout[h, r, rand_cols] = 1
        return layout

    def _set_sliding(self, layout, h, num_blocks):
        if num_blocks < self.num_sliding_window_blocks:
            raise ValueError(
                f"Number of sliding window blocks, {self.num_sliding_window_blocks}, must be smaller than "
                f"overall number of blocks in a row, {num_blocks}!"
            )
        half = self.num_sliding_window_blocks // 2
        for r in range(num_blocks):
            lo = max(0, r - half)
            hi = min(num_blocks, r + half + 1)
            layout[h, r, lo:hi] = 1
        return layout

    def _set_global(self, layout, h, num_blocks):
        if num_blocks < self.num_global_blocks:
            raise ValueError(
                f"Number of global blocks, {self.num_global_blocks}, must be smaller than overall number "
                f"of blocks in a row, {num_blocks}!"
            )
        g = self.num_global_blocks
        layout[h, 0:g, :] = 1
        layout[h, :, 0:g] = 1
        layout[h, -g:, :] = 1
        layout[h, :, -g:] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        num_blocks = layout.shape[1]
        for h in range(self.num_layout_heads):
            layout = self._set_random(layout, h, num_blocks)
            layout = self._set_sliding(layout, h, num_blocks)
            layout = self._set_global(layout, h, num_blocks)
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer (reference sparsity_config.py:544): sliding
    window + user-chosen global blocks (bidirectional)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_sliding_window_blocks=3, global_block_indices=None, global_block_end_indices=None):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices if global_block_indices is not None else [0]
        if global_block_end_indices is not None:
            if len(self.global_block_indices) != len(global_block_end_indices):
                raise ValueError(
                    f"Global block start indices length, {len(self.global_block_indices)}, must be same as "
                    f"global block end indices length, {len(global_block_end_indices)}!"
                )
            for _, (start_idx, end_idx) in enumerate(zip(self.global_block_indices, global_block_end_indices)):
                if start_idx >= end_idx:
                    raise ValueError(
                        f"Global block start index, {start_idx}, must be smaller than global block end index, {end_idx}!"
                    )
        self.global_block_end_indices = global_block_end_indices

    def _set_sliding(self, layout, h, num_blocks):
        half = self.num_sliding_window_blocks // 2
        for r in range(num_blocks):
            lo = max(0, r - half)
            hi = min(num_blocks, r + half + 1)
            layout[h, r, lo:hi] = 1
        return layout

    def _set_global(self, layout, h, num_blocks):
        if self.global_block_end_indices is None:
            targets = [(i, i + 1) for i in self.global_block_indices]
        else:
            targets = list(zip(self.global_block_indices, self.global_block_end_indices))
        for lo, hi in targets:
            lo, hi = min(lo, num_blocks), min(hi, num_blocks)
            if lo >= hi:
                continue
            layout[h, :, lo:hi] = 1
            layout[h, lo:hi, :] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        num_blocks = layout.shape[1]
        for h in range(self.num_layout_heads):
            layout = self._set_sliding(layout, h, num_blocks)
            layout = self._set_global(layout, h, num_blocks)
        return self.check_and_propagate_first_head_layout(layout)


def sparsity_config_from_dict(d, num_heads):
    """Build a SparsityConfig from a parsed ds_config ``sparse_attention``
    section (``runtime/config.py:get_sparse_attention``). The reference
    parses the JSON but leaves users to construct the object by hand in
    their model code; this closes that gap — the parsed dict's keys are
    exactly the constructor kwargs.

        cfg = engine.sparse_attention_sparsity_config(num_heads=16)
    """
    classes = {
        "dense": DenseSparsityConfig,
        "fixed": FixedSparsityConfig,
        "variable": VariableSparsityConfig,
        "bigbird": BigBirdSparsityConfig,
        "bslongformer": BSLongformerSparsityConfig,
    }
    d = dict(d)
    # absent mode defaults to "fixed", matching the JSON parser
    # (runtime/config.py SPARSE_MODE_DEFAULT)
    mode = d.pop("mode", "fixed")
    try:
        cls = classes[mode]
    except KeyError:
        raise NotImplementedError(f"sparsity mode {mode!r} not implemented") from None
    return cls(num_heads=num_heads, **d)
