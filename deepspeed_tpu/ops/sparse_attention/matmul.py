"""Block-sparse matmul ops (sdd / dsd / dds).

Capability parity with the reference's Triton block-sparse ``MatMul``
(``deepspeed/ops/sparse_attention/matmul.py`` + ``trsrc/matmul.tr``): the three
sparse x dense product modes over a [H, S/B, S/B] block layout:

- ``sdd``: dense @ dense -> sparse blocks (only layout-nonzero blocks computed)
- ``dsd``: sparse @ dense -> dense
- ``dds``: dense @ sparse -> dense

TPU-first: the hot path (attention) uses the FUSED kernel in
``ops/transformer/attention.py`` — on TPU separately materializing sparse
score blocks then softmax then PV wastes HBM round-trips that the fused
online-softmax kernel avoids. These standalone ops exist for API parity and
for non-attention uses; they compute via gather/einsum over layout blocks,
which XLA fuses into batched MXU matmuls over the nnz block list.

Sparse operand format: [B, nnz, block, block] where nnz enumerates the
layout's nonzero (h, i, j) blocks in row-major order (the reference's same
packing).
"""

import numpy as np

import jax
import jax.numpy as jnp


class MatMul:
    """Block-sparse matmul bound to a fixed layout (reference matmul.py)."""

    def __init__(self, layout, block, mode, trans_a=False, trans_b=False):
        if mode not in ("sdd", "dsd", "dds"):
            raise NotImplementedError(f"Supported modes are: sdd, dsd, dds; got {mode}")
        self.layout = np.asarray(layout)
        self.block = block
        self.mode = mode
        self.trans_a = trans_a
        self.trans_b = trans_b
        H, self.nb_q, self.nb_k = self.layout.shape
        self.num_heads = H
        hh, ii, jj = np.nonzero(self.layout)
        self.blocks_h = jnp.asarray(hh, jnp.int32)
        self.blocks_i = jnp.asarray(ii, jnp.int32)
        self.blocks_j = jnp.asarray(jj, jnp.int32)
        self.nnz = len(hh)

    def _split_blocks(self, x):
        """[B, H, S, T] -> per-block gather [B, nnz, blk, blk_t]."""
        B, H, S, T = x.shape
        blk = self.block
        xb = x.reshape(B, H, S // blk, blk, T // blk, blk).transpose(0, 1, 2, 4, 3, 5)
        return xb[:, self.blocks_h, self.blocks_i, self.blocks_j]  # [B, nnz, blk, blk]

    def _merge_blocks(self, vals, B, S, T):
        """[B, nnz, blk, blk] -> dense [B, H, S, T] with zeros elsewhere."""
        blk = self.block
        out = jnp.zeros((B, self.num_heads, S // blk, T // blk, blk, blk), vals.dtype)
        out = out.at[:, self.blocks_h, self.blocks_i, self.blocks_j].set(vals)
        return out.transpose(0, 1, 2, 4, 3, 5).reshape(B, self.num_heads, S, T)

    def __call__(self, a, b):
        blk = self.block
        if self.mode == "sdd":
            # C_block(h,i,j) = op(a)[h, rows i] @ op(b)[h, cols j]
            if self.trans_a:
                a = jnp.swapaxes(a, -1, -2)
            if self.trans_b:
                b = jnp.swapaxes(b, -1, -2)
            B = a.shape[0]
            K = a.shape[-1]
            a_blk = a.reshape(B, self.num_heads, self.nb_q, blk, K)
            b_blk = b.reshape(B, self.num_heads, K, self.nb_k, blk)
            a_sel = a_blk[:, self.blocks_h, self.blocks_i]          # [B, nnz, blk, K]
            b_sel = b_blk[:, self.blocks_h, :, self.blocks_j]       # [nnz, B, K, blk]
            b_sel = jnp.moveaxis(b_sel, 0, 1)                       # [B, nnz, K, blk]
            return jnp.einsum("bnik,bnkj->bnij", a_sel, b_sel)
        elif self.mode == "dsd":
            # a sparse [B,nnz,blk,blk], b dense [B,H,S,D] -> dense [B,H,S,D]
            if self.trans_a:
                a = jnp.swapaxes(a, -1, -2)
                rows, cols = self.blocks_j, self.blocks_i
            else:
                rows, cols = self.blocks_i, self.blocks_j
            B = b.shape[0]
            D = b.shape[-1]
            nb_rows = self.nb_k if self.trans_a else self.nb_q
            b_blk = b.reshape(B, self.num_heads, b.shape[2] // blk, blk, D)
            b_sel = b_blk[:, self.blocks_h, cols]            # [B, nnz, blk, D]
            prod = jnp.einsum("bnij,bnjd->bnid", a, b_sel)   # [B, nnz, blk, D]
            out = jnp.zeros((B, self.num_heads, nb_rows, blk, D), prod.dtype)
            out = out.at[:, self.blocks_h, rows].add(prod)
            return out.reshape(B, self.num_heads, nb_rows * blk, D)
        else:  # dds
            if self.trans_b:
                b = jnp.swapaxes(b, -1, -2)
                rows, cols = self.blocks_j, self.blocks_i
            else:
                rows, cols = self.blocks_i, self.blocks_j
            B = a.shape[0]
            S = a.shape[2]
            a_blk = a  # [B, H, S, K]
            nb_cols = self.nb_q if self.trans_b else self.nb_k
            a_split = a_blk.reshape(B, self.num_heads, S, a.shape[-1] // blk, blk)
            a_sel = a_split[:, self.blocks_h, :, rows]        # [nnz? ...]
            a_sel = jnp.moveaxis(a_sel, 0, 1)                 # [B, nnz, S, blk]
            prod = jnp.einsum("bnsj,bnjk->bnsk", a_sel, b)    # [B, nnz, S, blk]
            out = jnp.zeros((B, self.num_heads, S, nb_cols, blk), prod.dtype)
            out = out.at[:, self.blocks_h, :, cols].add(jnp.moveaxis(prod, 1, 0))
            return out.reshape(B, self.num_heads, S, nb_cols * blk)


class Softmax:
    """Block-sparse softmax over sparse score blocks (reference softmax.py:
    rpe / key-padding / attention masks, scale)."""

    def __init__(self, layout, block):
        self.layout = np.asarray(layout)
        self.block = block
        H, self.nb_q, self.nb_k = self.layout.shape
        self.num_heads = H
        hh, ii, jj = np.nonzero(self.layout)
        self.blocks_h = jnp.asarray(hh, jnp.int32)
        self.blocks_i = jnp.asarray(ii, jnp.int32)
        self.blocks_j = jnp.asarray(jj, jnp.int32)
        self.nnz = len(hh)

    def __call__(self, x, scale=1.0, rpe=None, key_padding_mask=None, attn_mask=None,
                 key_padding_mask_mode="add", attn_mask_mode="add"):
        """x: sparse blocks [B, nnz, blk, blk]; softmax over each row's
        nonzero-union, computed via segment-wise max/sum across a row's blocks."""
        blk = self.block
        B = x.shape[0]
        x = x.astype(jnp.float32) * scale

        if rpe is not None:
            rpe_blk = rpe.reshape(self.num_heads, self.nb_q, blk, self.nb_k, blk)
            x = x + rpe_blk.transpose(0, 1, 3, 2, 4)[self.blocks_h, self.blocks_i, self.blocks_j][None]
        if key_padding_mask is not None:
            kp = key_padding_mask.reshape(B, self.nb_k, blk)
            kp_sel = kp[:, self.blocks_j]                       # [B, nnz, blk]
            if key_padding_mask_mode == "add":
                x = x + kp_sel[:, :, None, :].astype(jnp.float32)
            else:
                x = jnp.where(kp_sel[:, :, None, :] != 0, x, -1e30)
        if attn_mask is not None:
            am_blk = attn_mask.reshape(self.nb_q, blk, self.nb_k, blk).transpose(0, 2, 1, 3)
            am_sel = am_blk[self.blocks_i, self.blocks_j][None]
            if attn_mask_mode == "add":
                x = x + am_sel.astype(jnp.float32)
            else:
                x = jnp.where(am_sel != 0, x, -1e30)

        # Row-wise online max/sum across each (h, i) row's blocks via segment ops.
        seg_ids = self.blocks_h * self.nb_q + self.blocks_i     # [nnz]
        n_seg = self.num_heads * self.nb_q
        row_max_blk = jnp.max(x, axis=-1)                        # [B, nnz, blk]
        seg_max = jax.ops.segment_max(
            jnp.moveaxis(row_max_blk, 1, 0), seg_ids, num_segments=n_seg
        )                                                        # [nseg, B, blk]? — moveaxis: [nnz, B, blk]
        m = seg_max[seg_ids]                                     # [nnz, B, blk]
        p = jnp.exp(x - jnp.moveaxis(m, 0, 1)[:, :, :, None])
        row_sum_blk = jnp.sum(p, axis=-1)                        # [B, nnz, blk]
        seg_sum = jax.ops.segment_sum(
            jnp.moveaxis(row_sum_blk, 1, 0), seg_ids, num_segments=n_seg
        )
        l = jnp.moveaxis(seg_sum[seg_ids], 0, 1)[:, :, :, None]  # [B, nnz, blk, 1]
        return (p / jnp.where(l > 0, l, 1.0)).astype(x.dtype)
