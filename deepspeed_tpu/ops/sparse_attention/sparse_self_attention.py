"""SparseSelfAttention: QK^T -> sparse softmax -> PV under a block layout.

Capability parity with the reference ``deepspeed/ops/sparse_attention/
sparse_self_attention.py:14`` (attention chain :152-164). TPU-first: on TPU
the whole chain dispatches to the FUSED Pallas kernel
(``ops/transformer/attention.py``) — one kernel instead of the reference's
sdd-matmul + sparse-softmax + dsd-matmul sequence, so score blocks never hit
HBM. The unfused MatMul/Softmax path remains available for parity testing.
"""

import numpy as np

import jax.numpy as jnp

from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    FixedSparsityConfig,
    SparsityConfig,
)
from deepspeed_tpu.ops.transformer.attention import flash_attention


class SparseSelfAttention:
    """Computes sparse self-attention given q,k,v [B, H, S, D]."""

    ops = {}

    def __init__(self, sparsity_config=None, key_padding_mask_mode="add",
                 attn_mask_mode="mul", max_seq_length=2048):
        self.sparsity_config = sparsity_config or FixedSparsityConfig(num_heads=4)
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self.max_seq_length = max_seq_length
        self._layout_cache = {}

    def get_layout(self, L):
        if L % self.sparsity_config.block != 0:
            raise ValueError(
                f"Sequence Length, {L}, needs to be divisible by Block size {self.sparsity_config.block}!"
            )
        if L not in self._layout_cache:
            self._layout_cache[L] = np.asarray(
                self.sparsity_config.make_layout(L)
            )
        return self._layout_cache[L]

    def __call__(self, query, key, value, rpe=None, key_padding_mask=None, attn_mask=None):
        """query/key/value: [B, H, S, D]. Masks follow the reference semantics:
        ``key_padding_mask`` [B, S] (add mode: additive float; mul mode: 0/1),
        ``attn_mask`` [S, S]."""
        assert query.dtype == key.dtype == value.dtype, "only one dtype supported"
        B, H, S, D = query.shape
        layout = self.get_layout(S)
        block = self.sparsity_config.block

        bias = jnp.zeros((B, S), jnp.float32)
        if key_padding_mask is not None:
            kp = jnp.asarray(key_padding_mask)
            if self.key_padding_mask_mode == "add":
                bias = bias + kp.astype(jnp.float32)
            else:
                bias = bias + jnp.where(kp != 0, 0.0, -1e30)

        causal = False
        if attn_mask is not None:
            am = np.asarray(attn_mask)
            tril = np.tril(np.ones_like(am))
            if self.attn_mask_mode == "mul" and np.array_equal(am != 0, tril != 0):
                causal = True  # common case handled in-kernel
            else:
                raise NotImplementedError(
                    "general attn_mask is supported via the unfused Softmax op; "
                    "the fused path handles causal masks"
                )

        return flash_attention(
            query, key, value, mask=bias, layout=layout, block=block, causal=causal
        )

    forward = __call__
