"""Helpers to adopt sparse attention in HF-style transformer models.

Capability parity with the reference ``deepspeed/ops/sparse_attention/
sparse_attention_utils.py:13``: position-embedding extension, input padding to
a block multiple, and swapping a model's self-attention for
``BertSparseSelfAttention``.
"""

import numpy as np

import jax.numpy as jnp

from deepspeed_tpu.ops.sparse_attention.bert_sparse_self_attention import (
    BertSparseSelfAttention,
)


class SparseAttentionUtils:
    """Static helpers (reference keeps the same static-class shape)."""

    @staticmethod
    def extend_position_embedding(params, max_position):
        """Extend a position-embedding table to ``max_position`` rows by tiling
        the trained rows (reference extends HF bert/roberta tables)."""

        def extend(table):
            cur = table.shape[0]
            if cur >= max_position:
                return table
            reps = int(np.ceil(max_position / cur))
            return jnp.tile(table, (reps, 1))[:max_position]

        return extend(params) if hasattr(params, "shape") else jnp.asarray(params)

    @staticmethod
    def update_tokenizer_model_max_length(tokenizer, max_position):
        tokenizer.model_max_length = max_position
        if hasattr(tokenizer, "init_kwargs"):
            tokenizer.init_kwargs["model_max_length"] = max_position
        return tokenizer

    @staticmethod
    def replace_model_self_attention_with_sparse_self_attention(
        model_config, sparsity_config
    ):
        """Return a BertSparseSelfAttention factory for the model's shape; the
        flax idiom is construct-time substitution rather than the reference's
        in-place module surgery (module_inject does the recursive swap)."""
        return BertSparseSelfAttention(
            hidden_size=model_config.hidden_size,
            num_attention_heads=model_config.num_attention_heads,
            sparsity_config=sparsity_config,
        )

    @staticmethod
    def pad_to_block_size(block_size, input_ids, attention_mask=None,
                          token_type_ids=None, position_ids=None,
                          inputs_embeds=None, pad_token_id=0, model_embeddings=None):
        """Pad sequence length up to a block multiple (reference :138): returns
        (pad_len, padded tensors...)."""
        B, S = input_ids.shape[:2]
        pad_len = (block_size - S % block_size) % block_size
        if pad_len == 0:
            return 0, input_ids, attention_mask, token_type_ids, position_ids, inputs_embeds

        def pad(x, value=0):
            if x is None:
                return None
            widths = [(0, 0)] * x.ndim
            widths[1] = (0, pad_len)
            return jnp.pad(x, widths, constant_values=value)

        return (
            pad_len,
            pad(input_ids, pad_token_id),
            pad(attention_mask, 0),
            pad(token_type_ids, 0),
            pad(position_ids, 0),
            pad(inputs_embeds, 0),
        )

    @staticmethod
    def unpad_sequence_output(pad_len, sequence_output):
        if pad_len > 0:
            sequence_output = sequence_output[:, :-pad_len]
        return sequence_output
