from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    SparsityConfig,
    DenseSparsityConfig,
    FixedSparsityConfig,
    VariableSparsityConfig,
    BigBirdSparsityConfig,
    BSLongformerSparsityConfig,
    sparsity_config_from_dict,
)
from deepspeed_tpu.ops.sparse_attention.matmul import MatMul, Softmax
from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import SparseSelfAttention
from deepspeed_tpu.ops.sparse_attention.bert_sparse_self_attention import (
    BertSparseSelfAttention,
)
from deepspeed_tpu.ops.sparse_attention.sparse_attention_utils import SparseAttentionUtils
