"""BertSparseSelfAttention: BERT-style QKV projection + SparseSelfAttention.

Capability parity with the reference ``deepspeed/ops/sparse_attention/
bert_sparse_self_attention.py:9`` as a flax module.
"""

import jax.numpy as jnp
import flax.linen as nn

from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import SparseSelfAttention
from deepspeed_tpu.ops.sparse_attention.sparsity_config import FixedSparsityConfig


class BertSparseSelfAttention(nn.Module):
    """Drop-in sparse replacement for a BERT self-attention block.

    Config carries hidden_size / num_attention_heads (reference takes a BERT
    config object); ``sparsity_config`` picks the layout family.
    """

    hidden_size: int
    num_attention_heads: int
    sparsity_config: object = None

    def setup(self):
        if self.hidden_size % self.num_attention_heads != 0:
            raise ValueError(
                f"The hidden size ({self.hidden_size}) is not a multiple of "
                f"the number of attention heads ({self.num_attention_heads})"
            )
        self.attention_head_size = self.hidden_size // self.num_attention_heads
        self.query = nn.Dense(self.hidden_size)
        self.key = nn.Dense(self.hidden_size)
        self.value = nn.Dense(self.hidden_size)
        cfg = self.sparsity_config or FixedSparsityConfig(num_heads=self.num_attention_heads)
        self.sparse_self_attention = SparseSelfAttention(cfg)

    def _transpose_for_scores(self, x):
        B, S, _ = x.shape
        return x.reshape(B, S, self.num_attention_heads, self.attention_head_size).transpose(0, 2, 1, 3)

    def __call__(self, hidden_states, attention_mask=None):
        q = self._transpose_for_scores(self.query(hidden_states))
        k = self._transpose_for_scores(self.key(hidden_states))
        v = self._transpose_for_scores(self.value(hidden_states))
        ctx = self.sparse_self_attention(q, k, v, key_padding_mask=attention_mask)
        B, H, S, D = ctx.shape
        return ctx.transpose(0, 2, 1, 3).reshape(B, S, H * D)
