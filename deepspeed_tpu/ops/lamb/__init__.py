from deepspeed_tpu.ops.lamb.fused_lamb import FusedLamb
