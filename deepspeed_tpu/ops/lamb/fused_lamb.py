"""Fused LAMB.

Capability parity with the reference's ``FusedLamb`` (``deepspeed/ops/lamb/
fused_lamb.py`` + ``csrc/lamb/fused_lamb_cuda_kernel.cu``): LAMB step with a
per-tensor trust ratio ||w||/||u|| clamped to [min_coeff, max_coeff]. The two
norm reductions per tensor are XLA-fused; under ZeRO the shard-local step uses
the same code over the flat partition.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class LambState(NamedTuple):
    step: jnp.ndarray
    exp_avg: object
    exp_avg_sq: object


class FusedLamb:
    def __init__(self, lr=1e-3, bias_correction=True, betas=(0.9, 0.999), eps=1e-8,
                 eps_inside_sqrt=False, weight_decay=0.0, max_grad_norm=0.0,
                 max_coeff=10.0, min_coeff=0.01, amsgrad=False, **kwargs):
        if amsgrad:
            raise RuntimeError("FusedLamb does not support the AMSGrad variant.")
        if kwargs.get("no_decay_names"):
            raise ValueError(
                "no_decay_names is only supported by Adam/AdamW (FusedAdam)")
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = tuple(betas)
        self.eps = eps
        self.eps_inside_sqrt = eps_inside_sqrt
        self.weight_decay = weight_decay
        self.max_coeff = max_coeff
        self.min_coeff = min_coeff

    def init(self, params):
        zeros = lambda p: jnp.zeros_like(p, dtype=jnp.float32)
        return LambState(
            step=jnp.asarray(0, jnp.int32),
            exp_avg=jax.tree_util.tree_map(zeros, params),
            exp_avg_sq=jax.tree_util.tree_map(zeros, params),
        )

    def update(self, grads, state, params, lr=None):
        lr = self.lr if lr is None else lr
        beta1, beta2 = self.betas
        step = state.step + 1

        def upd(g, m, v, p):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_new = beta1 * m + (1 - beta1) * g
            v_new = beta2 * v + (1 - beta2) * jnp.square(g)
            if self.bias_correction:
                bc1 = 1 - beta1**step.astype(jnp.float32)
                bc2 = 1 - beta2**step.astype(jnp.float32)
                m_hat = m_new / bc1
                v_hat = v_new / bc2
            else:
                m_hat, v_hat = m_new, v_new
            if self.eps_inside_sqrt:
                update = m_hat / jnp.sqrt(v_hat + self.eps)
            else:
                update = m_hat / (jnp.sqrt(v_hat) + self.eps)
            if self.weight_decay != 0.0:
                update = update + self.weight_decay * p32
            # Per-tensor trust ratio with coefficient clamping
            # (reference fused_lamb_cuda_kernel.cu reduction + clamp).
            w_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
            u_norm = jnp.sqrt(jnp.sum(jnp.square(update)))
            trust = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, self.min_coeff, self.max_coeff),
                jnp.asarray(1.0, jnp.float32),
            )
            return (p32 - lr * trust * update).astype(p.dtype), m_new, v_new

        from deepspeed_tpu.ops.utils_op import tree_map_multi

        new_params, new_m, new_v = tree_map_multi(
            upd, 3, grads, state.exp_avg, state.exp_avg_sq, params
        )
        return new_params, LambState(step=step, exp_avg=new_m, exp_avg_sq=new_v)

    @property
    def name(self):
        return "lamb"
