"""Flatten/unflatten over pytrees.

Capability parity with the reference's ``utils`` op (``csrc/utils/
flatten_unflatten.cpp``: torch's flatten_dense_tensors exposed as a fast op,
used by the engine and ZeRO). Under XLA these are pure data movement that the
compiler fuses/elides, so no native kernel is needed; the API matches so ZeRO
and fp16 code reads like the reference design.
"""

import numpy as np

import jax
import jax.numpy as jnp


def tree_spec(tree):
    """(treedef, shapes, dtypes, sizes) describing a pytree of arrays."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = [l.shape for l in leaves]
    dtypes = [l.dtype for l in leaves]
    sizes = [int(np.prod(s)) if len(s) else 1 for s in shapes]
    return treedef, shapes, dtypes, sizes


def flatten_dense_tensors(tree, dtype=jnp.float32):
    """Concatenate all leaves into one flat 1-D array (jit-safe)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((0,), dtype)
    return jnp.concatenate([l.reshape(-1).astype(dtype) for l in leaves])


def unflatten_dense_tensors(flat, treedef, shapes, dtypes):
    """Inverse of flatten: split + reshape back into the pytree (jit-safe)."""
    sizes = [int(np.prod(s)) if len(s) else 1 for s in shapes]
    offsets = np.cumsum([0] + sizes)
    leaves = [
        jax.lax.dynamic_slice(flat, (int(offsets[i]),), (sizes[i],)).reshape(shapes[i]).astype(dtypes[i])
        for i in range(len(shapes))
    ]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def tree_map_multi(fn, n_out, tree, *rest):
    """Map ``fn`` (returning an ``n_out``-tuple) over aligned pytrees and
    un-zip the results into ``n_out`` pytrees. Unlike
    ``tree_map(..., is_leaf=lambda x: isinstance(x, tuple))`` picking, this is
    robust to tuples appearing INSIDE the input pytrees (e.g. the compiled
    pipeline's ``(stacked_params, aux_params)``)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    rest_leaves = [jax.tree_util.tree_leaves(r) for r in rest]
    outs = [fn(l, *(rl[i] for rl in rest_leaves)) for i, l in enumerate(leaves)]
    return tuple(
        jax.tree_util.tree_unflatten(treedef, [o[k] for o in outs])
        for k in range(n_out)
    )


def pad_to_multiple(flat, multiple):
    """Zero-pad a flat array so its length divides ``multiple``; returns (padded, orig_len)."""
    n = flat.shape[0]
    padded = int(np.ceil(n / multiple)) * multiple if n else multiple
    if padded != n:
        flat = jnp.concatenate([flat, jnp.zeros((padded - n,), flat.dtype)])
    return flat, n
