"""Native-op build system.

Capability parity with the reference's ``op_builder/`` (``OpBuilder.load()``:
import a pre-built library or ninja-JIT-compile it on first use,
builder.py:170-220). Here ops are plain C shared libraries compiled with g++
and loaded via ctypes; AOT builds go through ``csrc/Makefile`` or setup.py.
"""

import os
import shutil
import subprocess

from deepspeed_tpu.utils.logging import logger

CSRC = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..", "csrc"))
LIBDIR = os.path.join(os.path.dirname(__file__), "lib")


class OpBuilder:
    NAME = "base"
    SOURCES = []  # relative to csrc/
    EXTRA_FLAGS = []

    def lib_path(self):
        return os.path.join(LIBDIR, f"libdstpu_{self.NAME}.so")

    def is_compatible(self):
        return shutil.which("g++") is not None

    def command(self, out):
        srcs = [os.path.join(CSRC, s) for s in self.SOURCES]
        return ["g++", "-O3", "-march=native", "-fopenmp", "-fPIC", "-shared", "-o", out] + srcs + self.EXTRA_FLAGS

    def load_path(self):
        """Return path to the built .so, JIT-compiling if needed."""
        out = self.lib_path()
        srcs = [os.path.join(CSRC, s) for s in self.SOURCES]
        if os.path.exists(out) and all(
            os.path.getmtime(out) >= os.path.getmtime(s) for s in srcs if os.path.exists(s)
        ):
            return out
        if not self.is_compatible():
            raise RuntimeError(f"no C++ compiler available to build op {self.NAME}")
        os.makedirs(LIBDIR, exist_ok=True)
        cmd = self.command(out)
        logger.info(f"JIT-building op {self.NAME}: {' '.join(cmd)}")
        subprocess.check_call(cmd)
        return out


class CPUAdamBuilder(OpBuilder):
    """Host library: offload Adam/LAMB, flatten/unflatten, LUT segmenter."""

    NAME = "cpu"
    SOURCES = ["cpu_adam.cpp", "host_ops.cpp"]


class PallasOp:
    """Registry entry for a Pallas (device) kernel — 'installed' means the
    Pallas TPU lowering path is importable; nothing to compile ahead of time
    (XLA JIT-compiles at first trace, reference op_builder's JIT semantics)."""

    def __init__(self, name):
        self.NAME = name

    def is_compatible(self):
        try:
            from jax.experimental import pallas  # noqa: F401
            from jax.experimental.pallas import tpu  # noqa: F401

            return True
        except ImportError:
            return False

    def installed(self):
        return self.is_compatible()


ALL_OPS = {
    "cpu_adam": CPUAdamBuilder,
    "utils": CPUAdamBuilder,            # flatten/unflatten live in the host lib
    "transformer": PallasOp,            # fused attention (dense layouts)
    "sparse_attn": PallasOp,            # fused attention (block-sparse layouts)
}


def compatible_ops():
    """{op name: compatible?} (reference git_version_info.compatible_ops —
    a build-time matrix there; computed live here, where nothing is
    precompiled)."""
    out = {}
    for name, builder_cls in ALL_OPS.items():
        b = builder_cls() if builder_cls is not PallasOp else PallasOp(name)
        out[name] = bool(b.is_compatible())
    return out


def op_report():
    """Install/compatibility matrix (reference env_report.py op_report)."""
    lines = ["op name " + "." * 20 + " installed .. compatible", "-" * 60]
    for name, builder_cls in ALL_OPS.items():
        b = builder_cls() if builder_cls is not PallasOp else PallasOp(name)
        if isinstance(b, PallasOp):
            installed = b.installed()
        else:
            installed = os.path.exists(b.lib_path())
        compatible = b.is_compatible()
        lines.append(f"{name:<28} {'[YES]' if installed else '[NO] '} ...... {'[OKAY]' if compatible else '[NO]'}")
    return "\n".join(lines)
