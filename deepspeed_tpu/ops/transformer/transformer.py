"""DeepSpeedTransformerLayer for TPU.

Capability parity with the reference's fused transformer op
(``deepspeed/ops/transformer/transformer.py`` +
``csrc/transformer/ds_transformer_cuda.cpp``): a full BERT-style encoder layer
with the same config surface — pre/post-LayerNorm, attention/hidden dropout
ratios, ``normalize_invertible``/``attn_dropout_checkpoint``/``gelu_checkpoint``
memory knobs, ``stochastic_mode`` — built the TPU way:

- The reference hand-fuses LN/bias/dropout/softmax chains in CUDA. On TPU, XLA
  fuses those elementwise chains into the surrounding matmuls; the one place
  fusion needs help is the attention core (QK^T -> masked softmax -> PV), which
  dispatches to a Pallas flash-attention kernel on TPU
  (``deepspeed_tpu.ops.transformer.attention``) and a jnp reference path
  elsewhere.
- The memory knobs map to ``jax.checkpoint`` (rematerialization) policies
  instead of saved-tensor juggling: ``attn_dropout_checkpoint``/
  ``gelu_checkpoint``/``normalize_invertible`` all become "don't save, recompute"
  choices, which is exactly their semantic in the reference (csrc
  ds_transformer_cuda.cpp:21-37).
- ``stochastic_mode`` relaxes determinism for speed in the reference; here it
  simply permits XLA's nondeterministic reductions (no-op flag, kept for config
  parity).
"""

from dataclasses import dataclass, field

import os

import jax
import jax.numpy as jnp
import flax.linen as nn


@dataclass
class DeepSpeedTransformerConfig:
    """Config surface parity: reference transformer.py:25-121."""

    batch_size: int = -1
    max_seq_length: int = -1
    hidden_size: int = -1
    intermediate_size: int = -1
    heads: int = -1
    attn_dropout_ratio: float = -1
    hidden_dropout_ratio: float = -1
    num_hidden_layers: int = -1
    initializer_range: float = -1
    local_rank: int = -1
    seed: int = -1
    fp16: bool = False
    bf16: bool = True
    pre_layer_norm: bool = True
    normalize_invertible: bool = False
    gelu_checkpoint: bool = False
    adjust_init_range: bool = True
    attn_dropout_checkpoint: bool = False
    stochastic_mode: bool = False
    huggingface: bool = False
    training: bool = True
    causal: bool = False  # autoregressive masking applied in-kernel (GPT-style)

    @classmethod
    def from_dict(cls, json_object):
        config = cls()
        for key, value in json_object.items():
            if hasattr(config, key):
                setattr(config, key, value)
        return config

    @classmethod
    def from_json_file(cls, json_file):
        import json

        with open(json_file, "r", encoding="utf-8") as reader:
            return cls.from_dict(json.loads(reader.read()))


def _is_causal_mask(mask):
    """Static check: is this additive [.,.,S,S] mask exactly lower-triangular
    (0 on/below diag, large-negative above)? Only answerable for concrete
    arrays; traced masks -> False (jnp fallback)."""
    import numpy as np

    try:
        m = np.asarray(mask)
    except Exception:
        return False
    S = m.shape[-1]
    tril = np.tril(np.ones((S, S), bool))
    return bool(np.all((m[..., :, :] >= -1e-6) == tril))


def _attention_core(q, k, v, mask, dropout_ratio, deterministic, dropout_rng,
                    use_pallas=True, causal=False):
    """Scaled masked attention softmax + PV.

    The reference implements this as fused CUDA softmax/dropout kernels
    (csrc/transformer/softmax_kernels.cu, seq<=8K). On TPU this dispatches to a
    Pallas flash-attention kernel when available; otherwise an XLA-fused jnp
    path (still one fused softmax on TPU).

    Shapes: q,k,v = [B, H, S, D]; mask = [B, 1, 1, S] additive key bias;
    ``causal`` applies autoregressive masking (in-kernel on the fused path).
    """
    # DSTPU_ATTN=xla forces the jnp einsum chain (XLA-fused attention) even on
    # TPU — the A/B switch for benchmarking the Pallas kernel against XLA's
    # own fusion at a given shape without code changes.
    if os.environ.get("DSTPU_ATTN", "").strip().lower() == "xla":
        use_pallas = False
    if use_pallas:
        from deepspeed_tpu.ops.transformer.attention import flash_attention

        rate = 0.0 if deterministic else float(dropout_ratio)
        if rate == 0.0 or dropout_rng is not None:
            # Attention-prob dropout runs IN-KERNEL (mask regenerated from a
            # seed in backward — the reference's fused softmax-dropout
            # capability), so training with attn dropout stays on the fused
            # path instead of falling back to the jnp einsum chain.
            kw = dict(dropout_rate=rate, dropout_rng=dropout_rng if rate > 0 else None)
            # The fused kernel takes a KEY bias ([B,1,1,S] / [B,S]) plus an
            # in-kernel causal flag. A full [.,.,S,S] mask must either be
            # recognized as causal (concrete arrays only) or fall through to
            # the general jnp path — collapsing it to a key bias would be wrong.
            if mask is None or (mask.ndim == 4 and mask.shape[-2] == 1 and mask.shape[1] == 1):
                return flash_attention(q, k, v, mask, causal=causal, **kw)
            if not causal and mask.ndim == 4 and mask.shape[-2] == mask.shape[-1]:
                if _is_causal_mask(mask):
                    return flash_attention(q, k, v, None, causal=True, **kw)

    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    scores = jnp.einsum("bhsd,bhtd->bhst", q, k) * scale
    if mask is not None:
        scores = scores + mask
    if causal:
        S = q.shape[2]
        cm = jnp.tril(jnp.ones((S, S), bool))
        scores = jnp.where(cm[None, None], scores, jnp.asarray(-1e30, scores.dtype))
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    if not deterministic and dropout_ratio > 0.0:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_ratio, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_ratio), 0.0)
    return jnp.einsum("bhst,bhtd->bhsd", probs, v)


class DeepSpeedTransformerLayer(nn.Module):
    """BERT-style encoder layer with the reference's layout and knobs.

    Computation chain (reference ds_transformer_cuda.cpp:142-283):
    [pre-LN] -> QKV GEMM -> attention core -> attn out GEMM -> dropout+residual
    -> [LN] -> FF1 -> gelu -> FF2 -> dropout+residual [-> post-LN].
    """

    config: DeepSpeedTransformerConfig

    @nn.compact
    def __call__(self, hidden_states, attention_mask=None, deterministic=None):
        cfg = self.config
        deterministic = not cfg.training if deterministic is None else deterministic
        H = cfg.hidden_size
        nh = cfg.heads
        hd = H // nh
        B, S, _ = hidden_states.shape

        init = nn.initializers.normal(stddev=cfg.initializer_range if cfg.initializer_range > 0 else 0.02)
        dense = lambda feats, name: nn.Dense(feats, kernel_init=init, name=name, dtype=hidden_states.dtype)

        def attn_block(x):
            # Fused QKV projection (reference packs qkv into one GEMM).
            qkv = dense(3 * H, "qkv")(x)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            reshape = lambda t: t.reshape(B, S, nh, hd).transpose(0, 2, 1, 3)
            q, k, v = reshape(q), reshape(k), reshape(v)
            rng = self.make_rng("dropout") if (not deterministic and cfg.attn_dropout_ratio > 0) else None
            ctx = _attention_core(q, k, v, attention_mask, cfg.attn_dropout_ratio,
                                  deterministic, rng, causal=cfg.causal)
            ctx = ctx.transpose(0, 2, 1, 3).reshape(B, S, H)
            return dense(H, "attn_out")(ctx)

        def ffn_block(x):
            h = dense(cfg.intermediate_size, "ff1")(x)
            h = nn.gelu(h, approximate=False)
            return dense(H, "ff2")(h)

        dropout = nn.Dropout(rate=cfg.hidden_dropout_ratio if cfg.hidden_dropout_ratio > 0 else 0.0)

        ln1 = nn.LayerNorm(dtype=hidden_states.dtype, name="ln_attn")
        ln2 = nn.LayerNorm(dtype=hidden_states.dtype, name="ln_ffn")

        if cfg.pre_layer_norm:
            a = attn_block(ln1(hidden_states))
            a = dropout(a, deterministic=deterministic)
            x = hidden_states + a
            f = ffn_block(ln2(x))
            f = dropout(f, deterministic=deterministic)
            out = x + f
        else:
            a = attn_block(hidden_states)
            a = dropout(a, deterministic=deterministic)
            x = ln1(hidden_states + a)
            f = ffn_block(x)
            f = dropout(f, deterministic=deterministic)
            out = ln2(x + f)
        return out
