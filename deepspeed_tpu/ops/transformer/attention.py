"""Fused (optionally block-sparse) attention kernel for TPU.

Capability parity with TWO reference native-kernel subsystems at once:

- the fused attention-softmax chain of the transformer op
  (``csrc/transformer/softmax_kernels.cu``: scaled masked softmax fwd/bwd up to
  8K sequence), and
- the Triton block-sparse attention suite
  (``deepspeed/ops/sparse_attention/trsrc/{matmul.tr,softmax_*.tr}`` +
  ``csrc/sparse_attention/utils.cpp``'s layout->LUT preprocessing).

TPU-first design: ONE Pallas kernel computes QK^T -> masked online-softmax ->
PV per (batch*head, query-block-row) grid cell, streaming key/value blocks
named by a per-row lookup table (LUT). A dense layout makes it flash
attention; a sparse layout (Fixed/BigBird/Longformer, see
``sparsity_config.py``) skips absent blocks entirely, which is exactly the
load-balanced-LUT design of the reference's Triton kernels re-tiled for the
MXU (128-lane blocks instead of 16/32). Memory stays O(S*D + nnz_blocks) —
scores never materialize.

The backward pass on the TPU path runs dedicated flash backward Pallas
kernels (``_attn_bwd_dq_kernel`` / ``_attn_bwd_dkv_kernel``): dq streams the
row LUT, dk/dv/dbias stream the transposed (column) LUT, recomputing p from
the saved log-sum-exp residual so memory stays O(S*D). On non-TPU backends
the dense jnp reference path runs fwd and bwd (same numerics, dense-masked).
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK = 128


# ---------------------------------------------------------------------------
# layout -> LUT  (reference csrc/sparse_attention/utils.cpp in numpy)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=64)
def _dense_lut(num_heads, num_q_blocks, num_k_blocks):
    lut = np.tile(np.arange(num_k_blocks, dtype=np.int32), (num_heads, num_q_blocks, 1))
    counts = np.full((num_heads, num_q_blocks), num_k_blocks, np.int32)
    return lut, counts


def layout_to_lut(layout):
    """[H, Qb, Kb] 0/1 layout -> (lut [H, Qb, maxnnz] int32, counts [H, Qb]).

    Rows are padded to the max row population; the kernel loops ``counts``
    blocks so padding is never touched. Delegates to the native OpenMP
    segmenter (csrc/host_ops.cpp, parity with the reference's
    csrc/sparse_attention/utils.cpp) when the library is built.
    """
    from deepspeed_tpu.ops.host_ops import layout_to_lut_host

    return layout_to_lut_host(np.asarray(layout))


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------

def _fold_dropout_seed(seed, bh, qi, kj):
    """Fold the 4-word dropout-PRNG identity into the TWO seed words Mosaic's
    tpu.prng_set_seed_32 accepts (real-TPU compile rejects more). Injective
    for fixed ``seed``: an odd multiplier permutes i32 space (distinguishes
    bh), and block indices are always < 2**16 (distinguishes (qi, kj)) —
    distinct blocks must never share a dropout mask. Works on concrete ints
    and traced i32 alike (unit-tested for injectivity; the kernel path is
    only compilable on real TPU hardware)."""
    return (
        seed + bh * jnp.int32(-1640531527),
        qi * jnp.int32(65536) + kj,
    )


def _dropout_keep(seed_ref, bh, qi, kj, block_q, block_k, rate):
    """[BQ, BK] keep/(1-rate) scale mask from the TPU PRNG, deterministically
    re-derivable from (seed, bh, qi, kj) — the forward and BOTH backward
    kernels regenerate the identical mask instead of storing O(S^2) bits
    (the flash-dropout trick; reference stores the mask from its fused
    dropout kernels, csrc/transformer/dropout_kernels.cu)."""
    pltpu.prng_seed(*_fold_dropout_seed(seed_ref[0], bh, qi, kj))
    bits = pltpu.prng_random_bits((block_q, block_k)).astype(jnp.uint32)
    threshold = jnp.uint32(min(int(rate * 2**32), 2**32 - 1))
    return jnp.where(bits >= threshold, 1.0 / (1.0 - rate), 0.0)


def _attn_kernel(seed_ref, counts_ref, lut_ref, q_ref, k_ref, v_ref, bias_ref,
                 o_ref, lse_ref,
                 *, num_heads, block_q, block_k, maxn, scale, causal, dropout_rate):
    """One (batch*head, q-block-row) cell: stream LUT-named k/v blocks with
    online softmax. carry = (m, l, acc) runs in registers/VMEM values.

    Dropout (rate > 0) applies to the softmax PROBS: the normalizer l
    accumulates the UNDROPPED p while acc accumulates (mask * p / keep) @ v,
    so out = dropout(softmax(s)) @ v exactly."""
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    h = jax.lax.rem(bh, num_heads)

    # MXU dtype discipline: matmul OPERANDS stay in the input dtype (bf16
    # inputs hit the native bf16 MXU path — fp32 matmuls are several times
    # slower on TPU) while every accumulation/softmax runs in fp32 via
    # preferred_element_type. Scale applies to the fp32 scores, not to q.
    q = q_ref[0]                                      # [BQ, D], input dtype
    in_dtype = q.dtype
    D = q.shape[-1]
    count = counts_ref[h, qi]

    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(n, carry):
        m, l, acc = carry
        kj = lut_ref[h, qi, n]
        k_blk = k_ref[0, pl.ds(kj * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kj * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                                      # [BQ, BK] fp32
        s = s + bias_ref[0, 0, pl.ds(kj * block_k, block_k)].astype(jnp.float32)[None, :]
        if causal:
            k_pos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, -1e30)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        p_acc = p
        if dropout_rate > 0.0:
            p_acc = p * _dropout_keep(seed_ref, bh, qi, kj, block_q, block_k, dropout_rate)
        acc_new = acc * corr + jax.lax.dot_general(
            p_acc.astype(in_dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, count, body, (m0, l0, acc0))

    out = jnp.where(l > 0.0, acc / jnp.where(l > 0.0, l, 1.0), 0.0)
    o_ref[0] = out.astype(o_ref.dtype)
    # log-sum-exp residual for the flash backward; +inf-like for empty rows so
    # exp(s - lse) == 0 there. Stored [1,1,BQ]: Mosaic requires the last two
    # block dims be (8,128)-aligned or equal to the array dims, which a 2D
    # (1, BQ) block on a (BH, S) array violates whenever BH > 1.
    lse = jnp.where(l[:, 0] > 0.0, m[:, 0] + jnp.log(jnp.where(l[:, 0] > 0, l[:, 0], 1.0)), 1e30)
    lse_ref[0, 0] = lse


def _attention_pallas(q, k, v, bias, lut, counts, *, block_q, block_k, causal,
                      interpret=False, dropout_rate=0.0, seed=None):
    """q,k,v: [B, H, S, D]; bias additive [B, S] (key bias, e.g. padding).
    ``seed``: [1] int32 array feeding the in-kernel dropout PRNG."""
    B, H, S, D = q.shape
    BH = B * H
    qr = q.reshape(BH, S, D)
    kr = k.reshape(BH, S, D)
    vr = v.reshape(BH, S, D)
    maxn = lut.shape[-1]
    scale = 1.0 / float(np.sqrt(D))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(BH, S // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, *_: (bh, qi, 0)),
            pl.BlockSpec((1, S, D), lambda bh, qi, *_: (bh, 0, 0)),
            pl.BlockSpec((1, S, D), lambda bh, qi, *_: (bh, 0, 0)),
            pl.BlockSpec((1, 1, S), lambda bh, qi, *_: (bh, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_q, D), lambda bh, qi, *_: (bh, qi, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bh, qi, *_: (bh, 0, qi)),
        ),
    )
    kernel = functools.partial(
        _attn_kernel, num_heads=H, block_q=block_q, block_k=block_k,
        maxn=maxn, scale=scale, causal=causal, dropout_rate=dropout_rate,
    )
    bias_r = jnp.broadcast_to(bias[:, None, :], (B, H, S)).reshape(BH, 1, S)
    seed_arr = jnp.zeros((1,), jnp.int32) if seed is None else jnp.asarray(seed, jnp.int32).reshape(1)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 1, S), jnp.float32),
        ),
        interpret=interpret,
    )(seed_arr, jnp.asarray(counts), jnp.asarray(lut), qr, kr, vr, bias_r)
    return out.reshape(B, H, S, D), lse.reshape(BH, S)


def _attn_bwd_dq_kernel(seed_ref, counts_ref, lut_ref, q_ref, k_ref, v_ref, bias_ref,
                        do_ref, lse_ref, delta_ref, dq_ref,
                        *, num_heads, block_q, block_k, scale, causal, dropout_rate):
    """dq for one (bh, q-block-row): dq = scale * sum_j ds_j @ k_j with
    ds = p * (mask * dO @ v^T - delta) and p = exp(s - lse). The dropout mask
    regenerates from (seed, bh, qi, kj) — identical to the forward's."""
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    h = jax.lax.rem(bh, num_heads)

    q = q_ref[0]                      # input dtype; scale applied to scores
    do = do_ref[0]
    in_dtype = q.dtype
    lse = lse_ref[0, 0]
    delta = delta_ref[0, 0]
    D = q.shape[-1]
    count = counts_ref[h, qi]
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(n, dq):
        kj = lut_ref[h, qi, n]
        k_blk = k_ref[0, pl.ds(kj * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(kj * block_k, block_k), :]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = s + bias_ref[0, 0, pl.ds(kj * block_k, block_k)].astype(jnp.float32)[None, :]
        if causal:
            k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -1e30)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            dp = dp * _dropout_keep(seed_ref, bh, qi, kj, block_q, block_k, dropout_rate)
        ds = p * (dp - delta[:, None])
        return dq + jax.lax.dot_general(ds.astype(in_dtype), k_blk, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, count, body, jnp.zeros((block_q, D), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _attn_bwd_dkv_kernel(seed_ref, qcounts_ref, qlut_ref, q_ref, k_ref, v_ref, bias_ref,
                         do_ref, lse_ref, delta_ref, dk_ref, dv_ref, db_ref,
                         *, num_heads, block_q, block_k, scale, causal, dropout_rate):
    """dk/dv/dbias for one (bh, k-block-column), looping the transposed LUT's
    q blocks: dv = sum (mask*p)^T dO; dk = sum ds^T (scale*q); dbias =
    sum_rows ds. The dropout mask regenerates with the same (seed, bh, qi,
    kj) ordering as the forward, regardless of this kernel's transposed
    iteration order."""
    bh = pl.program_id(0)
    kj = pl.program_id(1)
    h = jax.lax.rem(bh, num_heads)

    k_blk = k_ref[0]                  # input dtype; scale folded at write-out
    v_blk = v_ref[0]
    in_dtype = k_blk.dtype
    bias_j = bias_ref[0, 0].astype(jnp.float32)
    D = k_blk.shape[-1]
    count = qcounts_ref[h, kj]
    k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)

    def body(n, carry):
        dk, dv, db = carry
        qi = qlut_ref[h, kj, n]
        q_i = q_ref[0, pl.ds(qi * block_q, block_q), :]
        do_i = do_ref[0, pl.ds(qi * block_q, block_q), :]
        lse_i = lse_ref[0, 0, pl.ds(qi * block_q, block_q)]
        delta_i = delta_ref[0, 0, pl.ds(qi * block_q, block_q)]
        s = jax.lax.dot_general(q_i, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = s + bias_j[None, :]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            s = jnp.where(q_pos >= k_pos, s, -1e30)
        p = jnp.exp(s - lse_i[:, None])
        dp = jax.lax.dot_general(do_i, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        p_drop = p
        if dropout_rate > 0.0:
            keep = _dropout_keep(seed_ref, bh, qi, kj, block_q, block_k, dropout_rate)
            p_drop = p * keep
            dp = dp * keep
        dv = dv + jax.lax.dot_general(p_drop.astype(in_dtype), do_i, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        ds = p * (dp - delta_i[:, None])
        dk = dk + jax.lax.dot_general(ds.astype(in_dtype), q_i, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        db = db + jnp.sum(ds, axis=0)
        return dk, dv, db

    zero = jnp.zeros((block_k, D), jnp.float32)
    dk, dv, db = jax.lax.fori_loop(0, count, body, (zero, zero, jnp.zeros((block_k,), jnp.float32)))
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)
    db_ref[0, 0] = db


def _attention_pallas_bwd(q, k, v, bias, out, lse, g, lut, counts, qlut, qcounts,
                          *, block_q, block_k, causal, interpret=False,
                          dropout_rate=0.0, seed=None):
    """Flash backward: returns (dq, dk, dv, dbias[B,S])."""
    B, H, S, D = q.shape
    BH = B * H
    rs = lambda t: t.reshape(BH, S, D)
    qr, kr, vr, dor, outr = rs(q), rs(k), rs(v), rs(g), rs(out)
    scale = 1.0 / float(np.sqrt(D))
    bias_r = jnp.broadcast_to(bias[:, None, :], (B, H, S)).reshape(BH, 1, S)
    # [BH,1,S] so the (1,1,block) / (1,1,S) blockspecs below are Mosaic-legal
    # (a 2D (1,block) block on a (BH,S) array is rejected when BH > 1).
    delta = jnp.sum(dor.astype(jnp.float32) * outr.astype(jnp.float32), axis=-1)
    delta_r = delta.reshape(BH, 1, S)
    lse_r = lse.reshape(BH, 1, S)

    seed_arr = jnp.zeros((1,), jnp.int32) if seed is None else jnp.asarray(seed, jnp.int32).reshape(1)

    # dq: grid over q block rows
    dq_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(BH, S // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, qi, *_: (bh, qi, 0)),
            pl.BlockSpec((1, S, D), lambda bh, qi, *_: (bh, 0, 0)),
            pl.BlockSpec((1, S, D), lambda bh, qi, *_: (bh, 0, 0)),
            pl.BlockSpec((1, 1, S), lambda bh, qi, *_: (bh, 0, 0)),
            pl.BlockSpec((1, block_q, D), lambda bh, qi, *_: (bh, qi, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bh, qi, *_: (bh, 0, qi)),
            pl.BlockSpec((1, 1, block_q), lambda bh, qi, *_: (bh, 0, qi)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, qi, *_: (bh, qi, 0)),
    )
    dq = pl.pallas_call(
        functools.partial(_attn_bwd_dq_kernel, num_heads=H, block_q=block_q,
                          block_k=block_k, scale=scale, causal=causal,
                          dropout_rate=dropout_rate),
        grid_spec=dq_spec,
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        interpret=interpret,
    )(seed_arr, jnp.asarray(counts), jnp.asarray(lut), qr, kr, vr, bias_r, dor, lse_r, delta_r)

    # dk/dv/dbias: grid over k block columns with the TRANSPOSED LUT
    dkv_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(BH, S // block_k),
        in_specs=[
            pl.BlockSpec((1, S, D), lambda bh, kj, *_: (bh, 0, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, kj, *_: (bh, kj, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, kj, *_: (bh, kj, 0)),
            pl.BlockSpec((1, 1, block_k), lambda bh, kj, *_: (bh, 0, kj)),
            pl.BlockSpec((1, S, D), lambda bh, kj, *_: (bh, 0, 0)),
            pl.BlockSpec((1, 1, S), lambda bh, kj, *_: (bh, 0, 0)),
            pl.BlockSpec((1, 1, S), lambda bh, kj, *_: (bh, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_k, D), lambda bh, kj, *_: (bh, kj, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, kj, *_: (bh, kj, 0)),
            pl.BlockSpec((1, 1, block_k), lambda bh, kj, *_: (bh, 0, kj)),
        ),
    )
    dk, dv, db = pl.pallas_call(
        functools.partial(_attn_bwd_dkv_kernel, num_heads=H, block_q=block_q,
                          block_k=block_k, scale=scale, causal=causal,
                          dropout_rate=dropout_rate),
        grid_spec=dkv_spec,
        out_shape=(
            jax.ShapeDtypeStruct((BH, S, D), k.dtype),
            jax.ShapeDtypeStruct((BH, S, D), v.dtype),
            jax.ShapeDtypeStruct((BH, 1, S), jnp.float32),
        ),
        interpret=interpret,
    )(seed_arr, jnp.asarray(qcounts), jnp.asarray(qlut), qr, kr, vr, bias_r, dor, lse_r, delta_r)

    unrs = lambda t: t.reshape(B, H, S, D)
    dbias = db.reshape(B, H, S).sum(axis=1).astype(bias.dtype)
    return unrs(dq), unrs(dk), unrs(dv), dbias


# ---------------------------------------------------------------------------
# jnp reference path (non-TPU backends + the recompute backward)
# ---------------------------------------------------------------------------

def _attention_reference(q, k, v, bias, layout_mask, *, causal,
                         dropout_rate=0.0, seed=None):
    B, H, S, D = q.shape
    scale = 1.0 / np.sqrt(D)
    s = jnp.einsum("bhsd,bhtd->bhst", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    s = s + bias[:, None, None, :].astype(jnp.float32)
    if causal:
        cm = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(cm[None, None], s, -1e30)
    if layout_mask is not None:
        s = jnp.where(layout_mask[None], s, -1e30)
    # Rows with no admissible key (all -inf) produce 0, matching the kernel.
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    alive = m > -1e29
    probs = jnp.where(alive, p / jnp.where(l > 0, l, 1.0), 0.0)
    if dropout_rate > 0.0 and seed is not None:
        # Seed-deterministic prob dropout (same semantics as the Pallas
        # kernels' in-kernel PRNG; the bit streams differ between backends,
        # which is fine — dropout is stochastic regularization).
        key = jax.random.PRNGKey(jnp.asarray(seed).reshape(())[()].astype(jnp.uint32))
        keep = jax.random.bernoulli(key, 1.0 - dropout_rate, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
    out = jnp.einsum("bhst,bhtd->bhsd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def attention_reference(q, k, v, mask=None, causal=False):
    """Dense attention accepting an arbitrary ADDITIVE mask broadcastable to
    [B,H,S,S] (the reference transformer's mask shape) — the documented
    fallback for masks ``flash_attention`` cannot express in-kernel."""
    B, H, S, D = q.shape
    scale = 1.0 / np.sqrt(D)
    s = jnp.einsum("bhsd,bhtd->bhst", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if mask is not None:
        m = jnp.asarray(mask, jnp.float32)
        if m.ndim == 2:
            m = m[:, None, None, :]
        s = s + m
    if causal:
        cm = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(cm[None, None], s, -1e30)
    probs = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhst,bhtd->bhsd", probs, v.astype(jnp.float32)).astype(q.dtype)


def _expand_layout_mask(layout, S, block):
    if layout is None:
        return None
    layout = jnp.asarray(layout, bool)
    return jnp.repeat(jnp.repeat(layout, block, axis=1), block, axis=2)


# ---------------------------------------------------------------------------
# public entry
# ---------------------------------------------------------------------------

def _luts_for(layout, H, S, block):
    """(row LUT, counts, transposed LUT, transposed counts)."""
    nb = S // block
    if layout is None:
        lut, counts = _dense_lut(H, nb, nb)
        return lut, counts, lut, counts
    lut, counts = layout_to_lut(layout)
    qlut, qcounts = layout_to_lut(np.asarray(layout).transpose(0, 2, 1))
    return lut, counts, qlut, qcounts


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _attention(q, k, v, bias, seed, layout_key, block, causal, force_ref, dropout_rate):
    layout = _LAYOUTS.get(layout_key) if layout_key is not None else None
    if force_ref or not _on_tpu():
        return _attention_reference(
            q, k, v, bias, _expand_layout_mask(layout, q.shape[2], block),
            causal=causal, dropout_rate=dropout_rate, seed=seed,
        )
    B, H, S, D = q.shape
    lut, counts, _, _ = _luts_for(layout, H, S, block)
    out, _ = _attention_pallas(
        q, k, v, bias, lut, counts, block_q=block, block_k=block, causal=causal,
        dropout_rate=dropout_rate, seed=seed,
    )
    return out


def _on_tpu():
    return jax.default_backend() == "tpu"


def _attention_fwd(q, k, v, bias, seed, layout_key, block, causal, force_ref, dropout_rate):
    layout = _LAYOUTS.get(layout_key) if layout_key is not None else None
    if force_ref or not _on_tpu():
        out = _attention_reference(
            q, k, v, bias, _expand_layout_mask(layout, q.shape[2], block),
            causal=causal, dropout_rate=dropout_rate, seed=seed,
        )
        return out, (q, k, v, bias, seed, None, None)
    B, H, S, D = q.shape
    lut, counts, _, _ = _luts_for(layout, H, S, block)
    out, lse = _attention_pallas(
        q, k, v, bias, lut, counts, block_q=block, block_k=block, causal=causal,
        dropout_rate=dropout_rate, seed=seed,
    )
    return out, (q, k, v, bias, seed, out, lse)


def _attention_bwd(layout_key, block, causal, force_ref, dropout_rate, res, g):
    """Flash backward kernels on the Pallas path (O(S*D) memory, dropout mask
    regenerated in-kernel from the saved seed); dense rematerialized VJP on
    the reference path (same seed reproduces the same mask)."""
    q, k, v, bias, seed, out, lse = res
    layout = _LAYOUTS.get(layout_key) if layout_key is not None else None
    seed_ct = (
        None if seed is None
        else np.zeros(np.shape(seed), jax.dtypes.float0)
    )

    if lse is not None:
        B, H, S, D = q.shape
        lut, counts, qlut, qcounts = _luts_for(layout, H, S, block)
        dq, dk, dv, dbias = _attention_pallas_bwd(
            q, k, v, bias, out, lse, g, lut, counts, qlut, qcounts,
            block_q=block, block_k=block, causal=causal,
            dropout_rate=dropout_rate, seed=seed,
        )
        return dq, dk, dv, dbias, seed_ct

    def f(q, k, v, bias):
        return _attention_reference(
            q, k, v, bias, _expand_layout_mask(layout, q.shape[2], block),
            causal=causal, dropout_rate=dropout_rate, seed=seed,
        )

    _, vjp = jax.vjp(f, q, k, v, bias)
    return vjp(g) + (seed_ct,)


_attention.defvjp(_attention_fwd, _attention_bwd)

# Layouts must be hashable for custom_vjp nondiff args: register by key.
_LAYOUTS = {}


def _register_layout(layout):
    if layout is None:
        return None
    arr = np.asarray(layout)
    key = hash(arr.tobytes()) ^ hash(arr.shape)
    _LAYOUTS[key] = arr
    return key


def flash_attention(q, k, v, mask=None, layout=None, block=DEFAULT_BLOCK,
                    causal=False, force_reference=False,
                    dropout_rate=0.0, dropout_rng=None):
    """Fused attention. q,k,v: [B,H,S,D]; ``mask``: additive [B,1,1,S] (or
    [B,S]) key bias; ``layout``: optional [H, S/block, S/block] 0/1 block
    sparsity; ``causal`` adds the autoregressive mask in-kernel.

    ``dropout_rate`` > 0 (with a ``dropout_rng`` PRNG key) applies dropout to
    the softmax probs IN-KERNEL: the mask is regenerated from a seed in the
    backward kernels instead of being stored, so memory stays O(S*D) — the
    fused-softmax-dropout capability of the reference's transformer kernels
    (csrc/transformer/{softmax,dropout}_kernels.cu). The TPU kernel and the
    reference path draw from different PRNGs (same distribution)."""
    B, H, S, D = q.shape
    if not (0.0 <= dropout_rate < 1.0):
        raise ValueError(
            f"dropout_rate must be in [0, 1), got {dropout_rate} "
            "(a fraction, not a percentage)"
        )
    if dropout_rate > 0.0:
        if dropout_rng is None:
            raise ValueError("dropout_rate > 0 requires dropout_rng")
        seed = jax.random.randint(dropout_rng, (1,), 0, 2**31 - 1, dtype=jnp.int32)
    else:
        seed = None
        dropout_rate = 0.0
    if S % block != 0:
        # Unaligned sequence: fall back to the dense reference path.
        force_reference = True
    if mask is None:
        bias = jnp.zeros((B, S), q.dtype)
    elif mask.ndim == 4:
        # Only a broadcastable key bias [B,1,1,S] collapses losslessly; a full
        # [B,1,S,S]/[B,H,S,S] additive mask (the reference's shape) must NOT be
        # silently sliced to its first query row.
        if mask.shape[-2] != 1 or mask.shape[1] != 1:
            raise ValueError(
                f"flash_attention only supports key-bias masks [B,1,1,S] or [B,S]; "
                f"got {mask.shape}. For causal masking pass causal=True; for an "
                f"arbitrary S x S additive mask use the dense reference path "
                f"(ops.transformer.attention.attention_reference)."
            )
        bias = mask[:, 0, 0, :]
    else:
        bias = mask
    key = _register_layout(layout)
    return _attention(q, k, v, bias, seed, key, block, causal, force_reference,
                      float(dropout_rate))
