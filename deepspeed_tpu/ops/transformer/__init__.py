from deepspeed_tpu.ops.transformer.transformer import (
    DeepSpeedTransformerConfig,
    DeepSpeedTransformerLayer,
)
