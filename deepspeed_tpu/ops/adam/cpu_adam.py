"""DeepSpeedCPUAdam: host-side Adam for ZeRO-Offload.

Capability parity with the reference's ``deepspeed/ops/adam/cpu_adam.py`` +
``csrc/adam/cpu_adam.cpp`` (SIMD/OpenMP Adam over the fp32 master shard,
5-7x over a naive host Adam). The kernel lives in ``csrc/cpu_adam.cpp``,
compiled to ``deepspeed_tpu/ops/lib/libdstpu_cpu.so`` and loaded via ctypes
(the op_builder JIT-compiles it on first use if missing); a pure-numpy fallback
keeps the feature available without a toolchain.

It also implements the device-path optimizer interface (init/update) by
delegating to FusedAdam so the same config runs with or without offload.
"""

import ctypes
import os

import numpy as np

from deepspeed_tpu.ops.adam.fused_adam import FusedAdam
from deepspeed_tpu.utils.logging import logger

_LIB = None
_LIB_TRIED = False


def _load_lib():
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    path = os.path.join(os.path.dirname(__file__), "..", "lib", "libdstpu_cpu.so")
    path = os.path.abspath(path)
    if not os.path.exists(path):
        try:
            from deepspeed_tpu.ops.op_builder import CPUAdamBuilder

            path = CPUAdamBuilder().load_path()
        except Exception as e:
            logger.warning(f"cpu_adam native kernel unavailable ({e}); using numpy fallback")
            return None
    try:
        lib = ctypes.CDLL(path)
        lib.ds_adam_step.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
            ctypes.c_int64, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_float, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        if hasattr(lib, "ds_adam_step_out"):  # absent in pre-streaming .so builds
            lib.ds_adam_step_out.argtypes = [
                ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_int64, ctypes.c_float, ctypes.c_float, ctypes.c_float,
                ctypes.c_float, ctypes.c_float, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ]
        _LIB = lib
    except OSError as e:
        logger.warning(f"failed to load cpu_adam native kernel: {e}; using numpy fallback")
    return _LIB


class HostAdamState:
    __slots__ = ("step", "exp_avg", "exp_avg_sq")

    def __init__(self, n):
        self.step = 0
        self.exp_avg = np.zeros(n, np.float32)
        self.exp_avg_sq = np.zeros(n, np.float32)


class DeepSpeedCPUAdam(FusedAdam):
    """Adam that can step on host memory (the ZeRO-Offload optimizer)."""

    optimizer_id = 0

    def __init__(self, lr=1e-3, bias_correction=True, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, amsgrad=False, adam_w_mode=True, **kwargs):
        if kwargs.get("no_decay_names"):
            raise ValueError(
                "no_decay_names is not supported by the host (offload) Adam: "
                "the C++ kernel applies decay uniformly")
        super().__init__(lr=lr, bias_correction=bias_correction, betas=betas, eps=eps,
                         weight_decay=weight_decay, adam_w_mode=adam_w_mode, amsgrad=amsgrad)
        self._host_state = None

    # -- host path --------------------------------------------------------
    def init_host(self, flat_master):
        self._host_state = HostAdamState(flat_master.shape[0])
        return self._host_state

    def step_host(self, master, grads, lr=None, lo=0, hi=None, advance_step=True,
                  master_out=None):
        """Adam step over the host fp32 master (numpy arrays).

        ``lo``/``hi`` restrict the step to a contiguous slice of the flat
        vector so ZeRO-Offload can pipeline D2H / compute / H2D at leaf
        granularity; ``grads`` may be the full vector or exactly the slice.
        ``advance_step=False`` keeps the shared Adam step counter (bias
        correction) fixed for the 2nd..Nth slice of one logical step.

        With ``master_out=None`` the step is in place. When ``master_out``
        is a buffer of master's shape, updated params are written to
        ``master_out[lo:hi]`` and ``master`` is left untouched (bitwise
        the same values — the kernels share per-element arithmetic); the
        streamed offload path ping-pongs two masters this way so the H2D
        commit can hand out views with no snapshot copy. Moments update
        in place either way.
        """
        st = self._host_state
        assert st is not None, "call init_host first"
        if advance_step:
            st.step += 1
        hi = master.shape[0] if hi is None else hi
        n = hi - lo
        assert grads.shape[0] in (n, master.shape[0]), (
            f"grads must be the [lo,hi) slice ({n}) or the full vector "
            f"({master.shape[0]}), got {grads.shape[0]}"
        )
        g = grads if grads.shape[0] == n else grads[lo:hi]
        m = master[lo:hi]
        out = None if master_out is None else master_out[lo:hi]
        ea = st.exp_avg[lo:hi]
        es = st.exp_avg_sq[lo:hi]
        lr = float(self.lr if lr is None else lr)
        lib = _load_lib()
        beta1, beta2 = self.betas
        if lib is not None:
            fp = ctypes.POINTER(ctypes.c_float)
            common = (
                ctypes.c_int64(n), ctypes.c_float(lr),
                ctypes.c_float(beta1), ctypes.c_float(beta2), ctypes.c_float(self.eps),
                ctypes.c_float(self.weight_decay), ctypes.c_int(1 if self.adam_w_mode else 0),
                ctypes.c_int(st.step), ctypes.c_int(1 if self.bias_correction else 0),
            )
            gp = np.ascontiguousarray(g).ctypes.data_as(fp)
            if out is None:
                lib.ds_adam_step(m.ctypes.data_as(fp), gp,
                                 ea.ctypes.data_as(fp), es.ctypes.data_as(fp), *common)
            elif hasattr(lib, "ds_adam_step_out"):
                lib.ds_adam_step_out(m.ctypes.data_as(fp), out.ctypes.data_as(fp), gp,
                                     ea.ctypes.data_as(fp), es.ctypes.data_as(fp), *common)
            else:
                # stale .so without the out-of-place symbol: copy-then-step
                # keeps the exact in-place arithmetic (bitwise identical)
                np.copyto(out, m)
                lib.ds_adam_step(out.ctypes.data_as(fp), gp,
                                 ea.ctypes.data_as(fp), es.ctypes.data_as(fp), *common)
        else:
            if self.weight_decay and not self.adam_w_mode:
                g = g + self.weight_decay * m
            np.multiply(ea, beta1, out=ea)
            ea += (1 - beta1) * g
            np.multiply(es, beta2, out=es)
            es += (1 - beta2) * np.square(g)
            if self.bias_correction:
                bc1 = 1 - beta1**st.step
                bc2 = 1 - beta2**st.step
                update = (ea / bc1) / (np.sqrt(es / bc2) + self.eps)
            else:
                update = ea / (np.sqrt(es) + self.eps)
            if self.weight_decay and self.adam_w_mode:
                update = update + self.weight_decay * m
            if out is None:
                m -= lr * update
            else:
                np.subtract(m, lr * update, out=out)
        return master if master_out is None else master_out
