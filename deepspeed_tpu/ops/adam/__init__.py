from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
from deepspeed_tpu.ops.adam.fused_adam import FusedAdam
