"""Fused Adam/AdamW.

Capability parity with the reference's ``FusedAdam`` (``deepspeed/ops/adam/
fused_adam.py`` + ``csrc/adam/multi_tensor_adam.cu``): one fused update over
many tensors. On TPU the XLA compiler fuses the elementwise Adam math across a
pytree into few kernels, and ZeRO runs it over a single flat fp32 shard — both
give the multi-tensor-apply behavior without a hand-rolled kernel; a Pallas
variant can slot in behind the same interface if profiling warrants.

The optimizer is functional: ``init(params) -> state``, ``update(grads, state,
params, lr) -> (new_params, new_state)``. The learning rate is an argument so
schedules can feed it from inside a jitted step.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class AdamState(NamedTuple):
    step: jnp.ndarray
    exp_avg: object  # pytree like params
    exp_avg_sq: object  # pytree like params


class FusedAdam:
    def __init__(self, lr=1e-3, bias_correction=True, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, adam_w_mode=True, amsgrad=False, **kwargs):
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = tuple(betas)
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode

    def init(self, params):
        zeros = lambda p: jnp.zeros_like(p, dtype=jnp.float32)
        return AdamState(
            step=jnp.asarray(0, jnp.int32),
            exp_avg=jax.tree_util.tree_map(zeros, params),
            exp_avg_sq=jax.tree_util.tree_map(zeros, params),
        )

    def update(self, grads, state, params, lr=None):
        lr = self.lr if lr is None else lr
        beta1, beta2 = self.betas
        step = state.step + 1

        def upd(g, m, v, p):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if self.weight_decay != 0.0 and not self.adam_w_mode:
                g = g + self.weight_decay * p32
            m_new = beta1 * m + (1 - beta1) * g
            v_new = beta2 * v + (1 - beta2) * jnp.square(g)
            if self.bias_correction:
                bc1 = 1 - beta1**step.astype(jnp.float32)
                bc2 = 1 - beta2**step.astype(jnp.float32)
                denom = jnp.sqrt(v_new / bc2) + self.eps
                update = (m_new / bc1) / denom
            else:
                update = m_new / (jnp.sqrt(v_new) + self.eps)
            if self.weight_decay != 0.0 and self.adam_w_mode:
                update = update + self.weight_decay * p32
            return (p32 - lr * update).astype(p.dtype), m_new, v_new

        from deepspeed_tpu.ops.utils_op import tree_map_multi

        new_params, new_m, new_v = tree_map_multi(
            upd, 3, grads, state.exp_avg, state.exp_avg_sq, params
        )
        return new_params, AdamState(step=step, exp_avg=new_m, exp_avg_sq=new_v)

    # Reference name used by engine optimizer matrix.
    @property
    def name(self):
        return "adamw" if self.adam_w_mode else "adam"

    def state_dict_shapes(self, params):
        return {"exp_avg": params, "exp_avg_sq": params}
