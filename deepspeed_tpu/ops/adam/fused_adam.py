"""Fused Adam/AdamW.

Capability parity with the reference's ``FusedAdam`` (``deepspeed/ops/adam/
fused_adam.py`` + ``csrc/adam/multi_tensor_adam.cu``): one fused update over
many tensors. On TPU the XLA compiler fuses the elementwise Adam math across a
pytree into few kernels, and ZeRO runs it over a single flat fp32 shard — both
give the multi-tensor-apply behavior without a hand-rolled kernel; a Pallas
variant can slot in behind the same interface if profiling warrants.

The optimizer is functional: ``init(params) -> state``, ``update(grads, state,
params, lr) -> (new_params, new_state)``. The learning rate is an argument so
schedules can feed it from inside a jitted step.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class AdamState(NamedTuple):
    step: jnp.ndarray
    exp_avg: object  # pytree like params
    exp_avg_sq: object  # pytree like params


def decay_scales(params, no_decay_names):
    """Per-leaf weight-decay multipliers (1.0 / 0.0) from key-path substring
    matching — the pytree equivalent of torch param groups' standard
    "no decay for bias/LayerNorm" recipe. Paths are static under jit."""
    subs = [s.lower() for s in no_decay_names]

    def scale(path, _):
        path_str = "/".join(str(getattr(k, "key", k)) for k in path).lower()
        return 0.0 if any(s in path_str for s in subs) else 1.0

    return jax.tree_util.tree_map_with_path(scale, params)


class FusedAdam:
    def __init__(self, lr=1e-3, bias_correction=True, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, adam_w_mode=True, amsgrad=False,
                 no_decay_names=None, **kwargs):
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
        self.lr = lr
        self.bias_correction = bias_correction
        self.betas = tuple(betas)
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        # param-group parity: leaves whose key path contains any of these
        # substrings (case-insensitive) get NO weight decay (bias/LN recipe)
        self.no_decay_names = list(no_decay_names or [])

    def init(self, params):
        zeros = lambda p: jnp.zeros_like(p, dtype=jnp.float32)
        return AdamState(
            step=jnp.asarray(0, jnp.int32),
            exp_avg=jax.tree_util.tree_map(zeros, params),
            exp_avg_sq=jax.tree_util.tree_map(zeros, params),
        )

    def update(self, grads, state, params, lr=None, decay_mask=None):
        """``decay_mask``: optional per-leaf weight-decay multiplier (scalar
        or array broadcastable to the leaf) — ZeRO's flat path passes the
        flattened mask here since key paths are gone after flattening. When
        absent, ``no_decay_names`` is resolved against ``params``' paths."""
        lr = self.lr if lr is None else lr
        beta1, beta2 = self.betas
        step = state.step + 1
        if decay_mask is None:
            if self.no_decay_names and self.weight_decay != 0.0:
                decay_mask = decay_scales(params, self.no_decay_names)
            else:
                decay_mask = jax.tree_util.tree_map(lambda _: 1.0, params)

        def upd(g, m, v, p, dscale):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if self.weight_decay != 0.0 and not self.adam_w_mode:
                g = g + self.weight_decay * dscale * p32
            m_new = beta1 * m + (1 - beta1) * g
            v_new = beta2 * v + (1 - beta2) * jnp.square(g)
            if self.bias_correction:
                bc1 = 1 - beta1**step.astype(jnp.float32)
                bc2 = 1 - beta2**step.astype(jnp.float32)
                denom = jnp.sqrt(v_new / bc2) + self.eps
                update = (m_new / bc1) / denom
            else:
                update = m_new / (jnp.sqrt(v_new) + self.eps)
            if self.weight_decay != 0.0 and self.adam_w_mode:
                update = update + self.weight_decay * dscale * p32
            return (p32 - lr * update).astype(p.dtype), m_new, v_new

        from deepspeed_tpu.ops.utils_op import tree_map_multi

        new_params, new_m, new_v = tree_map_multi(
            upd, 3, grads, state.exp_avg, state.exp_avg_sq, params, decay_mask
        )
        return new_params, AdamState(step=step, exp_avg=new_m, exp_avg_sq=new_v)

    # Reference name used by engine optimizer matrix.
    @property
    def name(self):
        return "adamw" if self.adam_w_mode else "adam"

    def state_dict_shapes(self, params):
        return {"exp_avg": params, "exp_avg_sq": params}
