"""Chunked vocabulary cross-entropy: never materializes [B,S,V] logits.

The reference computes LM losses through full logits + CrossEntropyLoss
(vocab-sized activations); at BERT/GPT-2 vocab sizes the fp32 logits tensor
is the single largest transient of the training step (~1GB for GPT-2 at
micro-batch 8 x seq 1024 x 50304). TPU-first replacement: scan over row
chunks, compute each chunk's logits -> logsumexp -> gold-logit gather ->
masked NLL, and wrap the chunk in ``jax.checkpoint`` so the backward
recomputes chunk logits instead of saving them. Peak memory drops from
O(B*S*V) to O(chunk*V) with identical math (logsumexp - gold in fp32).
"""

import jax
import jax.numpy as jnp


def chunked_cross_entropy(hidden, kernel, bias, labels, ignore_index=-1,
                          rows_per_chunk=512):
    """Mean NLL of ``softmax(hidden @ kernel + bias)`` against ``labels``.

    - ``hidden``: [..., H] (any leading batch/seq dims)
    - ``kernel``: [H, V]; ``bias``: [V] or None
    - ``labels``: [...] int, ``ignore_index`` entries contribute 0
    Matches ``cross_entropy(full_logits, labels)`` exactly: per-row NLL is
    logsumexp(logits) - logits[gold], both in fp32.
    """
    H = hidden.shape[-1]
    h = hidden.reshape(-1, H)
    y = labels.reshape(-1)
    n = h.shape[0]

    rows = max(1, min(rows_per_chunk, n))
    pad = (-n) % rows
    if pad:
        h = jnp.concatenate([h, jnp.zeros((pad, H), h.dtype)])
        y = jnp.concatenate([y, jnp.full((pad,), ignore_index, y.dtype)])
    n_chunks = h.shape[0] // rows
    h = h.reshape(n_chunks, rows, H)
    y = y.reshape(n_chunks, rows)

    @jax.checkpoint
    def chunk_nll(hc, yc):
        logits = (hc @ kernel).astype(jnp.float32)
        if bias is not None:
            logits = logits + bias.astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        valid = yc != ignore_index
        gold = jnp.take_along_axis(
            logits, jnp.where(valid, yc, 0)[:, None], axis=-1
        )[:, 0]
        nll = jnp.where(valid, lse - gold, 0.0)
        return nll.sum(), valid.sum()

    def body(carry, xs):
        tot, cnt = carry
        hc, yc = xs
        s, c = chunk_nll(hc, yc)
        return (tot + s, cnt + c), None

    (total, count), _ = jax.lax.scan(
        body, (jnp.float32(0.0), jnp.int32(0)), (h, y)
    )
    return total / jnp.maximum(count, 1)
