from deepspeed_tpu.profiling.flops_profiler.profiler import (
    FlopsProfiler,
    get_model_profile,
    flops_to_string,
    macs_to_string,
    params_to_string,
    duration_to_string,
)
