"""FLOPS profiler.

Capability parity with the reference ``deepspeed/profiling/flops_profiler/
profiler.py`` (``FlopsProfiler:11``): per-step model FLOPs/MACs/params and
latency, printed between configured steps, plus duration/FLOPS getters.

TPU-first redesign: the reference monkey-patches ``torch.nn.functional``
(:457-519) to count MACs as the eager graph runs. Under XLA the compiler
already knows the exact cost of the compiled program, so this profiler asks
XLA (``Compiled.cost_analysis()``) and falls back to jaxpr-walking for
backends that report nothing. No patching, no hooks, exact numbers.
"""

import time

import numpy as np

import jax

from deepspeed_tpu.utils.logging import logger


def _count_params(params):
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))


def _jaxpr_flops(jaxpr, *avals):
    """Crude structural FLOP count from a jaxpr: counts dot_general/conv as
    2*M*N*K and elementwise ops as output size."""
    total = 0
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        out_size = sum(int(np.prod(v.aval.shape)) for v in eqn.outvars if hasattr(v.aval, "shape"))
        if prim == "dot_general":
            a, b = eqn.invars[0].aval, eqn.invars[1].aval
            dnums = eqn.params["dimension_numbers"]
            contract = dnums[0][0]
            k = int(np.prod([a.shape[d] for d in contract])) if contract else 1
            total += 2 * out_size * k
        elif prim in ("conv_general_dilated",):
            lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
            total += 2 * out_size * int(np.prod(rhs.shape[:-1]))
        elif prim in ("pjit", "custom_jvp_call", "custom_vjp_call", "remat", "checkpoint",
                      "custom_vjp_call_jaxpr", "closed_call"):
            inner = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
            if inner is not None:
                total += _jaxpr_flops(getattr(inner, "jaxpr", inner))
        elif prim == "scan":
            inner = eqn.params.get("jaxpr")
            if inner is not None:
                total += eqn.params.get("length", 1) * _jaxpr_flops(inner.jaxpr)
        else:
            total += out_size
    return total


class FlopsProfiler:
    """Profile a jitted step function (or an engine's forward).

    Usage parity with the reference: ``start_profile()`` / ``stop_profile()``
    bracket a step; getters expose flops/macs/params/duration;
    ``print_model_profile()`` emits the report. The model argument is a
    callable + example args instead of an nn.Module.
    """

    def __init__(self, model=None, example_args=None):
        self.model = model
        self.example_args = example_args
        self.started = False
        self.flops = 0
        self.params = 0
        self.t_start = None
        self.duration = 0.0

    # -- static analysis ---------------------------------------------------
    def analyze(self, fn, *args):
        """FLOPs of one call of ``fn(*args)`` from XLA's own cost model."""
        lowered = jax.jit(fn).lower(*args)
        flops = None
        try:
            cost = lowered.compile().cost_analysis()
            if cost:
                c = cost[0] if isinstance(cost, (list, tuple)) else cost
                flops = c.get("flops")
        except Exception:
            flops = None
        if not flops or not np.isfinite(flops):
            jaxpr = jax.make_jaxpr(fn)(*args)
            flops = _jaxpr_flops(jaxpr.jaxpr)
        return int(flops)

    # -- step profiling (reference start/stop/print cycle) ----------------
    def start_profile(self, ignore_list=None):
        self.started = True
        self.t_start = time.perf_counter()

    def stop_profile(self):
        if self.t_start is not None:
            self.duration = time.perf_counter() - self.t_start
        self.started = False

    def reset_profile(self):
        self.flops = 0
        self.duration = 0.0
        self.t_start = None

    def end_profile(self):
        self.reset_profile()

    def get_total_flops(self, as_string=False):
        return flops_to_string(self.flops) if as_string else self.flops

    def get_total_macs(self, as_string=False):
        macs = self.flops // 2
        return macs_to_string(macs) if as_string else macs

    def get_total_duration(self, as_string=False):
        return duration_to_string(self.duration) if as_string else self.duration

    def get_total_params(self, as_string=False):
        return params_to_string(self.params) if as_string else self.params

    def set_flops(self, flops):
        self.flops = int(flops)

    def set_params(self, params_tree):
        self.params = _count_params(params_tree)

    def print_model_profile(self, profile_step=None, module_depth=-1, top_modules=3,
                            detailed=True, output_file=None):
        lines = [
            "-------------------------- DeepSpeed Flops Profiler --------------------------",
            f"Profile step:                   {profile_step}",
            f"Params:                         {self.get_total_params(as_string=True)}",
            f"FLOPs per step:                 {self.get_total_flops(as_string=True)}",
            f"MACs per step:                  {self.get_total_macs(as_string=True)}",
            f"Step latency:                   {self.get_total_duration(as_string=True)}",
        ]
        if self.duration > 0 and self.flops:
            lines.append(f"Achieved FLOPS:                 {flops_to_string(self.flops / self.duration)}/s")
        lines.append("-" * 79)
        report = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as f:
                f.write(report)
        else:
            logger.info("\n" + report)
        return report

    def print_aggregated_profile(self, module_depth=-1, top_modules=3):
        self.print_model_profile(module_depth=module_depth, top_modules=top_modules)


def get_model_profile(model, args=(), kwargs=None, print_profile=True, detailed=True,
                      module_depth=-1, top_modules=3, warm_up=1, as_string=True,
                      output_file=None, ignore_modules=None):
    """One-shot: measure (flops, macs, params) of a model callable
    (reference get_model_profile)."""
    kwargs = kwargs or {}
    prof = FlopsProfiler(model)
    fn = model.apply if hasattr(model, "apply") else model
    flops = prof.analyze(lambda *a: fn(*a, **kwargs), *args)
    prof.set_flops(flops)
    if args and hasattr(args[0], "keys"):
        prof.set_params(args[0])
    if print_profile:
        prof.print_model_profile(output_file=output_file)
    macs = flops // 2
    if as_string:
        return flops_to_string(flops), macs_to_string(macs), params_to_string(prof.params)
    return flops, macs, prof.params


# -- formatting helpers (reference exposes the same names) -----------------

def _si(value, units, scale=1000.0, precision=2):
    for u in units:
        if abs(value) < scale:
            return f"{value:.{precision}f} {u}"
        value /= scale
    return f"{value:.{precision}f} {units[-1]}" if units else str(value)


def flops_to_string(flops, units=None, precision=2):
    return _si(float(flops), ["FLOPS", "KFLOPS", "MFLOPS", "GFLOPS", "TFLOPS", "PFLOPS"], precision=precision)


def macs_to_string(macs, units=None, precision=2):
    return _si(float(macs), ["MACs", "KMACs", "MMACs", "GMACs", "TMACs"], precision=precision)


def params_to_string(params_num, units=None, precision=2):
    return _si(float(params_num), ["", "k", "M", "G"], precision=precision).strip()


def duration_to_string(duration, units=None, precision=2):
    if duration > 1:
        return f"{duration:.{precision}f} s"
    if duration > 1e-3:
        return f"{duration * 1e3:.{precision}f} ms"
    return f"{duration * 1e6:.{precision}f} us"
