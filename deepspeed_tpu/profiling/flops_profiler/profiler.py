"""FLOPS profiler.

Capability parity with the reference ``deepspeed/profiling/flops_profiler/
profiler.py`` (``FlopsProfiler:11``): per-step model FLOPs/MACs/params and
latency, printed between configured steps, plus duration/FLOPS getters and
the per-module profile (``print_model_profile``/:174-230 and
``print_model_aggregated_profile``/:232-297 in the reference).

TPU-first redesign: the reference monkey-patches ``torch.nn.functional``
(:457-519) and installs per-module forward hooks to count MACs as the eager
graph runs. Under XLA the whole step is one traced program, so this profiler
asks the compiler instead: totals come from ``Compiled.cost_analysis()``
(falling back to a jaxpr walk), and the PER-MODULE breakdown comes from the
jaxpr's source-info **name stacks** — flax wraps every submodule call in
``jax.named_scope``, so each equation in the IR already carries its module
path (``Bert/encoder/layer_3/attention/...``). The compiler metadata IS the
hook. No patching, exact attribution, zero runtime overhead.
"""

import re
import time

import numpy as np

import jax

from deepspeed_tpu.utils.logging import logger

# Published bf16 peak TFLOPs per chip by device-kind substring (the table
# bench.py uses for its MFU column — kept here so the profiler's exported
# Train/Samples/mfu gauge and the bench agree on the denominator).
_PEAK_TFLOPS = [
    ("v6", 918.0),        # Trillium
    ("v5p", 459.0),
    ("v5 lite", 197.0),   # v5e reports "TPU v5 lite"
    ("v5e", 197.0),
    ("v5", 459.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
]


def device_peak_tflops(device_kind):
    """Peak bf16 TFLOPs for a jax ``device_kind`` string, None if unknown
    (CPU / unrecognized accelerator — MFU is then unreportable)."""
    kind = (device_kind or "").lower()
    for sub, peak in _PEAK_TFLOPS:
        if sub in kind:
            return peak
    return None


def _count_params(params):
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))


def _eqn_flops(eqn):
    """Structural FLOPs of one jaxpr equation: dot_general/conv as 2*M*N*K,
    everything else as output size (elementwise model)."""
    prim = eqn.primitive.name
    out_size = sum(int(np.prod(v.aval.shape)) for v in eqn.outvars if hasattr(v.aval, "shape"))
    if prim == "dot_general":
        a = eqn.invars[0].aval
        dnums = eqn.params["dimension_numbers"]
        contract = dnums[0][0]
        k = int(np.prod([a.shape[d] for d in contract])) if contract else 1
        return 2 * out_size * k
    if prim == "conv_general_dilated":
        rhs = eqn.invars[1].aval
        return 2 * out_size * int(np.prod(rhs.shape[:-1]))
    return out_size


def _join_scope(prefix, ns):
    if prefix and ns:
        return f"{prefix}/{ns}"
    return prefix or ns


def _walk_eqns(jaxpr, prefix="", mult=1):
    """Yield ``(module_scope, flops)`` for every leaf equation, recursing into
    call primitives (pjit/remat/scan/custom_*). Inner jaxprs lose the outer
    name stack, so the enclosing equation's scope is carried as a prefix;
    scan bodies multiply by trip count."""
    for eqn in jaxpr.eqns:
        ns = str(getattr(eqn.source_info, "name_stack", "") or "")
        scope = _join_scope(prefix, ns)
        params = eqn.params or {}
        inner = params.get("jaxpr") or params.get("call_jaxpr")
        if inner is not None:
            m = mult * int(params.get("length", 1)) if eqn.primitive.name == "scan" else mult
            yield from _walk_eqns(getattr(inner, "jaxpr", inner), scope, m)
            continue
        yield scope, mult * _eqn_flops(eqn)


def _jaxpr_flops(jaxpr, *avals):
    """Structural FLOP count of a whole jaxpr (module-blind total)."""
    return sum(f for _, f in _walk_eqns(jaxpr))


def _params_by_scope(params, root):
    """Parameter counts keyed by the same scope paths the jaxpr walk yields:
    ``root/<tree keys minus the collection dict and the leaf name>``."""
    acc = {}
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    for path, leaf in flat:
        keys = [str(getattr(k, "key", getattr(k, "idx", k))) for k in path]
        if keys and keys[0] in ("params", "batch_stats", "cache"):
            keys = keys[1:]
        scope = "/".join(([root] if root else []) + keys[:-1])
        if scope:
            acc[scope] = acc.get(scope, 0) + int(leaf.size)
    return acc


class FlopsProfiler:
    """Profile a jitted step function (or an engine's forward).

    Usage parity with the reference: ``start_profile()`` / ``stop_profile()``
    bracket a step; getters expose flops/macs/params/duration;
    ``print_model_profile()`` emits the report. The model argument is a
    callable + example args instead of an nn.Module.
    """

    def __init__(self, model=None, example_args=None):
        self.model = model
        self.example_args = example_args
        self.started = False
        self.flops = 0
        self.params = 0
        self.t_start = None
        self.duration = 0.0
        self.module_flops = {}   # exact scope -> flops of eqns at that scope
        self.module_params = {}  # exact scope -> params owned by that scope

    # -- static analysis ---------------------------------------------------
    def analyze(self, fn, *args):
        """FLOPs of one call of ``fn(*args)`` from XLA's own cost model."""
        lowered = jax.jit(fn).lower(*args)
        flops = None
        try:
            cost = lowered.compile().cost_analysis()
            if cost:
                c = cost[0] if isinstance(cost, (list, tuple)) else cost
                flops = c.get("flops")
        except Exception:
            flops = None
        if not flops or not np.isfinite(flops):
            jaxpr = jax.make_jaxpr(fn)(*args)
            flops = _jaxpr_flops(jaxpr.jaxpr)
        return int(flops)

    def analyze_modules(self, fn, *args, params=None):
        """Per-module MACs/params attribution of one ``fn(*args)`` call.

        Walks the traced jaxpr and buckets each equation's FLOPs by its flax
        ``named_scope`` path (the reference gets the same table from forward
        hooks, profiler.py:174-297). ``params`` (a pytree) additionally maps
        parameter counts onto the same scopes."""
        jaxpr = jax.make_jaxpr(fn)(*args)
        acc = {}
        for scope, f in _walk_eqns(jaxpr.jaxpr):
            acc[scope] = acc.get(scope, 0) + f
        self.module_flops = acc
        if params is not None:
            root = self._root_scope() or ""
            self.module_params = _params_by_scope(params, root)
        else:
            self.module_params = {}
        return acc

    def _root_scope(self):
        """Common first path segment of the traced scopes (the model name)."""
        roots = {s.split("/", 1)[0] for s in self.module_flops if s}
        return roots.pop() if len(roots) == 1 else None

    # -- step profiling (reference start/stop/print cycle) ----------------
    def start_profile(self, ignore_list=None):
        self.started = True
        self.t_start = time.perf_counter()

    def stop_profile(self):
        if self.t_start is not None:
            self.duration = time.perf_counter() - self.t_start
        self.started = False

    def reset_profile(self):
        self.flops = 0
        self.duration = 0.0
        self.t_start = None
        self.module_flops = {}
        self.module_params = {}

    def end_profile(self):
        self.reset_profile()

    def get_total_flops(self, as_string=False):
        return flops_to_string(self.flops) if as_string else self.flops

    def get_total_macs(self, as_string=False):
        macs = self.flops // 2
        return macs_to_string(macs) if as_string else macs

    def get_total_duration(self, as_string=False):
        return duration_to_string(self.duration) if as_string else self.duration

    def get_total_params(self, as_string=False):
        return params_to_string(self.params) if as_string else self.params

    def set_flops(self, flops):
        self.flops = int(flops)

    def set_params(self, params_tree):
        self.params = _count_params(params_tree)

    def achieved_tflops(self):
        """Model TFLOPs/s of the profiled step (flops / wall duration), or
        None before a profile completes."""
        if not self.flops or self.duration <= 0:
            return None
        return self.flops / self.duration / 1e12

    def mfu(self, device_kind=None):
        """Model FLOPs utilization vs the device's peak, or None when the
        peak is unknown (CPU, unrecognized accelerator)."""
        achieved = self.achieved_tflops()
        if achieved is None:
            return None
        if device_kind is None:
            device_kind = jax.devices()[0].device_kind
        peak = device_peak_tflops(device_kind)
        return achieved / peak if peak else None

    def _inclusive_tree(self):
        """Inclusive per-scope totals: every scope accumulates its subtree
        (the reference's ``accumulate_flops`` over module children)."""
        inc_f, inc_p = {}, {}
        for acc, inc in ((self.module_flops, inc_f), (self.module_params, inc_p)):
            for scope, v in acc.items():
                parts = [p for p in scope.split("/") if p]
                for d in range(1, len(parts) + 1):
                    key = "/".join(parts[:d])
                    inc[key] = inc.get(key, 0) + v
        return inc_f, inc_p

    def print_model_profile(self, profile_step=None, module_depth=-1, top_modules=3,
                            detailed=True, output_file=None):
        lines = [
            "-------------------------- DeepSpeed Flops Profiler --------------------------",
            f"Profile step:                   {profile_step}",
            f"Params:                         {self.get_total_params(as_string=True)}",
            f"FLOPs per step:                 {self.get_total_flops(as_string=True)}",
            f"MACs per step:                  {self.get_total_macs(as_string=True)}",
            f"Step latency:                   {self.get_total_duration(as_string=True)}",
        ]
        if self.duration > 0 and self.flops:
            lines.append(f"Achieved FLOPS:                 {flops_to_string(self.flops / self.duration)}/s")

        inc_f, inc_p = self._inclusive_tree()
        if inc_f:
            total_f = max(sum(self.module_flops.values()), 1)
            total_p = max(sum(self.module_params.values()), 1) if self.module_params else None
            lines += self._aggregated_lines(inc_f, inc_p, module_depth, top_modules)
            if detailed:
                # Reference prints the module graph with per-module annotations
                # (profiler.py:174-230). Latency is MODELED as the MACs share
                # of the measured step — XLA fuses the program, so per-module
                # wall time does not exist as a measurable quantity.
                lines.append("")
                lines.append("per-module profile (latency modeled as MACs share of the step):")
                for scope in sorted(inc_f):
                    parts = scope.split("/")
                    f = inc_f[scope]
                    items = [
                        macs_to_string(f // 2),
                        f"{f / total_f:.2%} MACs",
                    ]
                    if total_p is not None:
                        p = inc_p.get(scope, 0)
                        items = [params_to_string(p), f"{p / total_p:.2%} Params"] + items
                    if self.duration > 0:
                        items.append(duration_to_string(self.duration * f / total_f))
                    lines.append("  " * len(parts) + f"{parts[-1]}: " + ", ".join(items))
                unattr = self.module_flops.get("", 0)
                if unattr:
                    # eqns outside any flax scope (loss math, dtype casts);
                    # printed so the per-module shares visibly sum to 100%
                    lines.append(
                        f"  (outside modules): {macs_to_string(unattr // 2)}, "
                        f"{unattr / total_f:.2%} MACs"
                    )
        lines.append("-" * 79)
        report = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as f:
                f.write(report)
        else:
            logger.info("\n" + report)
        return report

    def _aggregated_lines(self, inc_f, inc_p, module_depth, top_modules):
        """Reference ``print_model_aggregated_profile`` (profiler.py:232-297):
        top-k module CLASSES by MACs/params at a given depth (depth -1 = the
        innermost level). Flax default instance names are ``Class_idx`` — the
        trailing index is stripped to aggregate by class."""
        by_depth = {}
        for scope, f in inc_f.items():
            parts = scope.split("/")
            d = len(parts) - 1
            cls = re.sub(r"_\d+$", "", parts[-1])
            ent = by_depth.setdefault(d, {}).setdefault(cls, [0, 0])
            ent[0] += f
            ent[1] += (inc_p or {}).get(scope, 0)
        if not by_depth:
            return []
        depth = module_depth if module_depth >= 0 else max(by_depth)
        depth = min(depth, max(by_depth))
        info = by_depth.get(depth, {})
        k = min(top_modules, len(info))
        top_macs = {c: macs_to_string(v[0] // 2) for c, v in
                    sorted(info.items(), key=lambda kv: kv[1][0], reverse=True)[:k]}
        lines = [f"Top {k} modules in MACs at depth {depth}: {top_macs}"]
        if inc_p:
            top_params = {c: params_to_string(v[1]) for c, v in
                          sorted(info.items(), key=lambda kv: kv[1][1], reverse=True)[:k]}
            lines.append(f"Top {k} modules in params at depth {depth}: {top_params}")
        return lines

    def print_aggregated_profile(self, module_depth=-1, top_modules=3):
        # aggregate-only view (reference print_model_aggregated_profile)
        self.print_model_profile(module_depth=module_depth, top_modules=top_modules,
                                 detailed=False)


def get_model_profile(model, args=(), kwargs=None, print_profile=True, detailed=True,
                      module_depth=-1, top_modules=3, warm_up=1, as_string=True,
                      output_file=None, ignore_modules=None):
    """One-shot: measure (flops, macs, params) of a model callable
    (reference get_model_profile)."""
    kwargs = kwargs or {}
    prof = FlopsProfiler(model)
    fn = model.apply if hasattr(model, "apply") else model
    flops = prof.analyze(lambda *a: fn(*a, **kwargs), *args)
    prof.set_flops(flops)
    params_tree = args[0] if args and hasattr(args[0], "keys") else None
    if print_profile:
        # the per-module table costs an extra trace; skip it when nothing
        # will be printed (callers then only consume the totals)
        prof.analyze_modules(lambda *a: fn(*a, **kwargs), *args, params=params_tree)
    if params_tree is not None:
        prof.set_params(params_tree)
    if print_profile:
        prof.print_model_profile(output_file=output_file)
    macs = flops // 2
    if as_string:
        return flops_to_string(flops), macs_to_string(macs), params_to_string(prof.params)
    return flops, macs, prof.params


# -- formatting helpers (reference exposes the same names) -----------------

def _si(value, units, scale=1000.0, precision=2):
    for u in units:
        if abs(value) < scale:
            return f"{value:.{precision}f} {u}"
        value /= scale
    return f"{value:.{precision}f} {units[-1]}" if units else str(value)


def flops_to_string(flops, units=None, precision=2):
    return _si(float(flops), ["FLOPS", "KFLOPS", "MFLOPS", "GFLOPS", "TFLOPS", "PFLOPS"], precision=precision)


def macs_to_string(macs, units=None, precision=2):
    return _si(float(macs), ["MACs", "KMACs", "MMACs", "GMACs", "TMACs"], precision=precision)


def params_to_string(params_num, units=None, precision=2):
    return _si(float(params_num), ["", "k", "M", "G"], precision=precision).strip()


def duration_to_string(duration, units=None, precision=2):
    if duration > 1:
        return f"{duration:.{precision}f} s"
    if duration > 1e-3:
        return f"{duration * 1e3:.{precision}f} ms"
    return f"{duration * 1e6:.{precision}f} us"
