"""Runtime compile/transfer sentinels — jaxlint's dynamic counterpart.

Static analysis (tools/jaxlint) catches hazards the AST can prove;
these two catch the ones only the runtime can see:

- ``CompileSentinel`` wraps a jitted callable and fails loudly when it
  compiles more programs than its budget. Replaces the hand-rolled
  ``fn._cache_size()`` pins the serving/generation tests used — the
  cache-size read lives HERE, in one sanctioned place, instead of being
  copy-pasted into every test that wants a recompile guarantee.
- ``transfer_free()`` is a context manager over ``jax.transfer_guard``
  asserting a region performs no implicit host<->device transfers
  (numpy arrays silently fed into jit, ``float()``/``.item()`` on
  device values). Explicit ``jax.device_put``/``jax.device_get`` remain
  allowed — the point is that every transfer in a hot region must be a
  visible, deliberate one.

Both are usable straight from tests and, under the ``jax_sentinels``
config block (profiling/config.py), from the engines themselves.
"""

import threading
from contextlib import contextmanager

import jax

from deepspeed_tpu import telemetry

__all__ = [
    "CompileBudgetExceededError",
    "CompileSentinel",
    "allowed_transfer",
    "allowed_transfer_names",
    "compile_cache_size",
    "register_allowed_transfer",
    "transfer_free",
]

# Named transfer allowlist: the only sanctioned escape hatch from a
# transfer_free() region. Subsystems that MUST page data host<->device in a
# hot path (ZeRO-Offload's grad/param streams) register a name at import
# time; the region that performs the traffic opens allowed_transfer(name).
# An unregistered name raises — traffic can never go implicit by typo, and
# the registry is greppable documentation of every deliberate paging site.
_ALLOWED_TRANSFERS = set()
_ALLOWED_TRANSFERS_LOCK = threading.Lock()


def register_allowed_transfer(name):
    """Register ``name`` as a sanctioned transfer site (idempotent).

    Returns the name so call sites can do
    ``_H2D = register_allowed_transfer("zero/offload_h2d")``."""
    if not isinstance(name, str) or not name:
        raise ValueError(f"transfer allowlist name must be a non-empty str, got {name!r}")
    with _ALLOWED_TRANSFERS_LOCK:
        _ALLOWED_TRANSFERS.add(name)
    return name


def allowed_transfer_names():
    """Snapshot of the registered allowlist (for tests/telemetry)."""
    with _ALLOWED_TRANSFERS_LOCK:
        return frozenset(_ALLOWED_TRANSFERS)


@contextmanager
def allowed_transfer(name):
    """Open a sanctioned transfer window inside a ``transfer_free()`` region.

    ``name`` must have been registered with ``register_allowed_transfer`` —
    an unknown name raises KeyError instead of silently allowing traffic.
    The guard level is thread-local (jax.transfer_guard), so a background
    host worker opening its own window never loosens the training thread's.
    """
    with _ALLOWED_TRANSFERS_LOCK:
        known = name in _ALLOWED_TRANSFERS
    if not known:
        raise KeyError(
            f"transfer site {name!r} is not on the allowlist — call "
            f"register_allowed_transfer({name!r}) at import time of the "
            f"subsystem that owns this traffic (registered: "
            f"{sorted(_ALLOWED_TRANSFERS)})")
    with jax.transfer_guard("allow"):
        yield


class CompileBudgetExceededError(RuntimeError):
    """A CompileSentinel-wrapped function compiled past its budget."""

    def __init__(self, name, compiles, budget):
        self.name = name
        self.compiles = compiles
        self.budget = budget
        super().__init__(
            f"'{name}' compiled {compiles} program(s), budget is {budget} — "
            f"an operand that should be traced is varying statically "
            f"(shape, dtype, static_argnums value, or python structure). "
            f"Run tools/jaxlint for the static view of likely causes.")


def compile_cache_size(fn):
    """Compiled-program count of a jitted callable (its jit cache size).

    The single sanctioned accessor for the private ``_cache_size`` hook;
    raises TypeError for callables that don't expose one (plain python
    functions, closures over jit)."""
    cache_size = getattr(fn, "_cache_size", None)
    if cache_size is None:
        raise TypeError(
            f"{getattr(fn, '__name__', fn)!r} exposes no jit cache "
            f"(_cache_size) — pass the jax.jit-wrapped callable itself")
    return cache_size()


class CompileSentinel:
    """Budgeted recompile watchdog around one jitted callable.

    Counts compiles as cache-size deltas since construction (or the last
    ``reset()``), so a warm cache never charges the budget. Use it three
    ways: call through it (`sentinel(*args)` — raises the moment the
    budget is exceeded), assert at the end of a scenario
    (``sentinel.check()``), or just read ``sentinel.compiles``.

    Thread-safe to call through (the serving engine drives it from a
    background loop thread); the budget check itself is read-only."""

    def __init__(self, fn, budget, name=None):
        if budget < 0:
            raise ValueError(f"budget must be >= 0, got {budget}")
        compile_cache_size(fn)     # validate up front, not at first call
        self._fn = fn
        self.budget = int(budget)
        self.name = name or getattr(fn, "__name__", "jitted function")
        self._lock = threading.Lock()
        self._baseline = compile_cache_size(fn)
        self._last_seen = 0

    @property
    def compiles(self):
        """New programs compiled since construction / last reset()."""
        return max(0, compile_cache_size(self._fn) - self._baseline)

    def check(self):
        """Raise CompileBudgetExceededError past the budget; returns the
        current compile count otherwise (handy for asserts)."""
        compiles = self.compiles
        if compiles > self._last_seen:
            telemetry.instant(
                "jax/recompile", cat="lifecycle",
                args={"name": self.name, "compiles": compiles,
                      "budget": self.budget})
            # registry counterpart of the instant: an SLO rule like
            # {"metric": "Jax/recompiles_total", "max": N} turns the
            # sentinel budget into a fleet-visible alert
            telemetry.get_registry().counter(
                "Jax/recompiles_total",
                help="recompiles observed by CompileSentinel.check").inc(
                compiles - self._last_seen)
            self._last_seen = compiles
        if compiles > self.budget:
            raise CompileBudgetExceededError(self.name, compiles, self.budget)
        return compiles

    def reset(self, budget=None):
        """Forgive past compiles (e.g. after an intentional reshape) and
        optionally move the budget."""
        with self._lock:
            self._baseline = compile_cache_size(self._fn)
            self._last_seen = 0
            if budget is not None:
                if budget < 0:
                    raise ValueError(f"budget must be >= 0, got {budget}")
                self.budget = int(budget)

    def __call__(self, *args, **kwargs):
        out = self._fn(*args, **kwargs)
        self.check()
        return out

    def __getattr__(self, item):
        # transparent proxy: engines stash sentinels where jitted fns
        # lived, so pytree/cache introspection must keep working
        return getattr(self._fn, item)

    def __repr__(self):
        return (f"CompileSentinel({self.name!r}, compiles={self.compiles}, "
                f"budget={self.budget})")


@contextmanager
def transfer_free(level="disallow"):
    """Assert a region performs no implicit host<->device transfers.

    ``level`` is a ``jax.transfer_guard`` level; the default
    ``"disallow"`` raises on *implicit* transfers — a numpy array fed
    straight into a jitted call, ``float()``/``int()``/``.item()`` on a
    device value — while explicit ``jax.device_put``/``device_get``
    stay allowed. That is exactly the steady-state contract of a hot
    loop: transfers are fine, *accidental* ones are not. Pass
    ``"disallow_explicit"`` to forbid host->device entirely.

    Platform note (pinned in tests/unit/test_sentinels.py): on the CPU
    backend device->host reads are zero-copy and never trip the guard,
    but numpy-into-jit and scalar coercions do — so CPU CI still
    catches the dominant hazard class, and the same region is strictly
    checked on TPU where every direction is a real copy."""
    with jax.transfer_guard(level):
        yield
