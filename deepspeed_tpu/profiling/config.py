"""FLOPS-profiler sub-config (parity: reference ``deepspeed/profiling/config.py``)."""

from deepspeed_tpu.runtime.config_utils import get_scalar_param

FLOPS_PROFILER = "flops_profiler"

FLOPS_PROFILER_ENABLED = "enabled"
FLOPS_PROFILER_ENABLED_DEFAULT = False

FLOPS_PROFILER_PROFILE_STEP = "profile_step"
FLOPS_PROFILER_PROFILE_STEP_DEFAULT = 1

FLOPS_PROFILER_MODULE_DEPTH = "module_depth"
FLOPS_PROFILER_MODULE_DEPTH_DEFAULT = -1

FLOPS_PROFILER_TOP_MODULES = "top_modules"
FLOPS_PROFILER_TOP_MODULES_DEFAULT = 3

FLOPS_PROFILER_DETAILED = "detailed"
FLOPS_PROFILER_DETAILED_DEFAULT = True


class DeepSpeedFlopsProfilerConfig:
    def __init__(self, param_dict):
        prof_dict = param_dict.get(FLOPS_PROFILER, {})
        self.enabled = get_scalar_param(prof_dict, FLOPS_PROFILER_ENABLED, FLOPS_PROFILER_ENABLED_DEFAULT)
        self.profile_step = get_scalar_param(prof_dict, FLOPS_PROFILER_PROFILE_STEP, FLOPS_PROFILER_PROFILE_STEP_DEFAULT)
        self.module_depth = get_scalar_param(prof_dict, FLOPS_PROFILER_MODULE_DEPTH, FLOPS_PROFILER_MODULE_DEPTH_DEFAULT)
        self.top_modules = get_scalar_param(prof_dict, FLOPS_PROFILER_TOP_MODULES, FLOPS_PROFILER_TOP_MODULES_DEFAULT)
        self.detailed = get_scalar_param(prof_dict, FLOPS_PROFILER_DETAILED, FLOPS_PROFILER_DETAILED_DEFAULT)

    def repr(self):
        return self.__dict__
