"""FLOPS-profiler sub-config (parity: reference ``deepspeed/profiling/config.py``)."""

from deepspeed_tpu.runtime.config_utils import get_scalar_param

FLOPS_PROFILER = "flops_profiler"

FLOPS_PROFILER_ENABLED = "enabled"
FLOPS_PROFILER_ENABLED_DEFAULT = False

FLOPS_PROFILER_PROFILE_STEP = "profile_step"
FLOPS_PROFILER_PROFILE_STEP_DEFAULT = 1

FLOPS_PROFILER_MODULE_DEPTH = "module_depth"
FLOPS_PROFILER_MODULE_DEPTH_DEFAULT = -1

FLOPS_PROFILER_TOP_MODULES = "top_modules"
FLOPS_PROFILER_TOP_MODULES_DEFAULT = 3

FLOPS_PROFILER_DETAILED = "detailed"
FLOPS_PROFILER_DETAILED_DEFAULT = True


JAX_SENTINELS = "jax_sentinels"

JAX_SENTINELS_ENABLED = "enabled"
JAX_SENTINELS_ENABLED_DEFAULT = False

# Compiled programs a sentinel-wrapped hot function may accumulate before
# CompileSentinel raises. >=1: the first trace is always charged.
JAX_SENTINELS_COMPILE_BUDGET = "compile_budget"
JAX_SENTINELS_COMPILE_BUDGET_DEFAULT = 4

# Wrap hot-loop dispatch in transfer_free() (jax.transfer_guard) so any
# implicit host<->device transfer raises instead of silently stalling.
JAX_SENTINELS_TRANSFER_GUARD = "transfer_guard"
JAX_SENTINELS_TRANSFER_GUARD_DEFAULT = False


class DeepSpeedSentinelConfig:
    """``jax_sentinels`` block: runtime compile/transfer watchdogs.

    Static hazards are jaxlint's job (tools/jaxlint); this block arms the
    dynamic side — CompileSentinel budgets on the engines' jitted hot
    functions and, optionally, a transfer guard around their dispatch.
    """

    def __init__(self, param_dict):
        sent_dict = param_dict.get(JAX_SENTINELS, {})
        if not isinstance(sent_dict, dict):
            raise ValueError(f"'{JAX_SENTINELS}' must be a dict, got {type(sent_dict).__name__}")
        self.enabled = get_scalar_param(sent_dict, JAX_SENTINELS_ENABLED, JAX_SENTINELS_ENABLED_DEFAULT)
        self.compile_budget = get_scalar_param(sent_dict, JAX_SENTINELS_COMPILE_BUDGET,
                                               JAX_SENTINELS_COMPILE_BUDGET_DEFAULT)
        self.transfer_guard = get_scalar_param(sent_dict, JAX_SENTINELS_TRANSFER_GUARD,
                                               JAX_SENTINELS_TRANSFER_GUARD_DEFAULT)
        if not isinstance(self.compile_budget, int) or isinstance(self.compile_budget, bool) \
                or self.compile_budget < 1:
            raise ValueError(
                f"'{JAX_SENTINELS}.{JAX_SENTINELS_COMPILE_BUDGET}' must be an int >= 1, "
                f"got {self.compile_budget!r}")

    def repr(self):
        return self.__dict__


class DeepSpeedFlopsProfilerConfig:
    def __init__(self, param_dict):
        prof_dict = param_dict.get(FLOPS_PROFILER, {})
        self.enabled = get_scalar_param(prof_dict, FLOPS_PROFILER_ENABLED, FLOPS_PROFILER_ENABLED_DEFAULT)
        self.profile_step = get_scalar_param(prof_dict, FLOPS_PROFILER_PROFILE_STEP, FLOPS_PROFILER_PROFILE_STEP_DEFAULT)
        self.module_depth = get_scalar_param(prof_dict, FLOPS_PROFILER_MODULE_DEPTH, FLOPS_PROFILER_MODULE_DEPTH_DEFAULT)
        self.top_modules = get_scalar_param(prof_dict, FLOPS_PROFILER_TOP_MODULES, FLOPS_PROFILER_TOP_MODULES_DEFAULT)
        self.detailed = get_scalar_param(prof_dict, FLOPS_PROFILER_DETAILED, FLOPS_PROFILER_DETAILED_DEFAULT)

    def repr(self):
        return self.__dict__
