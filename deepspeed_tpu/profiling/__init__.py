from deepspeed_tpu.profiling.sentinels import (
    CompileBudgetExceededError,
    CompileSentinel,
    allowed_transfer,
    allowed_transfer_names,
    compile_cache_size,
    register_allowed_transfer,
    transfer_free,
)

__all__ = [
    "CompileBudgetExceededError",
    "CompileSentinel",
    "allowed_transfer",
    "allowed_transfer_names",
    "compile_cache_size",
    "register_allowed_transfer",
    "transfer_free",
]
