from deepspeed_tpu.profiling.sentinels import (
    CompileBudgetExceededError,
    CompileSentinel,
    compile_cache_size,
    transfer_free,
)

__all__ = [
    "CompileBudgetExceededError",
    "CompileSentinel",
    "compile_cache_size",
    "transfer_free",
]
