"""Environment report CLI (parity: reference ``deepspeed/env_report.py`` +
``bin/ds_report``): op install/compatibility matrix plus jax/TPU topology info
in place of torch/cuda/nvcc versions."""

from deepspeed_tpu.ops.op_builder import op_report
from deepspeed_tpu.version import __version__

GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"


def debug_report():
    lines = []
    lines.append("-" * 60)
    lines.append("DeepSpeedTPU C++ op report")
    lines.append("-" * 60)
    lines.append(op_report())
    lines.append("-" * 60)
    lines.append("DeepSpeedTPU general environment info:")
    lines.append("-" * 60)
    lines.append(f"deepspeed_tpu version ......... {__version__}")
    try:
        import jax

        lines.append(f"jax version ................... {jax.__version__}")
        try:
            devices = jax.devices()
            lines.append(f"jax backend ................... {devices[0].platform if devices else 'none'}")
            lines.append(f"device count .................. {len(devices)}")
            lines.append(f"process count ................. {jax.process_count()}")
            for d in devices[:8]:
                lines.append(f"  device ...................... {d}")
        except Exception as e:
            lines.append(f"devices ....................... unavailable ({e})")
    except ImportError:
        lines.append("jax ........................... NOT INSTALLED")
    try:
        import flax

        lines.append(f"flax version .................. {flax.__version__}")
    except ImportError:
        lines.append("flax .......................... NOT INSTALLED")
    import shutil

    lines.append(f"g++ ........................... {'found' if shutil.which('g++') else 'MISSING'}")
    return "\n".join(lines)


def main():
    print(debug_report())


if __name__ == "__main__":
    main()
