"""Per-node launcher.

Capability parity with the reference's ``deepspeed/launcher/launch.py``
(``main:65``: decode world info, compute global rank mapping, set
``CUDA_VISIBLE_DEVICES``/``MASTER_*``/``RANK``/``LOCAL_RANK``, spawn one
process per local rank) — adapted to the TPU process model: ONE process per
host drives all local chips (jax single-controller-per-host), so this sets
``RANK`` = node rank, ``WORLD_SIZE`` = number of hosts, exports
``MASTER_ADDR/PORT`` for ``jax.distributed``, restricts visible chips when a
slot subset was requested, and execs the user script.
"""

import argparse
import os
import signal
import subprocess
import sys

from deepspeed_tpu.launcher.runner import decode_world_info
from deepspeed_tpu.utils.logging import logger


def parse_args():
    parser = argparse.ArgumentParser()
    parser.add_argument("--world_info", default="e30=", type=str,
                        help="base64-encoded world layout dictionary")
    parser.add_argument("--node_rank", default=0, type=str,
                        help="Rank of this node in the job, or 'MPI'/'OMPI' to read it "
                             "from the MPI launcher env (OpenMPI/MVAPICH2/PMI)")
    parser.add_argument("--master_addr", default="127.0.0.1", type=str)
    parser.add_argument("--master_port", default=29500, type=int)
    parser.add_argument("training_script", type=str)
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return parser.parse_args()


def mpi_node_rank():
    """Generic MPI rank discovery: OpenMPI, MVAPICH2, or PMI launchers."""
    return int(
        os.environ.get("OMPI_COMM_WORLD_RANK")
        or os.environ.get("MV2_COMM_WORLD_RANK")
        or os.environ.get("PMI_RANK")
        or "0"
    )


def main():
    args = parse_args()
    world_info = decode_world_info(args.world_info)
    assert len(world_info) > 0, "got no world info"

    if args.node_rank in ("OMPI", "MPI"):
        node_rank = mpi_node_rank()
    else:
        node_rank = int(args.node_rank)

    hosts = list(world_info.keys())
    num_nodes = len(hosts)
    this_host = hosts[node_rank]
    local_slots = world_info[this_host]

    current_env = os.environ.copy()
    current_env["MASTER_ADDR"] = args.master_addr
    current_env["MASTER_PORT"] = str(args.master_port)
    current_env["WORLD_SIZE"] = str(num_nodes)
    current_env["RANK"] = str(node_rank)
    current_env["LOCAL_RANK"] = "0"
    current_env["NODE_RANK"] = str(node_rank)
    if local_slots:
        # Restrict visible TPU chips (TPU_VISIBLE_CHIPS is the libtpu analogue
        # of CUDA_VISIBLE_DEVICES).
        current_env["TPU_VISIBLE_CHIPS"] = ",".join(map(str, local_slots))

    logger.info(
        f"launch: node_rank={node_rank}/{num_nodes} host={this_host} "
        f"slots={local_slots} master={args.master_addr}:{args.master_port}"
    )

    cmd = [sys.executable, "-u", args.training_script] + args.training_script_args
    process = subprocess.Popen(cmd, env=current_env)

    def sig_handler(signum, frame):
        process.terminate()
        sys.exit(1)

    signal.signal(signal.SIGTERM, sig_handler)
    process.wait()
    if process.returncode != 0:
        raise subprocess.CalledProcessError(returncode=process.returncode, cmd=cmd)


if __name__ == "__main__":
    main()
