"""Per-node launcher.

Capability parity with the reference's ``deepspeed/launcher/launch.py``
(``main:65``: decode world info, compute global rank mapping, set
``CUDA_VISIBLE_DEVICES``/``MASTER_*``/``RANK``/``LOCAL_RANK``, spawn one
process per local rank) — adapted to the TPU process model: ONE process per
host drives all local chips (jax single-controller-per-host), so this sets
``RANK`` = node rank, ``WORLD_SIZE`` = number of hosts, exports
``MASTER_ADDR/PORT`` for ``jax.distributed``, restricts visible chips when a
slot subset was requested, and execs the user script.

The child runs under ``WorkerSupervisor`` (launcher/supervisor.py): SIGTERM
*and* SIGINT are forwarded with terminate→wait→kill escalation, the child's
actual exit code is propagated, and — with ``--max_restarts`` — preempted or
crashed workers are restarted with heartbeat liveness monitoring and
exponential backoff (see docs/cluster_resilience.md for the exit-code
contract).
"""

import argparse
import os
import sys

from deepspeed_tpu.launcher.runner import decode_world_info
from deepspeed_tpu.launcher.supervisor import WorkerSupervisor
from deepspeed_tpu.utils.logging import logger


def parse_args():
    parser = argparse.ArgumentParser()
    parser.add_argument("--world_info", default="e30=", type=str,
                        help="base64-encoded world layout dictionary")
    parser.add_argument("--node_rank", default=0, type=str,
                        help="Rank of this node in the job, or 'MPI'/'OMPI' to read it "
                             "from the MPI launcher env (OpenMPI/MVAPICH2/PMI)")
    parser.add_argument("--master_addr", default="127.0.0.1", type=str)
    parser.add_argument("--master_port", default=29500, type=int)
    parser.add_argument("--max_restarts", default=0, type=int,
                        help="Restart budget for crashed/preempted/hung workers "
                             "(0 = run once, the old behavior)")
    parser.add_argument("--restart_backoff_s", default=1.0, type=float,
                        help="Base of the exponential backoff between crash restarts")
    parser.add_argument("--heartbeat_timeout_s", default=0.0, type=float,
                        help="Kill and restart a worker whose step heartbeat goes "
                             "stale for this long (0 = no liveness monitoring; must "
                             "exceed first-step compile time)")
    parser.add_argument("--telemetry_port", default=None, type=int,
                        help="Serve /healthz, /metrics, /snapshot and /trace from "
                             "the supervisor on this port (0 = ephemeral; omit to "
                             "disable)")
    parser.add_argument("--worker_telemetry_port", default=None, type=int,
                        help="Fixed port for the WORKER's telemetry endpoint "
                             "(exported as DSTPU_TELEMETRY_PORT; survives "
                             "restarts so the fleet collector can keep scraping)")
    parser.add_argument("--replica_port", default=None, type=int,
                        help="Fixed port for a SERVING replica's request "
                             "socket (exported as DSTPU_REPLICA_PORT; "
                             "survives restarts so a fleet router's "
                             "endpoint list never goes stale)")
    parser.add_argument("--replica_config", default=None, type=str,
                        help="Replica config JSON path (exported as "
                             "DSTPU_REPLICA_CONFIG for "
                             "inference/serving/replica.py workers)")
    parser.add_argument("--collector_port", default=None, type=int,
                        help="Run a FleetCollector next to the supervisor, "
                             "serving /fleet/metrics, /fleet/trace and "
                             "/fleet/snapshot on this port (0 = ephemeral). "
                             "Scrapes the worker endpoint (requires "
                             "--worker_telemetry_port) and merges the "
                             "supervisor's own restart instants")
    parser.add_argument("training_script", type=str)
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return parser.parse_args()


def mpi_node_rank():
    """Generic MPI rank discovery: OpenMPI, MVAPICH2, or PMI launchers."""
    return int(
        os.environ.get("OMPI_COMM_WORLD_RANK")
        or os.environ.get("MV2_COMM_WORLD_RANK")
        or os.environ.get("PMI_RANK")
        or "0"
    )


def main():
    args = parse_args()
    world_info = decode_world_info(args.world_info)
    assert len(world_info) > 0, "got no world info"

    if args.node_rank in ("OMPI", "MPI"):
        node_rank = mpi_node_rank()
    else:
        node_rank = int(args.node_rank)

    hosts = list(world_info.keys())
    num_nodes = len(hosts)
    if not 0 <= node_rank < num_nodes:
        logger.error(
            f"launch: node_rank {node_rank} is out of range for this world "
            f"layout ({num_nodes} host(s): {hosts}) — check --node_rank / the "
            "MPI rank env against the hostfile"
        )
        sys.exit(2)
    this_host = hosts[node_rank]
    local_slots = world_info[this_host]

    current_env = os.environ.copy()
    current_env["MASTER_ADDR"] = args.master_addr
    current_env["MASTER_PORT"] = str(args.master_port)
    current_env["WORLD_SIZE"] = str(num_nodes)
    current_env["RANK"] = str(node_rank)
    current_env["LOCAL_RANK"] = "0"
    current_env["NODE_RANK"] = str(node_rank)
    if local_slots:
        # Restrict visible TPU chips (TPU_VISIBLE_CHIPS is the libtpu analogue
        # of CUDA_VISIBLE_DEVICES).
        current_env["TPU_VISIBLE_CHIPS"] = ",".join(map(str, local_slots))

    logger.info(
        f"launch: node_rank={node_rank}/{num_nodes} host={this_host} "
        f"slots={local_slots} master={args.master_addr}:{args.master_port} "
        f"max_restarts={args.max_restarts}"
    )

    cmd = [sys.executable, "-u", args.training_script] + args.training_script_args
    supervisor = WorkerSupervisor(
        cmd, env=current_env,
        max_restarts=args.max_restarts,
        backoff_s=args.restart_backoff_s,
        heartbeat_timeout_s=args.heartbeat_timeout_s,
        http_port=args.telemetry_port,
        worker_port=args.worker_telemetry_port,
        replica_port=args.replica_port,
        replica_config=args.replica_config,
        log=lambda msg: logger.warning(f"launch[{node_rank}]: {msg}"),
    )

    collector = None
    if args.collector_port is not None:
        # stdlib-only import chain: the launcher process still never loads jax
        from deepspeed_tpu import telemetry
        from deepspeed_tpu.telemetry import FleetCollector

        collector = FleetCollector()
        if supervisor.worker_endpoint is not None:
            collector.add_endpoint(rank=node_rank,
                                   url=supervisor.worker_endpoint)
        else:
            logger.warning(
                "launch: --collector_port without --worker_telemetry_port: "
                "the collector has no worker endpoint to scrape (serving "
                "supervisor-side telemetry only)")
        # arm the launcher-side tracer so supervisor lifecycle instants
        # (worker/restart, worker/exit) are recorded, then merge them
        # (and the liveness gauges) into the fleet view
        telemetry.configure(True)
        telemetry.get_tracer().set_process_info(rank=-1, role="supervisor")
        supervisor.export_gauges(telemetry.get_registry())
        collector.attach_local(telemetry.get_tracer(), telemetry.get_registry())
        srv = collector.serve(port=args.collector_port)
        logger.info(f"launch: fleet collector at {srv.url}/fleet/metrics")

    try:
        rc = supervisor.run()
    finally:
        if collector is not None:
            collector.stop()
    sys.exit(rc)


if __name__ == "__main__":
    main()
