"""Multi-node launch backends (parity: reference ``deepspeed/launcher/
multinode_runner.py``: PDSHRunner / OpenMPIRunner / MVAPICHRunner). Each
backend materializes a command that runs ``deepspeed_tpu.launcher.launch`` on
every node with its node rank and the encoded world layout."""

import os
import shutil
import sys
from abc import ABC, abstractmethod
from shlex import quote


class MultiNodeRunner(ABC):
    def __init__(self, args, world_info_base64, master_addr, exports=None):
        self.args = args
        self.world_info_base64 = world_info_base64
        self.master_addr = master_addr
        self.exports = exports or {}
        self.user_arguments = args.user_args
        self.user_script = args.user_script

    @abstractmethod
    def backend_exists(self):
        ...

    @abstractmethod
    def get_cmd(self):
        ...

    @property
    def name(self):
        return self.__class__.__name__.lower().replace("runner", "")

    def export_string(self):
        return " ".join(f"export {k}={quote(v)};" for k, v in sorted(self.exports.items()))


class PDSHRunner(MultiNodeRunner):
    def backend_exists(self):
        return shutil.which("pdsh") is not None

    def get_cmd(self):
        import json, base64

        world = json.loads(base64.urlsafe_b64decode(self.world_info_base64))
        hosts = ",".join(world.keys())
        pdsh_cmd = ["pdsh", "-f", "1024", "-w", hosts]
        if self.args.launcher_args:
            pdsh_cmd += self.args.launcher_args.split()

        # %n is pdsh's node-rank substitution; each node learns its rank from it.
        payload = (
            f"{self.export_string()} cd {os.path.abspath('.')}; "
            f"{sys.executable} -u -m deepspeed_tpu.launcher.launch "
            f"--world_info={self.world_info_base64} --node_rank=%n "
            f"--master_addr={self.master_addr} --master_port={self.args.master_port} "
            f"{self.user_script} {' '.join(map(quote, self.user_arguments))}"
        )
        return pdsh_cmd + [payload]


class SSHRunner(MultiNodeRunner):
    """Plain-ssh fallback when pdsh is absent."""

    def backend_exists(self):
        return shutil.which("ssh") is not None

    def get_cmd(self):
        import json, base64

        world = json.loads(base64.urlsafe_b64decode(self.world_info_base64))
        cmds = []
        for rank, host in enumerate(world.keys()):
            payload = (
                f"{self.export_string()} cd {os.path.abspath('.')}; "
                f"{sys.executable} -u -m deepspeed_tpu.launcher.launch "
                f"--world_info={self.world_info_base64} --node_rank={rank} "
                f"--master_addr={self.master_addr} --master_port={self.args.master_port} "
                f"{self.user_script} {' '.join(map(quote, self.user_arguments))}"
            )
            cmds.append(f"ssh {host} {quote(payload)}")
        # run all nodes concurrently, wait for all
        script = " & ".join(cmds) + " & wait"
        return ["bash", "-c", script]


class OpenMPIRunner(MultiNodeRunner):
    def backend_exists(self):
        return shutil.which("mpirun") is not None

    def get_cmd(self):
        import json, base64

        world = json.loads(base64.urlsafe_b64decode(self.world_info_base64))
        total_procs = len(world)  # one process per host (drives all local chips)
        hosts = ",".join(f"{h}:1" for h in world.keys())
        mpirun_cmd = [
            "mpirun", "-n", str(total_procs), "--host", hosts,
            "--mca", "btl", "^openib", "--mca", "btl_tcp_if_include", "eth0",
        ]
        if self.args.launcher_args:
            mpirun_cmd += self.args.launcher_args.split()
        export_cmd = []
        for k, v in self.exports.items():
            export_cmd += ["-x", f"{k}={v}"]
        python_exec = [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
                       f"--world_info={self.world_info_base64}", "--node_rank=OMPI",
                       f"--master_addr={self.master_addr}", f"--master_port={self.args.master_port}"]
        return mpirun_cmd + export_cmd + python_exec + [self.user_script] + list(self.user_arguments)
