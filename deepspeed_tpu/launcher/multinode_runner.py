"""Multi-node launch backends (parity: reference ``deepspeed/launcher/
multinode_runner.py``: PDSHRunner / OpenMPIRunner / MVAPICHRunner). Each
backend materializes a command that runs ``deepspeed_tpu.launcher.launch`` on
every node with its node rank and the encoded world layout."""

import os
import shutil
import sys
import tempfile
from abc import ABC, abstractmethod
from shlex import quote

from deepspeed_tpu.launcher.runner import decode_world_info


class MultiNodeRunner(ABC):
    def __init__(self, args, world_info_base64, master_addr, exports=None):
        self.args = args
        self.world_info_base64 = world_info_base64
        self.master_addr = master_addr
        self.exports = exports or {}
        self.user_arguments = args.user_args
        self.user_script = args.user_script

    @abstractmethod
    def backend_exists(self):
        ...

    @abstractmethod
    def get_cmd(self):
        ...

    @property
    def name(self):
        return self.__class__.__name__.lower().replace("runner", "")

    def export_string(self):
        return " ".join(f"export {k}={quote(v)};" for k, v in sorted(self.exports.items()))

    def cleanup(self):
        """Remove anything ``get_cmd`` materialized on disk (temp hostfiles
        etc.). Called by ``runner.main`` after the launch finishes; the base
        implementation has nothing to clean."""


class PDSHRunner(MultiNodeRunner):
    def backend_exists(self):
        return shutil.which("pdsh") is not None

    def get_cmd(self):
        world = decode_world_info(self.world_info_base64)
        hosts = ",".join(world.keys())
        pdsh_cmd = ["pdsh", "-f", "1024", "-w", hosts]
        if self.args.launcher_args:
            pdsh_cmd += self.args.launcher_args.split()

        # %n is pdsh's node-rank substitution; each node learns its rank from it.
        payload = (
            f"{self.export_string()} cd {os.path.abspath('.')}; "
            f"{sys.executable} -u -m deepspeed_tpu.launcher.launch "
            f"--world_info={self.world_info_base64} --node_rank=%n "
            f"--master_addr={self.master_addr} --master_port={self.args.master_port} "
            f"{self.user_script} {' '.join(map(quote, self.user_arguments))}"
        )
        return pdsh_cmd + [payload]


class SSHRunner(MultiNodeRunner):
    """Plain-ssh fallback when pdsh is absent."""

    def backend_exists(self):
        return shutil.which("ssh") is not None

    def get_cmd(self):
        world = decode_world_info(self.world_info_base64)
        cmds = []
        for rank, host in enumerate(world.keys()):
            payload = (
                f"{self.export_string()} cd {os.path.abspath('.')}; "
                f"{sys.executable} -u -m deepspeed_tpu.launcher.launch "
                f"--world_info={self.world_info_base64} --node_rank={rank} "
                f"--master_addr={self.master_addr} --master_port={self.args.master_port} "
                f"{self.user_script} {' '.join(map(quote, self.user_arguments))}"
            )
            cmds.append(f"ssh {host} {quote(payload)}")
        # Run all nodes concurrently and propagate the FIRST nonzero exit
        # status: a bare `wait` always returns 0, which silently swallowed
        # per-node failures. Collect each background pid and wait on them
        # individually instead.
        script = (
            "pids=(); "
            + " ".join(f"{c} & pids+=($!);" for c in cmds)
            + ' rc=0; for p in "${pids[@]}"; do'
            + ' wait "$p"; s=$?; if [ "$rc" -eq 0 ]; then rc=$s; fi;'
            + ' done; exit "$rc"'
        )
        return ["bash", "-c", script]


class OpenMPIRunner(MultiNodeRunner):
    def backend_exists(self):
        return shutil.which("mpirun") is not None

    def get_cmd(self):
        world = decode_world_info(self.world_info_base64)
        total_procs = len(world)  # one process per host (drives all local chips)
        hosts = ",".join(f"{h}:1" for h in world.keys())
        mpirun_cmd = [
            "mpirun", "-n", str(total_procs), "--host", hosts,
            "--mca", "btl", "^openib", "--mca", "btl_tcp_if_include", "eth0",
        ]
        if self.args.launcher_args:
            mpirun_cmd += self.args.launcher_args.split()
        export_cmd = []
        for k, v in self.exports.items():
            export_cmd += ["-x", f"{k}={v}"]
        python_exec = [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
                       f"--world_info={self.world_info_base64}", "--node_rank=OMPI",
                       f"--master_addr={self.master_addr}", f"--master_port={self.args.master_port}"]
        return mpirun_cmd + export_cmd + python_exec + [self.user_script] + list(self.user_arguments)


class MVAPICHRunner(MultiNodeRunner):
    """MVAPICH2 backend (reference multinode_runner.py:118-177): mpirun over a
    generated plain hostfile with the MV2 tuning environment. TPU adaptation:
    one process per HOST drives all local chips, and the cuda-awareness knobs
    (MV2_USE_CUDA / MV2_CUDA_USE_NAIVE) are dropped — DCN traffic rides
    TCP/IB without GPUDirect."""

    # reference's MV2 deep-learning tuning set, minus the cuda knobs
    MV2_EXPORTS = {
        "MV2_SMP_USE_CMA": "0",
        "MV2_DEBUG_SHOW_BACKTRACE": "1",
        "MV2_SUPPORT_DL": "1",
        "MV2_ENABLE_AFFINITY": "0",
        "MV2_INTER_ALLGATHER_TUNING": "5",
    }

    def backend_exists(self):
        # mvapich installs `mpiname`; its output names the flavor
        if shutil.which("mpiname") is None:
            return False
        import subprocess

        try:
            out = subprocess.check_output(["mpiname"], text=True)
        except Exception:  # noqa: BLE001
            return False
        return "MVAPICH" in out

    _hostfile = None

    def get_cmd(self):
        world = decode_world_info(self.world_info_base64)
        # fresh temp hostfile per invocation: a fixed /tmp path would clobber
        # between concurrent jobs and follow planted symlinks
        fd, hostfile = tempfile.mkstemp(prefix="dstpu_mvapich_hosts_", text=True)
        self._hostfile = hostfile
        with os.fdopen(fd, "w") as f:
            for host in world.keys():
                f.write(f"{host}\n")
        total_procs = len(world)  # one process per host
        mpirun_cmd = [
            "mpirun", "-np", str(total_procs),
            "-hostfile", hostfile,
        ]
        if self.args.launcher_args:
            mpirun_cmd += self.args.launcher_args.split()
        export_cmd = []
        for k, v in {**self.MV2_EXPORTS, **self.exports}.items():
            # Hydra mpiexec takes TWO-token "-env <name> <value>"
            export_cmd += ["-env", k, str(v)]
        python_exec = [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
                       f"--world_info={self.world_info_base64}", "--node_rank=MPI",
                       f"--master_addr={self.master_addr}",
                       f"--master_port={self.args.master_port}"]
        return mpirun_cmd + export_cmd + python_exec + [self.user_script] + list(self.user_arguments)

    def cleanup(self):
        """Remove the generated temp hostfile once the launch is done
        (tolerates an already-removed file)."""
        if self._hostfile is not None:
            try:
                os.unlink(self._hostfile)
            except OSError:
                pass
            self._hostfile = None
