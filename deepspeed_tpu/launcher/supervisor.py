"""Worker supervision for the per-node launcher.

``launch.py`` used to spawn the training process exactly once and raise on
any nonzero exit — on preemptible TPU pods that turns every SIGTERM into a
dead job. ``WorkerSupervisor`` wraps the child with:

- **liveness monitoring** via a heartbeat file the engine touches at every
  optimizer-step boundary (``DSTPU_HEARTBEAT_FILE``): a stale heartbeat
  means the worker is wedged (not just slow — the engine beats even while
  recovering), so the supervisor kills and restarts it;
- **bounded restart with exponential backoff**: crashes restart up to
  ``max_restarts`` times with ``backoff_s * 2^(n-1)`` sleeps (capped);
  a preempted-resumable exit restarts promptly, without backoff;
- **distinct exit classes** (the exit-code contract below): clean exits and
  poisoned-fatal exits never restart; preempted-resumable and crash/hang
  exits do, while the restart budget lasts;
- **signal forwarding**: SIGTERM *and* SIGINT are forwarded to the child
  (so the engine's ``PreemptionHandler`` can commit an emergency
  checkpoint), escalating terminate → ``wait(grace)`` → kill. A signal
  received by the supervisor itself means the *job* is being torn down:
  the child's exit code is propagated and no restart happens.

Exit-code contract (shared with ``runtime/resilience/preemption.py``):

=================  ====  =============================================
``EXIT_CLEAN``     0     training finished; do not restart
``EXIT_POISONED``  98    poisoned/fatal (e.g. unrecoverable divergence);
                         restarting would fail the same way — do not
``EXIT_PREEMPTED`` 99    preemption checkpoint committed; resumable —
                         restart without backoff
other nonzero / signal   crash; restart with exponential backoff
=================  ====  =============================================

This module is stdlib-only on purpose: the supervisor must stay importable
(and restart workers) even when the training stack itself is the thing
crashing. The optional telemetry endpoint (``http_port``) is imported
lazily from ``deepspeed_tpu.telemetry`` — itself stdlib-only — and only
when requested, so the no-telemetry path never touches it.
"""

import os
import signal
import subprocess
import sys
import tempfile
import time

EXIT_CLEAN = 0
EXIT_POISONED = 98
EXIT_PREEMPTED = 99

# Env contract between the supervisor and the engine it supervises.
HEARTBEAT_FILE_ENV = "DSTPU_HEARTBEAT_FILE"
PREEMPTION_ENV = "DSTPU_PREEMPTION"
PREEMPT_SAVE_DIR_ENV = "DSTPU_PREEMPT_SAVE_DIR"
# Worker-side telemetry endpoint port (duplicated in telemetry/config.py —
# neither package may import the other eagerly): a worker whose telemetry
# block leaves http_port null binds this port instead, so the fleet
# collector knows where to scrape it.
TELEMETRY_PORT_ENV = "DSTPU_TELEMETRY_PORT"
# Serving-replica socket port + config path (duplicated in
# inference/serving/replica.py, same no-eager-import rule): a supervised
# serving replica binds this FIXED port so the router's endpoint stays
# valid across restarts — an ephemeral port would move on every recycle.
REPLICA_PORT_ENV = "DSTPU_REPLICA_PORT"
REPLICA_CONFIG_ENV = "DSTPU_REPLICA_CONFIG"

# Exit classes (WorkerSupervisor.exit_history entries).
CLASS_CLEAN = "clean"
CLASS_PREEMPTED = "preempted"
CLASS_FATAL = "fatal"
CLASS_CRASH = "crash"
CLASS_HUNG = "hung"


def classify_exit(returncode, fatal_exit_codes=(EXIT_POISONED,)):
    """Map a child exit code to its supervision class. Signal deaths come
    through as negative returncodes and classify as crashes."""
    if returncode == EXIT_CLEAN:
        return CLASS_CLEAN
    if returncode == EXIT_PREEMPTED:
        return CLASS_PREEMPTED
    if returncode in fatal_exit_codes:
        return CLASS_FATAL
    return CLASS_CRASH


class CrashLoopBreaker:
    """Per-worker crash-loop circuit breaker (the ``fleet.breaker`` block).

    Exponential backoff alone caps restart RATE but still burns the
    restart budget on a worker that dies the same way every time — and a
    serving replica mid-crash-loop keeps a live-looking endpoint the
    router wastes retries on. The breaker adds the missing state:

    - ``closed``: failures accumulate; ``threshold`` failure exits
      (crash/hung — never clean or preempted) inside ``window_s`` OPEN
      the breaker.
    - ``open``: the worker stays down for ``cooldown_s`` (its dead port
      makes the router's health probe fail, so the fleet routes around
      the quarantined endpoint without any extra coordination).
    - ``half_open``: after the cooldown exactly ONE probe restart is
      allowed. The probe failing re-opens with a fresh cooldown; the
      probe exiting clean/preempted closes the breaker.

    Deliberately clock-injectable and supervisor-agnostic so the chaos
    harness and unit tests can drive it through years of simulated
    crash-loops in milliseconds."""

    def __init__(self, threshold=3, window_s=30.0, cooldown_s=5.0,
                 clock=time.monotonic):
        self.threshold = max(1, int(threshold))
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self.state = "closed"
        self.open_count = 0             # times the breaker has opened
        self._failures = []             # failure timestamps inside window
        self._opened_at = 0.0

    @classmethod
    def from_config(cls, cfg, clock=time.monotonic):
        """Build from a ``BreakerConfig``-shaped object or dict; None when
        the block is absent or disabled."""
        if cfg is None or isinstance(cfg, CrashLoopBreaker):
            return cfg
        if isinstance(cfg, dict):
            if not cfg.get("enabled", True):
                return None
            return cls(threshold=cfg.get("threshold", 3),
                       window_s=cfg.get("window_s", 30.0),
                       cooldown_s=cfg.get("cooldown_s", 5.0), clock=clock)
        if not getattr(cfg, "enabled", True):
            return None
        return cls(threshold=getattr(cfg, "threshold", 3),
                   window_s=getattr(cfg, "window_s", 30.0),
                   cooldown_s=getattr(cfg, "cooldown_s", 5.0), clock=clock)

    @property
    def is_open(self):
        return self.state == "open"

    def record_failure(self, now=None):
        """Note one failure exit; returns True when this failure OPENS
        the breaker (the edge the telemetry instant fires on)."""
        now = self._clock() if now is None else now
        if self.state == "half_open":
            # the single probe failed: straight back to quarantine with a
            # fresh cooldown (and a fresh window — the probe IS evidence)
            self.state = "open"
            self._opened_at = now
            self._failures = [now]
            self.open_count += 1
            return True
        self._failures = [t for t in self._failures
                          if now - t <= self.window_s]
        self._failures.append(now)
        if self.state == "closed" and len(self._failures) >= self.threshold:
            self.state = "open"
            self._opened_at = now
            self.open_count += 1
            return True
        return False

    def record_success(self, now=None):
        """A clean/preempted exit closes the breaker and clears history."""
        self.state = "closed"
        self._failures = []

    def restart_delay_s(self, now=None):
        """Seconds the supervisor must hold the worker down: the
        remaining quarantine when open, else 0 (normal backoff rules)."""
        if self.state != "open":
            return 0.0
        now = self._clock() if now is None else now
        return max(0.0, self._opened_at + self.cooldown_s - now)

    def allow_probe(self, now=None):
        """True when a restart may proceed. An open breaker past its
        cooldown transitions to half_open (the one probe); an open
        breaker inside it refuses."""
        if self.state != "open":
            return True
        now = self._clock() if now is None else now
        if now >= self._opened_at + self.cooldown_s:
            self.state = "half_open"
            return True
        return False


class WorkerSupervisor:
    """Run one worker command under restart supervision.

    ``run()`` blocks until the worker exits in a non-restartable way (or
    the restart budget is exhausted) and returns the exit code the caller
    should propagate.
    """

    def __init__(self, cmd, env=None, max_restarts=0, backoff_s=1.0,
                 max_backoff_s=30.0, heartbeat_timeout_s=0.0,
                 heartbeat_file=None, poll_interval_s=0.05, term_grace_s=5.0,
                 fatal_exit_codes=(EXIT_POISONED,), log=None, http_port=None,
                 worker_port=None, replica_port=None, replica_config=None,
                 breaker=None, rank=None):
        self.cmd = list(cmd)
        self.env = dict(env if env is not None else os.environ)
        self.max_restarts = int(max_restarts)
        self.backoff_s = float(backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.poll_interval_s = float(poll_interval_s)
        self.term_grace_s = float(term_grace_s)
        self.fatal_exit_codes = tuple(fatal_exit_codes)
        self._log = log or (lambda msg: print(f"[supervisor] {msg}", file=sys.stderr, flush=True))

        if self.heartbeat_timeout_s > 0 and heartbeat_file is None:
            fd, heartbeat_file = tempfile.mkstemp(prefix="dstpu_heartbeat_")
            os.close(fd)
        self.heartbeat_file = heartbeat_file
        if self.heartbeat_file is not None:
            self.env[HEARTBEAT_FILE_ENV] = self.heartbeat_file
        # children auto-install the engine PreemptionHandler under a supervisor
        self.env.setdefault(PREEMPTION_ENV, "1")
        # a fixed worker telemetry port makes the worker scrapable by the
        # fleet collector across restarts (an ephemeral port would move)
        self.worker_port = worker_port
        if worker_port is not None:
            self.env[TELEMETRY_PORT_ENV] = str(int(worker_port))
        # a serving replica likewise keeps a FIXED request socket across
        # restarts so the router's endpoint list never goes stale
        self.replica_port = replica_port
        if replica_port is not None:
            self.env[REPLICA_PORT_ENV] = str(int(replica_port))
        if replica_config is not None:
            self.env[REPLICA_CONFIG_ENV] = str(replica_config)

        # crash-loop circuit breaker (fleet.breaker): accepts a built
        # CrashLoopBreaker, a BreakerConfig-shaped object/dict, or None
        self.breaker = CrashLoopBreaker.from_config(breaker)
        self.rank = int(rank if rank is not None
                        else os.environ.get("RANK", "0") or 0)
        self.consecutive_failures = 0   # failure exits since last clean

        self.child = None
        self.restarts = 0
        self.exit_history = []  # [(exit_class, returncode), ...]
        self._shutdown_signal = None
        self._spawned_at = 0.0
        self.http_port = http_port
        self.telemetry_server = None

    # -- lifecycle -----------------------------------------------------
    def run(self):
        prev = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                prev[sig] = signal.signal(sig, self._on_signal)
            except ValueError:  # not the main thread (tests): no forwarding
                pass
        if self.http_port is not None:
            self._start_telemetry_server()
        try:
            return self._supervise()
        finally:
            if self.telemetry_server is not None:
                self.telemetry_server.stop()
            for sig, handler in prev.items():
                signal.signal(sig, handler)

    def _supervise(self):
        while True:
            self._spawn()
            returncode, hung = self._wait()
            if self._shutdown_signal is not None:
                # the supervisor itself was told to stop: propagate the
                # child's verdict (EXIT_PREEMPTED when it checkpointed)
                self._log(
                    f"shutting down on signal {self._shutdown_signal}; "
                    f"worker exited {returncode}"
                )
                return returncode
            cls = CLASS_HUNG if hung else classify_exit(returncode, self.fatal_exit_codes)
            self.exit_history.append((cls, returncode))
            if cls in (CLASS_CLEAN, CLASS_PREEMPTED):
                self.consecutive_failures = 0
                if self.breaker is not None:
                    self.breaker.record_success()
            else:
                self.consecutive_failures += 1
            self._note_exit(cls, returncode)
            if cls == CLASS_CLEAN:
                return EXIT_CLEAN
            if cls == CLASS_FATAL:
                self._log(f"worker exit {returncode} is fatal (poisoned); not restarting")
                return returncode
            if self.restarts >= self.max_restarts:
                self._log(
                    f"worker {cls} (exit {returncode}); restart budget "
                    f"exhausted ({self.restarts}/{self.max_restarts})"
                )
                return returncode if returncode != 0 else 1
            self.restarts += 1
            if cls == CLASS_PREEMPTED:
                delay = 0.0  # resumable checkpoint committed: come back fast
            else:
                delay = min(self.backoff_s * (2 ** (self.restarts - 1)), self.max_backoff_s)
            if self.breaker is not None and cls in (CLASS_CRASH, CLASS_HUNG):
                if self.breaker.record_failure():
                    self._note_breaker_open(cls, returncode)
                    self._log(
                        f"crash-loop breaker OPEN after "
                        f"{self.consecutive_failures} consecutive failures; "
                        f"quarantined {self.breaker.cooldown_s:.1f}s"
                    )
                # quarantine dominates backoff while the breaker is open
                delay = max(delay, self.breaker.restart_delay_s())
            self._note_restart(cls, returncode, delay)
            self._log(
                f"worker {cls} (exit {returncode}); restart "
                f"{self.restarts}/{self.max_restarts} in {delay:.1f}s"
            )
            if delay > 0:
                time.sleep(delay)
            if self.breaker is not None:
                # open -> half_open: the next spawn is the single probe
                self.breaker.allow_probe()

    def _spawn(self):
        self.child = subprocess.Popen(self.cmd, env=self.env)
        self._spawned_at = time.monotonic()

    def _wait(self):
        """Poll the child until it exits. Returns (returncode, hung) where
        ``hung`` means the heartbeat went stale and the child was killed."""
        term_deadline = kill_deadline = None
        while True:
            rc = self.child.poll()
            if rc is not None:
                return rc, False
            now = time.monotonic()
            if self._shutdown_signal is not None:
                if term_deadline is None:
                    term_deadline = now + self.term_grace_s
                elif now >= term_deadline and kill_deadline is None:
                    self._log("worker ignored the forwarded signal; terminating")
                    self.child.terminate()
                    kill_deadline = now + self.term_grace_s
                elif kill_deadline is not None and now >= kill_deadline:
                    self._log("worker ignored terminate; killing")
                    self.child.kill()
                    return self.child.wait(), False
            elif self._heartbeat_stale(now):
                age = now - self._last_beat(now)
                self._log(
                    f"heartbeat stale ({age:.1f}s > {self.heartbeat_timeout_s}s): "
                    "worker is wedged; killing it"
                )
                self._stop_child()
                return self.child.returncode, True
            time.sleep(self.poll_interval_s)

    def _heartbeat_stale(self, now):
        if self.heartbeat_timeout_s <= 0 or self.heartbeat_file is None:
            return False
        return now - self._last_beat(now) > self.heartbeat_timeout_s

    def _last_beat(self, now):
        """Monotonic time of the newest sign of life: spawn counts as one (a
        worker gets a full timeout to produce its first step)."""
        try:
            mtime = os.path.getmtime(self.heartbeat_file)
        except OSError:
            return self._spawned_at
        # mtime is wall-clock; convert its age into the monotonic domain
        return max(self._spawned_at, now - max(0.0, time.time() - mtime))

    def _stop_child(self):
        """terminate → wait(grace) → kill escalation."""
        if self.child.poll() is not None:
            return
        self.child.terminate()
        try:
            self.child.wait(timeout=self.term_grace_s)
        except subprocess.TimeoutExpired:
            self.child.kill()
            self.child.wait()

    # -- telemetry (all lazily imported; no-ops unless requested) ------
    def _telemetry(self):
        """The telemetry package, or None. Imported only when the endpoint
        was requested or something else in-process already loaded it, so a
        bare supervisor never drags the package in just to note an exit."""
        if self.http_port is None and "deepspeed_tpu.telemetry" not in sys.modules:
            return None
        try:
            from deepspeed_tpu import telemetry
            return telemetry
        except Exception:
            return None

    def _start_telemetry_server(self):
        from deepspeed_tpu import telemetry
        from deepspeed_tpu.telemetry import TelemetryServer

        srv = TelemetryServer(registry=telemetry.get_registry(),
                              tracer=telemetry.get_tracer(),
                              port=int(self.http_port))
        srv.add_health_provider("worker", self._worker_health)
        srv.add_snapshot_provider("supervisor", self._snapshot)
        self.export_gauges(telemetry.get_registry())
        self.telemetry_server = srv.start()
        self._log(f"telemetry endpoint at {srv.url}")
        return srv

    @property
    def worker_endpoint(self):
        """The worker's telemetry URL (for a fleet collector), or None
        when no fixed ``worker_port`` was configured."""
        if self.worker_port is None:
            return None
        return f"http://127.0.0.1:{int(self.worker_port)}"

    @property
    def replica_endpoint(self):
        """(host, port) of the supervised serving replica's request
        socket (for a Router endpoint list), or None when this worker is
        not a serving replica."""
        if self.replica_port is None:
            return None
        return ("127.0.0.1", int(self.replica_port))

    def export_gauges(self, registry):
        """Register the supervisor's liveness as pull ``gauge_fn``s: a
        ``/fleet/metrics`` scrape sees restart counts, heartbeat age and
        child liveness without parsing trace events. Idempotent
        (re-registration overwrites), callable without a server too."""

        def _liveness():
            out = {"restarts": float(self.restarts),
                   "worker_alive": float(
                       self.child is not None and self.child.poll() is None)}
            if self.heartbeat_file is not None and self._spawned_at > 0:
                now = time.monotonic()
                out["heartbeat_age_s"] = max(0.0, now - self._last_beat(now))
            return out

        # kept for dashboard compatibility with the PR 7 name
        registry.gauge_fn("Supervisor/restarts", lambda: float(self.restarts),
                          help="worker restarts performed so far")
        registry.gauge_fn("Supervisor/worker", _liveness,
                          help="supervised worker liveness")
        # fleet-facing per-rank health: the collector's Fleet/* rollups
        # (and the autoscaler reading them) see crash-loop state without
        # parsing exit history; both reset on a clean/preempted exit
        registry.gauge_fn(
            f"Fleet/rank{self.rank}/restarts_consecutive",
            lambda: float(self.consecutive_failures),
            help="failure exits since this worker last exited clean")
        registry.gauge_fn(
            f"Fleet/rank{self.rank}/breaker_open",
            lambda: float(self.breaker is not None and self.breaker.is_open),
            help="1 while this worker's crash-loop breaker is open")
        return registry

    def _worker_health(self):
        alive = self.child is not None and self.child.poll() is None
        doc = {"healthy": alive, "restarts": self.restarts,
               "max_restarts": self.max_restarts}
        if alive and self.heartbeat_file is not None:
            now = time.monotonic()
            doc["heartbeat_age_s"] = round(now - self._last_beat(now), 3)
            if self._heartbeat_stale(now):
                doc["healthy"] = False
                doc["reason"] = "heartbeat stale"
        return doc

    def _snapshot(self):
        return {
            "restarts": self.restarts,
            "max_restarts": self.max_restarts,
            "exit_history": [
                {"class": cls, "returncode": rc} for cls, rc in self.exit_history
            ],
            "child_pid": getattr(self.child, "pid", None),
            "child_alive": self.child is not None and self.child.poll() is None,
        }

    def _note_exit(self, cls, returncode):
        tel = self._telemetry()
        if tel is None:
            return
        tel.instant("worker/exit", cat="lifecycle",
                    args={"class": cls, "returncode": returncode,
                          "restarts": self.restarts})
        tel.get_registry().counter(
            f"Supervisor/exits/{cls}",
            help="worker exits by supervision class").inc()

    def _note_breaker_open(self, cls, returncode):
        tel = self._telemetry()
        if tel is None:
            return
        tel.instant("fleet/breaker_open", cat="fleet",
                    args={"rank": self.rank, "class": cls,
                          "returncode": returncode,
                          "consecutive_failures": self.consecutive_failures,
                          "cooldown_s": self.breaker.cooldown_s,
                          "open_count": self.breaker.open_count})
        tel.get_registry().counter(
            "Fleet/breaker_opens_total",
            help="crash-loop breaker open events").inc()

    def _note_restart(self, cls, returncode, delay):
        tel = self._telemetry()
        if tel is None:
            return
        tel.instant("worker/restart", cat="lifecycle",
                    args={"class": cls, "returncode": returncode,
                          "restart": self.restarts,
                          "max_restarts": self.max_restarts,
                          "delay_s": delay})
        tel.get_registry().counter(
            "Supervisor/restarts_total",
            help="worker restarts performed by the supervisor").inc()

    def _on_signal(self, signum, frame):
        self._shutdown_signal = signum
        if self.child is not None and self.child.poll() is None:
            try:
                self.child.send_signal(signum)
            except OSError:
                pass
