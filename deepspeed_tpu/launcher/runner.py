"""Multi-node launcher front-end.

Capability parity with the reference's ``deepspeed/launcher/runner.py``
(``bin/deepspeed``): parse an MPI-style hostfile (``worker-0 slots=4``),
``--include/--exclude`` node:slot filters, encode the world layout as base64,
discover the master address, and dispatch per-node launch commands over
pdsh/ssh — except the per-node payload initializes ``jax.distributed`` (one
process per host driving all local TPU chips) instead of one process per GPU.
"""

import argparse
import base64
import json
import os
import subprocess
import sys
from collections import OrderedDict
from shlex import split

from deepspeed_tpu.utils.logging import logger

DLTS_HOSTFILE = "/job/hostfile"
EXPORT_ENVS = ["PYTHON", "PATH", "LD_LIBRARY_PATH", "TPU", "JAX", "XLA"]
DEEPSPEED_ENVIRONMENT_NAME = ".deepspeed_env"
DEEPSPEED_ENVIRONMENT_PATHS = [".", os.path.expanduser("~")]
PDSH_MAX_FAN_OUT = 1024


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="DeepSpeedTPU runner to help launch distributed multi-node/multi-chip training jobs"
    )
    parser.add_argument("-H", "--hostfile", type=str, default=DLTS_HOSTFILE,
                        help="Hostfile path (MPI-style) that defines the resource pool, e.g. 'worker-0 slots=4'")
    parser.add_argument("-i", "--include", type=str, default="",
                        help="Specify hardware resources to use as 'host1:0,2@host2'.")
    parser.add_argument("-e", "--exclude", type=str, default="",
                        help="Specify hardware resources to exclude, mutually exclusive with --include.")
    parser.add_argument("--num_nodes", type=int, default=-1,
                        help="Total number of worker nodes to run on, this will use the top N hosts from the hostfile.")
    parser.add_argument("--num_gpus", "--num_chips", type=int, default=-1, dest="num_gpus",
                        help="Max number of accelerator chips to use on each node.")
    parser.add_argument("--master_port", type=int, default=29500,
                        help="Port used by jax.distributed during distributed training.")
    parser.add_argument("--master_addr", type=str, default="",
                        help="IP address of node 0; will be inferred via hostfile if not specified.")
    parser.add_argument("--launcher", type=str, default="pdsh",
                        help="Multi-node launcher backend: pdsh, openmpi, mvapich, ssh.")
    parser.add_argument("--launcher_args", type=str, default="",
                        help="Flags to pass to the chosen launcher backend.")
    parser.add_argument("--force_multi", action="store_true",
                        help="Force multi-node mode even when only one node is specified.")
    parser.add_argument("user_script", type=str, help="User script to launch")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


def fetch_hostfile(hostfile_path):
    """Parse 'hostname slots=N' lines into an ordered {host: slots} dict
    (reference runner.py:115-143)."""
    if not os.path.isfile(hostfile_path):
        logger.warning(f"Unable to find hostfile, will proceed with training with local resources only.")
        return None

    resource_pool = OrderedDict()
    with open(hostfile_path, "r") as fd:
        for line in fd.readlines():
            line = line.strip()
            if line == "" or line.startswith("#"):
                continue
            try:
                hostname, slots = line.split()
                key, slot_count = slots.split("=")
                if key != "slots":
                    raise ValueError(f"expected 'slots=N', got '{slots}'")
                slot_count = int(slot_count)
            except ValueError as err:
                logger.error(f"Hostfile is not formatted correctly, unable to proceed with training.")
                raise err
            if hostname in resource_pool:
                logger.error(f"Hostfile contains duplicate hosts, unable to proceed with training.")
                raise ValueError(f"host {hostname} is already defined")
            resource_pool[hostname] = slot_count
    return resource_pool


def _parse_hostfile_filter(filter_str):
    """'host1:0,2@host2' -> {'host1': [0,2], 'host2': []} ([] = all slots)."""
    mapping = OrderedDict()
    for node_config in filter_str.split("@"):
        if node_config == "":
            continue
        if ":" in node_config:
            hostname, slots = node_config.split(":")
            slot_list = [int(x) for x in slots.split(",")]
        else:
            hostname, slot_list = node_config, []
        if hostname in mapping:
            raise ValueError(f"Hostname '{hostname}' found multiple times in filter")
        mapping[hostname] = slot_list
    return mapping


def parse_resource_filter(host_info, include_str="", exclude_str=""):
    """Apply --include/--exclude filters (reference runner.py:146-235).

    Returns the filtered {host: [slot_ids]} ordered dict.
    """
    if include_str and exclude_str:
        raise ValueError("include_str and exclude_str are mutually exclusive.")

    # Expand pool to explicit slot lists.
    pool = OrderedDict((host, list(range(slots))) for host, slots in host_info.items())

    if include_str:
        include = _parse_hostfile_filter(include_str)
        filtered = OrderedDict()
        for hostname, slots in include.items():
            if hostname not in pool:
                raise ValueError(f"Hostname '{hostname}' not found in hostfile")
            for s in slots:
                if s not in pool[hostname]:
                    raise ValueError(f"No slot '{s}' specified on host '{hostname}'")
            filtered[hostname] = slots if slots else pool[hostname]
        return filtered

    if exclude_str:
        exclude = _parse_hostfile_filter(exclude_str)
        filtered = OrderedDict()
        for hostname, slots in pool.items():
            if hostname not in exclude:
                filtered[hostname] = slots
            else:
                excl = exclude[hostname]
                if not excl:
                    continue  # whole host excluded
                for s in excl:
                    if s not in pool[hostname]:
                        raise ValueError(f"No slot '{s}' specified on host '{hostname}'")
                keep = [s for s in pool[hostname] if s not in excl]
                if keep:
                    filtered[hostname] = keep
        return filtered

    return pool


def parse_inclusion_exclusion(resource_pool, inclusion, exclusion):
    active_resources = OrderedDict()
    for hostname, slots in resource_pool.items():
        active_resources[hostname] = slots
    return parse_resource_filter(active_resources, include_str=inclusion, exclude_str=exclusion)


def encode_world_info(world_info):
    """base64(json) world layout passed to each node (reference runner.py:248-251)."""
    world_info_json = json.dumps(world_info).encode("utf-8")
    return base64.urlsafe_b64encode(world_info_json).decode("utf-8")


def decode_world_info(encoded):
    return json.loads(base64.urlsafe_b64decode(encoded.encode("utf-8")).decode("utf-8"))


def fetch_master_addr(resource_pool, requested=""):
    """First host's first reported IP via ssh (reference runner.py:281-288)."""
    if requested:
        return requested
    first_host = list(resource_pool.keys())[0]
    if first_host in ("localhost", "127.0.0.1"):
        return "127.0.0.1"
    try:
        hostname_cmd = [f"ssh {first_host} hostname -I"]
        result = subprocess.check_output(hostname_cmd, shell=True)
        return result.decode("utf-8").split()[0]
    except Exception:
        logger.warning(f"Unable to ssh {first_host} for master addr, using hostname directly")
        return first_host


def collect_env_exports():
    """Env vars to propagate (reference .deepspeed_env + prefix list)."""
    exports = {}
    for var, val in os.environ.items():
        if any(var.startswith(pfx) for pfx in EXPORT_ENVS):
            exports[var] = val
    for basedir in DEEPSPEED_ENVIRONMENT_PATHS:
        env_file = os.path.join(basedir, DEEPSPEED_ENVIRONMENT_NAME)
        if os.path.isfile(env_file):
            with open(env_file, "r") as fd:
                for line in fd.readlines():
                    line = line.strip()
                    if line and "=" in line:
                        key, val = line.split("=", 1)
                        exports[key] = val
    return exports


def main(args=None):
    args = parse_args(args)

    resource_pool = fetch_hostfile(args.hostfile)

    if not resource_pool:
        # Single node, all local chips, no ssh: exec launch module directly.
        # Empty slot list = use every local chip (launch.py only restricts
        # TPU_VISIBLE_CHIPS when an explicit subset is given).
        world_info = {"localhost": []}
        cmd = [
            sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch",
            f"--world_info={encode_world_info(world_info)}",
            "--node_rank=0",
            f"--master_addr=127.0.0.1",
            f"--master_port={args.master_port}",
            args.user_script,
        ] + args.user_args
        result = subprocess.Popen(cmd, env=os.environ.copy())
        result.wait()
        return result.returncode

    active_resources = parse_inclusion_exclusion(resource_pool, args.include, args.exclude)

    if args.num_nodes > 0:
        updated = OrderedDict()
        for count, (host, slots) in enumerate(active_resources.items()):
            if count >= args.num_nodes:
                break
            updated[host] = slots
        active_resources = updated

    if args.num_gpus > 0:
        active_resources = OrderedDict(
            (host, slots[: args.num_gpus]) for host, slots in active_resources.items()
        )

    master_addr = fetch_master_addr(active_resources, args.master_addr)
    world_info = encode_world_info({h: s for h, s in active_resources.items()})

    from deepspeed_tpu.launcher.multinode_runner import (
        MVAPICHRunner,
        OpenMPIRunner,
        PDSHRunner,
        SSHRunner,
    )

    runner_cls = {"pdsh": PDSHRunner, "openmpi": OpenMPIRunner,
                  "mvapich": MVAPICHRunner, "ssh": SSHRunner}.get(args.launcher.lower())
    if runner_cls is None:
        raise ValueError(f"Unknown launcher {args.launcher}")
    runner = runner_cls(args, world_info, master_addr, collect_env_exports())
    if not runner.backend_exists():
        raise RuntimeError(f"launcher backend '{args.launcher}' not installed")
    cmd = runner.get_cmd()
    logger.info(f"cmd = {' '.join(cmd)}")
    try:
        result = subprocess.Popen(cmd, env=os.environ.copy())
        result.wait()
    finally:
        runner.cleanup()  # e.g. the MVAPICH temp hostfile
    return result.returncode


if __name__ == "__main__":
    sys.exit(main())
