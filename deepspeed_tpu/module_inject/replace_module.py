"""Module injection: swap HF/BERT-style attention layers for the fused
DeepSpeedTransformerLayer, copying weights (and back).

Capability parity with the reference ``deepspeed/module_inject/replace_module.py``
(``replace_transformer_layer:6``, ``replace_module:160``). The torch version
mutates ``nn.Module`` graphs in place; the flax idiom is a pure function over
the PARAM TREE: HF-layout params convert to DeepSpeedTransformerLayer-layout
params (qkv fusion, LN renames) and the model swaps its layer class at
construction. ``revert_transformer_layer`` is the inverse mapping.
"""

import numpy as np

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.transformer.transformer import (
    DeepSpeedTransformerConfig,
    DeepSpeedTransformerLayer,
)


def _get(tree, *path):
    for p in path:
        tree = tree[p]
    return tree


def convert_hf_layer_params(hf_layer_params):
    """HF FlaxBertLayer params -> DeepSpeedTransformerLayer params.

    HF layout: attention.self.{query,key,value}, attention.output.dense,
    attention.output.LayerNorm, intermediate.dense, output.dense,
    output.LayerNorm. Ours fuses q/k/v into one qkv GEMM
    (reference copies qkv weights the same way, replace_module.py:35-90).
    """
    a = hf_layer_params["attention"]
    q = a["self"]["query"]; k = a["self"]["key"]; v = a["self"]["value"]
    qkv_kernel = jnp.concatenate([q["kernel"], k["kernel"], v["kernel"]], axis=1)
    qkv_bias = jnp.concatenate([q["bias"], k["bias"], v["bias"]], axis=0)
    return {
        "params": {
            "qkv": {"kernel": qkv_kernel, "bias": qkv_bias},
            "attn_out": {"kernel": a["output"]["dense"]["kernel"],
                         "bias": a["output"]["dense"]["bias"]},
            "ln_attn": {"scale": a["output"]["LayerNorm"]["scale"],
                        "bias": a["output"]["LayerNorm"]["bias"]},
            "ff1": {"kernel": hf_layer_params["intermediate"]["dense"]["kernel"],
                    "bias": hf_layer_params["intermediate"]["dense"]["bias"]},
            "ff2": {"kernel": hf_layer_params["output"]["dense"]["kernel"],
                    "bias": hf_layer_params["output"]["dense"]["bias"]},
            "ln_ffn": {"scale": hf_layer_params["output"]["LayerNorm"]["scale"],
                       "bias": hf_layer_params["output"]["LayerNorm"]["bias"]},
        }
    }


def revert_hf_layer_params(ds_layer_params, hidden_size):
    """DeepSpeedTransformerLayer params -> HF FlaxBertLayer params (inverse of
    ``convert_hf_layer_params``; reference's revert path in
    ops/module_inject.py)."""
    p = ds_layer_params["params"]
    qkv_k = p["qkv"]["kernel"]; qkv_b = p["qkv"]["bias"]
    H = hidden_size
    return {
        "attention": {
            "self": {
                "query": {"kernel": qkv_k[:, :H], "bias": qkv_b[:H]},
                "key": {"kernel": qkv_k[:, H:2 * H], "bias": qkv_b[H:2 * H]},
                "value": {"kernel": qkv_k[:, 2 * H:], "bias": qkv_b[2 * H:]},
            },
            "output": {
                "dense": dict(p["attn_out"]),
                "LayerNorm": dict(p["ln_attn"]),
            },
        },
        "intermediate": {"dense": dict(p["ff1"])},
        "output": {"dense": dict(p["ff2"]), "LayerNorm": dict(p["ln_ffn"])},
    }


def replace_transformer_layer(orig_layer_impl=None, model=None, model_params=None,
                              micro_batch_size=-1, config=None, seed=-1,
                              max_seq_length=-1, hidden_size=-1, heads=-1,
                              intermediate_size=-1, preln=False, fp16=False,
                              layer_path=("bert", "encoder", "layer"),
                              huggingface=False, local_rank=-1):
    """Convert every HF encoder layer's params under ``layer_path`` and return
    (DeepSpeedTransformerLayer factory, converted per-layer params list).

    ``model_params``: the HF model's param tree (``{"params": {...}}`` or bare).
    """
    tree = model_params.get("params", model_params)
    layers = _get(tree, *layer_path)
    layer_keys = sorted(layers.keys(), key=lambda s: int(s) if str(s).isdigit() else s)
    converted = [convert_hf_layer_params(layers[k]) for k in layer_keys]

    ds_config = DeepSpeedTransformerConfig(
        batch_size=micro_batch_size,
        max_seq_length=max_seq_length,
        hidden_size=hidden_size,
        intermediate_size=intermediate_size if intermediate_size > 0 else 4 * hidden_size,
        heads=heads,
        attn_dropout_ratio=0.0,
        hidden_dropout_ratio=0.0,
        num_hidden_layers=len(converted),
        initializer_range=0.02,
        seed=seed,
        fp16=fp16,
        pre_layer_norm=preln,
        huggingface=huggingface,
        local_rank=local_rank,
    )
    return DeepSpeedTransformerLayer(ds_config), converted


def revert_transformer_layer(ds_layers_params, hidden_size):
    """Inverse: list of DS layer params -> dict of HF layer params."""
    return {
        str(i): revert_hf_layer_params(p, hidden_size)
        for i, p in enumerate(ds_layers_params)
    }


def replace_module(params, match_fn, transform_fn, path=()):
    """Generic recursive param-subtree replacement (reference replace_module:
    160): wherever ``match_fn(path, subtree)`` is True, substitute
    ``transform_fn(subtree)``."""
    if match_fn(path, params):
        return transform_fn(params)
    if isinstance(params, dict):
        return {k: replace_module(v, match_fn, transform_fn, path + (k,)) for k, v in params.items()}
    return params


# ---------------------------------------------------------------------------
# policy-driven recursive injection (reference _replace_module:175 +
# replace_policy.py HFBertLayerPolicy): shape-matched subtrees are swapped
# ANYWHERE in an arbitrary model tree, no layer_path needed.
# ---------------------------------------------------------------------------

class HFBertLayerPolicy:
    """Detects HF FlaxBertLayer-shaped param subtrees and converts them
    to/from DeepSpeedTransformerLayer layout (the flax analogue of the
    reference's class-matched replace policy — params have no classes, so the
    SHAPE of the subtree is the policy's match criterion)."""

    @staticmethod
    def matches(path, subtree):
        # EXACT key sets, not supersets: a decoder layer carrying e.g. an
        # extra 'crossattention' subtree must NOT match — the fixed DS layout
        # has nowhere to keep the extras and the round trip would silently
        # drop them.
        if not isinstance(subtree, dict) or set(subtree) != {"attention", "intermediate", "output"}:
            return False
        attn = subtree["attention"]
        if not isinstance(attn, dict) or set(attn) != {"self", "output"}:
            return False
        self_attn, a_out = attn["self"], attn["output"]
        return (
            isinstance(self_attn, dict)
            and set(self_attn) == {"query", "key", "value"}
            and isinstance(a_out, dict)
            and set(a_out) == {"dense", "LayerNorm"}
            and isinstance(subtree["intermediate"], dict)
            and set(subtree["intermediate"]) == {"dense"}
            and isinstance(subtree["output"], dict)
            and set(subtree["output"]) == {"dense", "LayerNorm"}
        )

    convert = staticmethod(convert_hf_layer_params)

    @staticmethod
    def matches_ds(subtree):
        """Detects the converted DeepSpeedTransformerLayer layout (for the
        reverse walk). Exact key set, symmetric with ``matches`` — a superset
        match would silently drop extra keys on revert."""
        if not isinstance(subtree, dict) or set(subtree) != {"params"}:
            return False
        p = subtree["params"]
        return isinstance(p, dict) and set(p) == {
            "qkv", "attn_out", "ln_attn", "ln_ffn", "ff1", "ff2"
        }

    @staticmethod
    def revert(subtree, hidden_size):
        return revert_hf_layer_params(subtree, hidden_size)


def inject_policies(params, policies=(HFBertLayerPolicy,)):
    """Recursively swap every policy-matched subtree anywhere in ``params``
    for DeepSpeedTransformerLayer-layout params.

    Returns (new_params, replaced_paths) — replaced_paths lists the tree
    paths that were swapped, in traversal order, so callers can build the
    matching module structure (and ``revert_policies`` can invert exactly)."""
    replaced = []

    def walk(tree, path):
        for pol in policies:
            if pol.matches(path, tree):
                replaced.append(path)
                return pol.convert(tree)
        if isinstance(tree, dict):
            return {k: walk(v, path + (k,)) for k, v in tree.items()}
        return tree

    return walk(params, ()), replaced


def revert_policies(params, hidden_size, policies=(HFBertLayerPolicy,)):
    """Inverse of ``inject_policies``: recursively restore every
    DS-layout subtree to the policy's original (HF) layout."""
    reverted = []

    def walk(tree, path):
        for pol in policies:
            if pol.matches_ds(tree):
                reverted.append(path)
                return pol.revert(tree, hidden_size)
        if isinstance(tree, dict):
            return {k: walk(v, path + (k,)) for k, v in tree.items()}
        return tree

    return walk(params, ()), reverted
