from deepspeed_tpu.module_inject.replace_module import (
    convert_hf_layer_params,
    replace_module,
    replace_transformer_layer,
    revert_hf_layer_params,
    revert_transformer_layer,
)
