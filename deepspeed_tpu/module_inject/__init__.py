from deepspeed_tpu.module_inject.replace_module import (
    HFBertLayerPolicy,
    convert_hf_layer_params,
    inject_policies,
    replace_module,
    replace_transformer_layer,
    revert_hf_layer_params,
    revert_policies,
    revert_transformer_layer,
)
