"""``telemetry`` ds_config block (validated by ``runtime/config.py``).

Off by default; enabling it arms the process-global tracer + metrics
registry (``telemetry.configure_from_config``) and, when ``http_port`` is
set, lets the serving engine attach the introspection endpoint.

Config::

    "telemetry": {"enabled": true,
                  "trace_max_events": 65536,   # ring-buffer bound
                  "http_port": 0,              # null: no server; 0: ephemeral
                  "trace_file": "trace.json",  # written on engine close (optional)
                  "slo": [                     # declarative SLO rules (telemetry/slo.py)
                      {"metric": "Serving/ttft_p95_s", "max": 0.5, "for_s": 30}],
                  "slo_policy": "warn"}        # or "fail": raise SloViolationError

Kept free of ``runtime/`` imports so the telemetry package stays
importable without the training stack (the stdlib-only supervisor
serves /healthz too).
"""

import os

from deepspeed_tpu.telemetry.slo import SLO_POLICIES, validate_slo_rule

TELEMETRY = "telemetry"

TELEMETRY_ENABLED = "enabled"
TELEMETRY_ENABLED_DEFAULT = False

TELEMETRY_TRACE_MAX_EVENTS = "trace_max_events"
TELEMETRY_TRACE_MAX_EVENTS_DEFAULT = 65536

# None: no HTTP server. 0: bind an ephemeral port (tests / single-host
# debugging — read it back from ServingEngine.telemetry_server.port).
TELEMETRY_HTTP_PORT = "http_port"
TELEMETRY_HTTP_PORT_DEFAULT = None

TELEMETRY_TRACE_FILE = "trace_file"
TELEMETRY_TRACE_FILE_DEFAULT = None

TELEMETRY_SLO = "slo"
TELEMETRY_SLO_POLICY = "slo_policy"
TELEMETRY_SLO_POLICY_DEFAULT = "warn"

# Supervisor -> worker port contract: the launcher's WorkerSupervisor
# exports this env var so a worker whose config leaves http_port null
# still binds the port the fleet collector was told to scrape. Duplicated
# (not imported) in launcher/supervisor.py: the telemetry package must
# not import the launcher and vice versa stays lazy.
TELEMETRY_PORT_ENV = "DSTPU_TELEMETRY_PORT"


def resolve_http_port(telemetry_config):
    """Effective telemetry HTTP port: an explicit ``http_port`` wins, else
    the supervisor-injected ``DSTPU_TELEMETRY_PORT``, else None (no server)."""
    if telemetry_config is not None and telemetry_config.http_port is not None:
        return telemetry_config.http_port
    raw = os.environ.get(TELEMETRY_PORT_ENV, "").strip()
    if raw:
        try:
            return int(raw)
        except ValueError:
            return None
    return None


class DeepSpeedTelemetryConfig:
    """Validated view of the ``telemetry`` block."""

    def __init__(self, param_dict):
        tel_dict = param_dict.get(TELEMETRY, {})
        if not isinstance(tel_dict, dict):
            raise ValueError(
                f"'{TELEMETRY}' must be a dict, got {type(tel_dict).__name__}")
        # block present at all? absent blocks must not clobber global
        # telemetry state armed by an earlier engine in the same process
        self.configured = TELEMETRY in param_dict
        self.enabled = tel_dict.get(TELEMETRY_ENABLED, TELEMETRY_ENABLED_DEFAULT)
        if not isinstance(self.enabled, bool):
            raise ValueError(
                f"'{TELEMETRY}.{TELEMETRY_ENABLED}' must be a bool, "
                f"got {self.enabled!r}")
        self.trace_max_events = tel_dict.get(
            TELEMETRY_TRACE_MAX_EVENTS, TELEMETRY_TRACE_MAX_EVENTS_DEFAULT)
        if not isinstance(self.trace_max_events, int) \
                or isinstance(self.trace_max_events, bool) \
                or self.trace_max_events < 1:
            raise ValueError(
                f"'{TELEMETRY}.{TELEMETRY_TRACE_MAX_EVENTS}' must be an int >= 1, "
                f"got {self.trace_max_events!r}")
        self.http_port = tel_dict.get(TELEMETRY_HTTP_PORT,
                                      TELEMETRY_HTTP_PORT_DEFAULT)
        if self.http_port is not None and (
                not isinstance(self.http_port, int)
                or isinstance(self.http_port, bool)
                or not 0 <= self.http_port <= 65535):
            raise ValueError(
                f"'{TELEMETRY}.{TELEMETRY_HTTP_PORT}' must be null or an int "
                f"in [0, 65535], got {self.http_port!r}")
        self.trace_file = tel_dict.get(TELEMETRY_TRACE_FILE,
                                       TELEMETRY_TRACE_FILE_DEFAULT)
        if self.trace_file is not None and not isinstance(self.trace_file, str):
            raise ValueError(
                f"'{TELEMETRY}.{TELEMETRY_TRACE_FILE}' must be null or a "
                f"string path, got {self.trace_file!r}")
        raw_slo = tel_dict.get(TELEMETRY_SLO, [])
        if not isinstance(raw_slo, (list, tuple)):
            raise ValueError(
                f"'{TELEMETRY}.{TELEMETRY_SLO}' must be a list of rule "
                f"dicts, got {raw_slo!r}")
        self.slo_rules = [
            validate_slo_rule(r, where=f"{TELEMETRY}.{TELEMETRY_SLO}[{i}]")
            for i, r in enumerate(raw_slo)]
        self.slo_policy = tel_dict.get(TELEMETRY_SLO_POLICY,
                                       TELEMETRY_SLO_POLICY_DEFAULT)
        if self.slo_policy not in SLO_POLICIES:
            raise ValueError(
                f"'{TELEMETRY}.{TELEMETRY_SLO_POLICY}' must be one of "
                f"{SLO_POLICIES}, got {self.slo_policy!r}")

    def repr(self):
        return self.__dict__
