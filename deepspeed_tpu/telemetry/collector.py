"""Fleet collector: scrape per-worker telemetry, merge into one view.

PR 7's telemetry is strictly per-process — each worker owns a tracer ring
buffer, a registry and a ``/metrics`` endpoint. :class:`FleetCollector`
is the fleet layer on top: it scrapes every worker's ``/registry`` (raw
slash-tag metrics JSON), ``/snapshot`` and ``/trace`` endpoints and
produces

- **one merged Chrome trace**: every scraped event is rewritten onto
  ``pid = rank`` (named lanes via ``process_name`` metadata, synthesized
  when a worker didn't stamp its own) and rebased onto the collector's
  wall-clock epoch using each trace's ``metadata.epoch_unix`` — so spans
  recorded by processes with unrelated ``perf_counter`` epochs line up on
  a single Perfetto timeline. Supervisor lifecycle instants
  (``worker/restart``, ``resilience/*``) land in the same timeline via
  :meth:`attach_local`.
- **rank-labelled metrics + fleet rollups**: every numeric worker metric
  becomes ``Fleet/rank<r>/<tag>``, plus ``Fleet/<tag>/min|max|mean``
  across ranks, a liveness gauge per rank, and the straggler gauges
  (``Fleet/straggler_rank`` — the lagging-rank index — and
  ``Fleet/step_time_skew``) from :class:`StragglerDetector`, which is fed
  the step spans found in each scraped trace.
- **gap markers**: an unreachable worker degrades to a partial merge —
  its lane gets a ``fleet/scrape_gap`` instant at the outage edge, its
  ``Fleet/rank<r>/up`` gauge drops to 0, and everyone else's data still
  merges.

``serve()`` exposes it all on a :class:`TelemetryServer`:
``/fleet/metrics`` (Prometheus text), ``/fleet/trace`` (merged Chrome
JSON — load directly into Perfetto), ``/fleet/snapshot`` (per-rank
status + snapshots + rollups), and ``/alerts`` when an
:class:`~deepspeed_tpu.telemetry.slo.SloEngine` is attached (evaluated
against the fleet rollups on every scrape).

Scrapes default to ``drain=True`` so each worker event is merged (and
counted by the straggler detector) exactly once; peeking scrapes
(``drain=False``) skip the detector to avoid double counting.

Stdlib-only (see ``telemetry/trace.py``): the launcher embeds this next
to the supervisor without dragging jax into its process.
"""

import json
import threading
import time
from collections import deque
from urllib.request import urlopen

from deepspeed_tpu.telemetry.anomaly import StragglerDetector
from deepspeed_tpu.telemetry.registry import prom_name
from deepspeed_tpu.telemetry.server import TelemetryServer
from deepspeed_tpu.telemetry.trace import PH_INSTANT, PH_METADATA

_DEFAULT_MAX_EVENTS = 262144

# pid lane for events merged via attach_local (supervisor/launcher side)
LOCAL_RANK = -1


class FleetCollector:
    """Scrapes worker telemetry endpoints; merges traces and metrics."""

    def __init__(self, endpoints=None, timeout_s=2.0,
                 max_events=_DEFAULT_MAX_EVENTS, detector=None, slo=None):
        self.timeout_s = float(timeout_s)
        self.detector = detector if detector is not None \
            else StragglerDetector()
        self.slo = slo
        self._lock = threading.RLock()       # state (events/metrics/status)
        self._scrape_lock = threading.Lock()  # serializes whole scrapes
        self._endpoints = {}                 # rank -> {"url", "role"}
        self._locals = []                    # (rank, role, tracer, registry)
        self._events = deque(maxlen=int(max_events))
        self._events_dropped = 0
        self._seen_pids = set()              # ranks with process_name merged
        self._rank_metrics = {}              # rank -> {tag: float}
        self._rank_snapshots = {}            # rank -> /snapshot doc
        self._status = {}                    # rank -> scrape status dict
        self._epoch_unix = time.time()       # merged-timeline zero
        self._server = None
        self._thread = None
        self._stop = threading.Event()
        for ep in endpoints or ():
            self.add_endpoint(**ep)

    # -- wiring ---------------------------------------------------------
    def add_endpoint(self, rank, url, role="worker"):
        """Register one worker endpoint (e.g. from the supervisor's
        ``worker_endpoint`` or an explicit ``host:port`` list)."""
        url = str(url).rstrip("/")
        if "://" not in url:
            url = "http://" + url
        with self._lock:
            self._endpoints[int(rank)] = {"url": url, "role": str(role)}
        return self

    def attach_local(self, tracer, registry=None, rank=LOCAL_RANK,
                     role="supervisor"):
        """Merge an in-process tracer/registry (no HTTP hop) — how the
        launcher's supervisor instants (``worker/restart`` etc.) join the
        merged timeline."""
        with self._lock:
            self._locals.append((int(rank), str(role), tracer, registry))
        return self

    def attach_router(self, router, registry, rank=LOCAL_RANK):
        """Merge a fleet Router's counters into the fleet view: exports
        its ``Fleet/router/*`` gauges into ``registry`` and attaches that
        registry as a local ``role="router"`` source, so ``/fleet/metrics``
        carries routed/retried/shed/drained next to the per-replica
        serving metrics and the SLO engine can alert on shed rate."""
        router.export_gauges(registry)
        with self._lock:
            self._locals.append((int(rank), "router", None, registry))
        return self

    # -- scraping -------------------------------------------------------
    def _fetch_json(self, url):
        with urlopen(url, timeout=self.timeout_s) as resp:
            return json.loads(resp.read().decode("utf-8"))

    def _now_rel_us(self):
        return (time.time() - self._epoch_unix) * 1e6

    def _append_event(self, ev):
        if len(self._events) == self._events.maxlen:
            self._events_dropped += 1
        self._events.append(ev)

    def scrape(self, drain=True):
        """One scrape pass over every endpoint + attached local source.
        Network failures degrade to a partial merge (gap marker + ``up=0``
        for the dead rank). Returns a summary dict."""
        with self._scrape_lock:
            return self._scrape_locked(drain)

    def _scrape_locked(self, drain):
        summary = {"up": [], "down": [], "events_merged": 0}
        with self._lock:
            endpoints = sorted(self._endpoints.items())
            locals_ = list(self._locals)
        q = "1" if drain else "0"
        for rank, ep in endpoints:
            try:
                reg = self._fetch_json(ep["url"] + "/registry")
                snap = self._fetch_json(ep["url"] + "/snapshot")
                trace = self._fetch_json(ep["url"] + f"/trace?drain={q}")
            except Exception as e:  # URLError/timeout/bad JSON: rank is down
                self._mark_down(rank, ep, e)
                summary["down"].append(rank)
                continue
            n = self._merge_source(rank, ep["role"], reg, snap, trace,
                                   drained=drain, url=ep["url"])
            summary["up"].append(rank)
            summary["events_merged"] += n
        for rank, role, tracer, registry in locals_:
            try:
                trace = (tracer.to_chrome_trace(drain=drain)
                         if tracer is not None else {"traceEvents": []})
                reg = registry.as_dict() if registry is not None else {}
            except Exception:
                continue
            summary["events_merged"] += self._merge_source(
                rank, role, reg, None, trace, drained=drain, url=None)
        self._emit_anomalies()
        if self.slo is not None:
            self.slo.evaluate(self.fleet_metrics())
        return summary

    def _mark_down(self, rank, ep, err):
        with self._lock:
            st = self._status.setdefault(rank, {})
            was_up = st.get("up")    # None on first contact: also an edge
            st.update(up=False, url=ep["url"], role=ep["role"],
                      error=str(err)[:200], gaps=st.get("gaps", 0) + 1,
                      scrapes=st.get("scrapes", 0),
                      last_scrape_unix=time.time())
            if was_up is not True:
                return
            # outage edge: one gap marker on the dead rank's lane
            self._append_event(
                {"ph": PH_INSTANT, "name": "fleet/scrape_gap", "cat": "fleet",
                 "ts": self._now_rel_us(), "pid": rank, "tid": 0, "s": "p",
                 "args": {"rank": rank, "error": str(err)[:200]}})

    def _merge_source(self, rank, role, reg, snap, trace_doc, drained, url):
        events = trace_doc.get("traceEvents") or []
        meta = trace_doc.get("metadata") or {}
        src_epoch = meta.get("epoch_unix")
        offset_us = ((src_epoch - self._epoch_unix) * 1e6
                     if isinstance(src_epoch, (int, float))
                     and not isinstance(src_epoch, bool) else 0.0)
        with self._lock:
            st = self._status.setdefault(rank, {})
            st.update(up=True, url=url, role=role, error=None,
                      gaps=st.get("gaps", 0),
                      scrapes=st.get("scrapes", 0) + 1,
                      last_scrape_unix=time.time())
            self._rank_metrics[rank] = {
                k: float(v) for k, v in reg.items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)}
            if snap is not None:
                self._rank_snapshots[rank] = snap
            have_meta = rank in self._seen_pids
            n = 0
            for ev in events:
                ev = dict(ev)
                ev["pid"] = rank
                if ev.get("ph") == PH_METADATA:
                    if have_meta:
                        continue    # metadata re-renders on every scrape
                    if ev.get("name") == "process_name":
                        self._seen_pids.add(rank)
                else:
                    ev["ts"] = float(ev.get("ts", 0.0)) + offset_us
                self._append_event(ev)
                n += 1
            if rank not in self._seen_pids:
                # worker didn't stamp identity: synthesize the lane name
                for mev in (
                        {"ph": PH_METADATA, "name": "process_name",
                         "cat": "__metadata", "ts": 0, "pid": rank, "tid": 0,
                         "args": {"name": f"{role} rank{rank}",
                                  "rank": rank, "role": role}},
                        {"ph": PH_METADATA, "name": "process_sort_index",
                         "cat": "__metadata", "ts": 0, "pid": rank, "tid": 0,
                         "args": {"sort_index": max(rank, 0)}}):
                    self._append_event(mev)
                    n += 1
                self._seen_pids.add(rank)
        if drained:
            # drained events are seen exactly once -> safe to count steps
            self.detector.observe_events(rank, events)
        return n

    def _emit_anomalies(self):
        for a in self.detector.update():
            name = ("fleet/straggler" if a.get("type") == "straggler"
                    else "fleet/step_spike")
            with self._lock:
                self._append_event(
                    {"ph": PH_INSTANT, "name": name, "cat": "fleet",
                     "ts": self._now_rel_us(), "pid": a.get("rank", LOCAL_RANK),
                     "tid": 0, "s": "p", "args": a})

    # -- aggregated views -----------------------------------------------
    def fleet_metrics(self):
        """Rank-labelled series + min/max/mean rollups + straggler and
        liveness gauges, as a flat ``{tag: float}`` dict."""
        with self._lock:
            out = {}
            per_tag = {}
            for rank in sorted(self._rank_metrics):
                for tag, v in self._rank_metrics[rank].items():
                    out[f"Fleet/rank{rank}/{tag}"] = v
                    per_tag.setdefault(tag, []).append(v)
            for tag, vals in per_tag.items():
                out[f"Fleet/{tag}/min"] = min(vals)
                out[f"Fleet/{tag}/max"] = max(vals)
                out[f"Fleet/{tag}/mean"] = sum(vals) / len(vals)
            alive = 0
            for rank in sorted(self._status):
                st = self._status[rank]
                up = 1.0 if st.get("up") else 0.0
                alive += int(up)
                out[f"Fleet/rank{rank}/up"] = up
                out[f"Fleet/rank{rank}/scrape_gaps_total"] = \
                    float(st.get("gaps", 0))
            out["Fleet/alive_ranks"] = float(alive)
            out["Fleet/ranks_total"] = float(len(self._status))
        for k, v in self.detector.gauges().items():
            out[f"Fleet/{k}"] = v
        return out

    def render_prometheus(self):
        """``/fleet/metrics`` body (text exposition 0.0.4)."""
        lines = []
        for tag, v in self.fleet_metrics().items():
            pname = prom_name(tag)
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname} {v}")
        return "\n".join(lines) + "\n"

    def merged_trace(self):
        """The accumulated multi-process Chrome trace document."""
        with self._lock:
            meta = {"epoch_unix": self._epoch_unix,
                    "ranks": sorted(self._status),
                    "straggler_rank": self.detector.straggler_rank}
            if self._events_dropped:
                meta["dropped_events"] = self._events_dropped
            return {"traceEvents": list(self._events),
                    "displayTimeUnit": "ms",
                    "metadata": meta}

    def fleet_snapshot(self):
        """``/fleet/snapshot`` body: per-rank status + latest snapshots,
        plus the straggler summary."""
        with self._lock:
            ranks = {str(r): {"status": dict(self._status.get(r, {})),
                              "snapshot": self._rank_snapshots.get(r)}
                     for r in sorted(set(self._status)
                                     | set(self._rank_snapshots))}
            buffered = len(self._events)
        doc = {"ranks": ranks,
               "straggler": self.detector.gauges(),
               "events_buffered": buffered}
        if self.slo is not None:
            doc["alerts"] = self.slo.alerts_doc()[1]
        return doc

    def write_merged_trace(self, path):
        with open(path, "w") as f:
            json.dump(self.merged_trace(), f)
        return path

    # -- serving + background scraping ----------------------------------
    def serve(self, port=0, host="127.0.0.1", scrape_on_request=True):
        """Expose ``/fleet/metrics``, ``/fleet/trace``, ``/fleet/snapshot``
        (and ``/alerts`` when an SLO engine is attached) on a background
        :class:`TelemetryServer`. With ``scrape_on_request`` every request
        triggers a fresh scrape first — no background thread needed for
        on-demand use; combine with :meth:`start` for a fixed cadence."""
        srv = TelemetryServer(host=host, port=port)

        def _maybe_scrape():
            if scrape_on_request:
                self.scrape()

        def _metrics():
            _maybe_scrape()
            return self.render_prometheus()

        def _trace():
            _maybe_scrape()
            return self.merged_trace()

        def _snapshot():
            _maybe_scrape()
            return self.fleet_snapshot()

        srv.add_text_route("/fleet/metrics", _metrics,
                           "text/plain; version=0.0.4; charset=utf-8")
        srv.add_json_route("/fleet/trace", _trace)
        srv.add_json_route("/fleet/snapshot", _snapshot)
        srv.add_health_provider(
            "collector",
            lambda: {"healthy": True,
                     "endpoints": len(self._endpoints),
                     "ranks_seen": len(self._status)})
        if self.slo is not None:
            self.slo.attach(srv)
        self._server = srv.start()
        return srv

    @property
    def server(self):
        return self._server

    def start(self, interval_s=5.0):
        """Scrape on a fixed cadence from a daemon thread."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def _loop():
            while not self._stop.wait(interval_s):
                try:
                    self.scrape()
                except Exception:
                    pass    # a failed pass must not kill the cadence

        self._thread = threading.Thread(
            target=_loop, name="fleet-collector", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        """Stop the scrape cadence and the server (if any)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._server is not None:
            self._server.stop()
            self._server = None
