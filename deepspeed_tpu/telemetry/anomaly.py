"""Straggler and step-time-spike detection over per-rank step durations.

Fed from the step spans the engines already emit
(``train/fwd_bwd_opt_step``, ``serving/decode_step``,
``pipe/compiled_step``), normally by the fleet collector as it merges
scraped traces — the hot loops themselves never call into this module.

Two complementary detectors, keyed per ``(span name, rank)`` so train and
serve distributions never mix:

- **Cross-rank straggler** (:meth:`StragglerDetector.update`): compares
  each rank's rolling mean step time against the fleet. A rank is the
  straggler when its mean exceeds the median of the other ranks by
  ``skew_threshold``× (robust at any fleet size, including 2 workers,
  where a z-score is degenerate — every rank sits exactly 1σ from the
  mean) OR, with >= 3 ranks, when its z-score over the per-rank means
  exceeds ``z_threshold``.
- **Per-rank spike** (:meth:`StragglerDetector.observe`): a single step
  ``spike_factor``× slower than that rank's own rolling median — a
  transient stall (GC pause, preemption signal, page fault storm) rather
  than a sustained skew. A rank that is *consistently* slow stops
  spiking (its own median catches up) and shows up as the straggler
  instead.

Detected anomalies are drained by :meth:`update` as event dicts (the
collector turns them into ``fleet/straggler`` / ``fleet/step_spike``
instants on the merged timeline) and summarized as gauges
(``Fleet/straggler_rank``, ``Fleet/step_time_skew``).

Stdlib-only (see ``telemetry/trace.py``).
"""

import statistics
import threading
from collections import deque

from deepspeed_tpu.telemetry.trace import PH_COMPLETE

# Span names treated as "one step" for straggler accounting.
STEP_SPAN_NAMES = frozenset({
    "train/fwd_bwd_opt_step",
    "train/forward_backward",
    "serving/decode_step",
    "pipe/compiled_step",
})


class StragglerDetector:
    """Rolling per-(span, rank) step-duration stats with anomaly events."""

    def __init__(self, window=64, min_samples=4, z_threshold=3.0,
                 skew_threshold=2.0, spike_factor=8.0, min_spike_s=0.001,
                 span_names=STEP_SPAN_NAMES):
        self.window = int(window)
        self.min_samples = int(min_samples)
        self.z_threshold = float(z_threshold)
        self.skew_threshold = float(skew_threshold)
        self.spike_factor = float(spike_factor)
        self.min_spike_s = float(min_spike_s)
        # None accepts every span name (caller pre-filters)
        self.span_names = (frozenset(span_names)
                           if span_names is not None else None)
        self._lock = threading.Lock()
        self._durs = {}          # (span name, rank) -> deque of seconds
        self._pending = []       # anomaly events awaiting update()
        self.straggler_rank = -1
        self.step_time_skew = 1.0
        self.spikes_total = 0
        self.stragglers_total = 0

    # -- feeding --------------------------------------------------------
    def observe(self, rank, name, dur_s):
        """Record one step duration (seconds) for ``rank``."""
        if self.span_names is not None and name not in self.span_names:
            return
        key = (name, int(rank))
        dur_s = float(dur_s)
        with self._lock:
            d = self._durs.get(key)
            if d is None:
                d = self._durs[key] = deque(maxlen=self.window)
            # spike test against the rank's OWN history, before appending
            if len(d) >= self.min_samples:
                med = statistics.median(d)
                if med > 0 and dur_s > self.spike_factor * med \
                        and dur_s > self.min_spike_s:
                    self.spikes_total += 1
                    self._pending.append(
                        {"type": "step_spike", "rank": key[1], "span": name,
                         "dur_s": dur_s, "median_s": med,
                         "factor": dur_s / med})
            d.append(dur_s)

    def observe_events(self, rank, events):
        """Feed Chrome trace event dicts (complete spans whose name is a
        step span); returns how many were consumed."""
        n = 0
        for ev in events:
            if ev.get("ph") != PH_COMPLETE:
                continue
            name = ev.get("name")
            if self.span_names is not None and name not in self.span_names:
                continue
            self.observe(rank, name, float(ev.get("dur", 0.0)) / 1e6)
            n += 1
        return n

    # -- detection ------------------------------------------------------
    def update(self):
        """Recompute cross-rank stats; returns (and drains) the pending
        anomaly events. Straggler events are edge-triggered — emitted when
        the straggler rank appears or changes, not every pass — while the
        ``straggler_rank``/``step_time_skew`` gauges track continuously."""
        with self._lock:
            by_name = {}     # span name -> {rank: rolling mean}
            for (name, rank), d in self._durs.items():
                if len(d) >= self.min_samples:
                    by_name.setdefault(name, {})[rank] = statistics.fmean(d)
            worst = None     # (skew, rank, span name, z)
            for name, means in by_name.items():
                if len(means) < 2:
                    continue
                ranks = sorted(means, key=means.get)
                slow, slow_mean = ranks[-1], means[ranks[-1]]
                ref = statistics.median([means[r] for r in ranks[:-1]])
                if ref > 0:
                    skew = slow_mean / ref
                elif slow_mean > 0:
                    skew = float("inf")
                else:
                    skew = 1.0
                z = 0.0
                if len(means) >= 3:
                    sd = statistics.pstdev(means.values())
                    if sd > 0:
                        z = (slow_mean - statistics.fmean(means.values())) / sd
                if worst is None or skew > worst[0]:
                    worst = (skew, slow, name, z)
            prev = self.straggler_rank
            if worst is None:
                self.straggler_rank = -1
                self.step_time_skew = 1.0
            else:
                skew, rank, name, z = worst
                self.step_time_skew = skew
                is_straggler = (skew >= self.skew_threshold
                                or z >= self.z_threshold)
                self.straggler_rank = rank if is_straggler else -1
                if is_straggler and rank != prev:
                    self.stragglers_total += 1
                    self._pending.append(
                        {"type": "straggler", "rank": rank, "span": name,
                         "skew": skew, "z": z})
            out, self._pending = self._pending, []
            return out

    def gauges(self):
        """Flat summary for ``/fleet/metrics`` rollups."""
        with self._lock:
            return {
                "straggler_rank": float(self.straggler_rank),
                "step_time_skew": float(self.step_time_skew),
                "step_spikes_total": float(self.spikes_total),
                "stragglers_total": float(self.stragglers_total),
            }

    def reset(self):
        with self._lock:
            self._durs.clear()
            self._pending.clear()
            self.straggler_rank = -1
            self.step_time_skew = 1.0
            self.spikes_total = 0
            self.stragglers_total = 0
