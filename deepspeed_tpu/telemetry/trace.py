"""Chrome-trace span/event tracer: bounded ring buffer, deferred rendering.

Design constraints (they are the whole point):

- **Hot-path work is timestamps only.** Opening/closing a span records two
  ``time.perf_counter()`` floats and appends ONE tuple to a
  ``collections.deque`` — no string formatting, no dict churn, no JSON, no
  host syncs, so jaxlint JL002 and ``transfer_free()`` stay green when the
  training/serving hot loops are traced. All Chrome-trace-event rendering
  is deferred to :meth:`Tracer.events` / :meth:`Tracer.to_chrome_trace`,
  which run off the hot path (test asserts, ``/trace`` scrapes, shutdown).
- **Bounded.** The ring buffer is ``deque(maxlen=max_events)``: a
  long-running server drops the oldest spans instead of growing without
  limit. Dropped-event count is tracked so a truncated trace says so.
- **Provably free when disabled.** ``span()`` on a disabled tracer returns
  a single module-level no-op object (``NULL_SPAN``) — no per-call
  allocation — and ``instant()`` returns before touching the clock. Hot
  loops additionally guard on ``tracer.enabled`` (one attribute read) so
  even argument construction is skipped.

The emitted JSON is the Chrome trace event format (load in Perfetto or
``chrome://tracing``): complete events ``ph="X"`` with ``ts``/``dur`` in
microseconds, instant events ``ph="i"``, one ``pid`` per process and the
recording thread's ident as ``tid``.

This module is stdlib-only on purpose: the launcher supervisor (itself
stdlib-only) serves traces too, and must not drag jax into its process.
"""

import json
import os
import threading
import time
from collections import deque

PH_COMPLETE = "X"
PH_INSTANT = "i"
PH_METADATA = "M"

_DEFAULT_MAX_EVENTS = 65536


class _NullSpan:
    """Shared no-op context manager returned by disabled tracers."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


NULL_SPAN = _NullSpan()


class _Span:
    """Live span: ``__enter__`` stamps t0, ``__exit__`` stamps t1 and
    appends one tuple. Everything else happens at render time."""

    __slots__ = ("_tracer", "_name", "_cat", "_args", "_t0")

    def __init__(self, tracer, name, cat, args):
        self._tracer = tracer
        self._name = name
        self._cat = cat
        self._args = args
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        self._tracer._append(
            (PH_COMPLETE, self._name, self._cat, self._t0, t1 - self._t0,
             threading.get_ident(), self._args))
        return False


class Tracer:
    """Span/instant recorder over a bounded ring buffer."""

    def __init__(self, enabled=False, max_events=_DEFAULT_MAX_EVENTS):
        self.enabled = bool(enabled)
        self._events = deque(maxlen=int(max_events))
        self._dropped = 0
        # Two clocks sampled back-to-back: ts values are rendered relative
        # to the perf_counter epoch (monotonic, sub-us), while epoch_unix
        # pins that epoch to wall-clock time so a fleet collector can
        # rebase traces from different processes onto one timeline.
        self._epoch = time.perf_counter()
        self.epoch_unix = time.time()
        self._process_info = None
        self._lock = threading.Lock()   # drain/render only; appends rely on GIL

    # -- configuration --------------------------------------------------
    def configure(self, enabled, max_events=None):
        """Re-arm (or disarm) the tracer in place. Shrinking ``max_events``
        keeps the newest events. Used by ``telemetry.configure_from_config``
        so engines constructed later see the same global tracer."""
        if max_events is not None and int(max_events) != self._events.maxlen:
            with self._lock:
                self._events = deque(self._events, maxlen=int(max_events))
        self.enabled = bool(enabled)
        return self

    @property
    def max_events(self):
        return self._events.maxlen

    def set_process_info(self, rank=None, role=None, label=None,
                         sort_index=None):
        """Stamp process identity onto the trace. Rendered as Chrome ``M``
        (metadata) records — ``process_name``/``process_sort_index`` — so a
        single-process trace opens in Perfetto with a named lane and a
        multi-rank merge needs no guesswork. ``None`` fields leave any
        previously-set value alone; repeated calls merge."""
        info = dict(self._process_info or {})
        if rank is not None:
            info["rank"] = int(rank)
        if role is not None:
            info["role"] = str(role)
        if label is not None:
            info["label"] = str(label)
        if sort_index is not None:
            info["sort_index"] = int(sort_index)
        self._process_info = info or None
        return self

    @property
    def process_info(self):
        return dict(self._process_info) if self._process_info else None

    # -- hot path -------------------------------------------------------
    def span(self, name, cat="train", args=None):
        """Context manager timing a region. ``args`` must be a dict of
        JSON-serializable host values (request ids, counts) or None."""
        if not self.enabled:
            return NULL_SPAN
        return _Span(self, name, cat, args)

    def instant(self, name, cat="lifecycle", args=None):
        """Point-in-time event (lifecycle transitions: rollback,
        preemption, restart, elastic resume, recompile)."""
        if not self.enabled:
            return
        self._append((PH_INSTANT, name, cat, time.perf_counter(), 0.0,
                      threading.get_ident(), args))

    def _append(self, rec):
        if len(self._events) == self._events.maxlen:
            self._dropped += 1
        self._events.append(rec)

    # -- cold path ------------------------------------------------------
    def __len__(self):
        return len(self._events)

    @property
    def dropped(self):
        return self._dropped

    def clear(self):
        with self._lock:
            self._events.clear()
            self._dropped = 0

    def events(self, drain=False):
        """Render the buffered records as Chrome trace event dicts
        (oldest first). ``drain=True`` empties the ring buffer."""
        with self._lock:
            if drain:
                recs = []
                while True:
                    try:
                        recs.append(self._events.popleft())
                    except IndexError:
                        break
            else:
                recs = list(self._events)
        pid = os.getpid()
        out = self._metadata_events(pid)
        for ph, name, cat, t0, dur, tid, args in recs:
            ev = {
                "ph": ph,
                "name": name,
                "cat": cat,
                "ts": (t0 - self._epoch) * 1e6,
                "pid": pid,
                "tid": tid,
            }
            if ph == PH_COMPLETE:
                ev["dur"] = dur * 1e6
            else:
                ev["s"] = "t"       # instant scope: thread
            if args:
                ev["args"] = dict(args)
            out.append(ev)
        return out

    def _metadata_events(self, pid):
        """Chrome ``M`` records for process identity (empty when unset).
        Synthesized at render time so they survive ``drain=True`` and ring
        overflow; ``ts``/``tid`` are zero by Chrome convention but present
        so every emitted event carries the same required keys."""
        info = self._process_info
        if not info:
            return []
        rank = info.get("rank")
        role = info.get("role")
        label = info.get("label")
        if label is None:
            parts = ([str(role)] if role is not None else []) \
                + ([f"rank{rank}"] if rank is not None else [])
            label = " ".join(parts) or f"pid{pid}"
        sort_index = info.get(
            "sort_index", rank if isinstance(rank, int) and rank >= 0 else 0)
        name_args = {"name": label, "os_pid": pid}
        if rank is not None:
            name_args["rank"] = rank
        if role is not None:
            name_args["role"] = role
        return [
            {"ph": PH_METADATA, "name": "process_name", "cat": "__metadata",
             "ts": 0, "pid": pid, "tid": 0, "args": name_args},
            {"ph": PH_METADATA, "name": "process_sort_index",
             "cat": "__metadata", "ts": 0, "pid": pid, "tid": 0,
             "args": {"sort_index": sort_index}},
        ]

    def to_chrome_trace(self, drain=False):
        """The full JSON-object trace form Perfetto/chrome://tracing load."""
        meta = {"epoch_unix": self.epoch_unix}
        if self._process_info:
            meta.update(self._process_info)
        if self._dropped:
            meta["dropped_events"] = self._dropped
        return {"traceEvents": self.events(drain=drain),
                "displayTimeUnit": "ms",
                "metadata": meta}

    def write(self, path, drain=False):
        doc = self.to_chrome_trace(drain=drain)
        with open(path, "w") as f:
            json.dump(doc, f)
        return path
