"""Declarative SLO engine evaluated over registry/snapshot values.

Rules live in the ``telemetry`` ds_config block::

    "telemetry": {"enabled": true,
                  "slo": [{"metric": "Serving/ttft_p95_s", "max": 0.5, "for_s": 30},
                          {"metric": "Serving/accept_rate", "min": 0.3},
                          {"metric": "Train/Samples/mfu",   "min": 0.2, "for_s": 60},
                          {"metric": "Jax/recompiles_total", "max": 8}],
                  "slo_policy": "warn"}      # or "fail"

Evaluation is pull-based and cheap: the caller hands :meth:`SloEngine.evaluate`
a flat ``{tag: value}`` mapping (a registry ``as_dict()``, a serving
snapshot prefixed with ``Serving/``, or the collector's fleet rollups) —
no rule ever runs inside a jit'd region or forces a device sync. A breach
must persist ``for_s`` seconds before the rule *fires* (hysteresis against
single-step blips); recovery resets both the clock and the firing state.

Firing emits one ``slo/alert`` instant into the trace timeline and bumps
``Slo/alerts_total``. Under ``policy="fail"`` it also raises
:class:`SloViolationError`, so a worker process dies nonzero and the
supervisor's exit-code contract (restart/quarantine) takes over; the
default ``"warn"`` only logs/exposes. ``/alerts`` (attach via
:meth:`SloEngine.attach`) mirrors ``/healthz``: HTTP 200 while quiet,
503 while any rule is firing, per-rule detail either way.

Metric lookup resolves aliases so rules read naturally: ``Serving/<k>``
also matches the pull-gauge name ``Serving/Snapshot/<k>``, ``Router/<k>``
matches the fleet router's gauges ``Fleet/router/<k>`` (so a rule like
``{"metric": "Router/shed_rate", "max": 0.1}`` alerts on overload
shedding), and at the fleet level a rule matches its worst-case rollup
(``Fleet/<metric>/max`` for ceilings, ``Fleet/<metric>/min`` for
floors).

Stdlib-only (see ``telemetry/trace.py``).
"""

import threading
import time

SLO_POLICIES = ("warn", "fail")

_RULE_KEYS = frozenset({"metric", "min", "max", "for_s"})


def validate_slo_rule(raw, where="telemetry.slo"):
    """Validate one raw rule dict; returns a normalized copy. The single
    source of truth — ``DeepSpeedTelemetryConfig`` calls this too."""
    if not isinstance(raw, dict):
        raise ValueError(f"{where}: each rule must be a dict, got {raw!r}")
    unknown = set(raw) - _RULE_KEYS
    if unknown:
        raise ValueError(
            f"{where}: unknown rule key(s) {sorted(unknown)} in {raw!r} "
            f"(allowed: {sorted(_RULE_KEYS)})")
    metric = raw.get("metric")
    if not isinstance(metric, str) or not metric:
        raise ValueError(f"{where}: 'metric' must be a non-empty string, "
                         f"got {metric!r}")
    out = {"metric": metric, "min": None, "max": None, "for_s": 0.0}
    for bound in ("min", "max"):
        v = raw.get(bound)
        if v is None:
            continue
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            raise ValueError(f"{where}: '{bound}' must be a number, got {v!r}")
        out[bound] = float(v)
    if out["min"] is None and out["max"] is None:
        raise ValueError(f"{where}: rule for '{metric}' needs 'min' and/or "
                         f"'max'")
    for_s = raw.get("for_s", 0.0)
    if isinstance(for_s, bool) or not isinstance(for_s, (int, float)) \
            or for_s < 0:
        raise ValueError(f"{where}: 'for_s' must be a number >= 0, "
                         f"got {for_s!r}")
    out["for_s"] = float(for_s)
    return out


class SloViolationError(RuntimeError):
    """Raised by ``policy="fail"`` when a rule fires."""

    def __init__(self, metric, value, bound_kind, bound, for_s):
        self.metric = metric
        self.value = value
        self.bound_kind = bound_kind
        self.bound = bound
        self.for_s = for_s
        super().__init__(
            f"SLO violated: {metric}={value:.6g} breached "
            f"{bound_kind}={bound:.6g} (sustained >= {for_s:.6g}s)")


class SloRule:
    """One validated rule: a metric with a floor and/or ceiling and a
    persistence requirement."""

    __slots__ = ("metric", "min", "max", "for_s")

    def __init__(self, metric, min=None, max=None, for_s=0.0):
        norm = validate_slo_rule(
            {"metric": metric, "min": min, "max": max, "for_s": for_s})
        self.metric = norm["metric"]
        self.min = norm["min"]
        self.max = norm["max"]
        self.for_s = norm["for_s"]

    def breached(self, value):
        return (self.max is not None and value > self.max) or \
               (self.min is not None and value < self.min)

    def as_dict(self):
        return {"metric": self.metric, "min": self.min, "max": self.max,
                "for_s": self.for_s}


class SloEngine:
    """Evaluates rules against value snapshots with ``for_s`` hysteresis."""

    def __init__(self, rules, policy="warn", tracer=None, registry=None,
                 clock=time.monotonic):
        if policy not in SLO_POLICIES:
            raise ValueError(f"slo_policy must be one of {SLO_POLICIES}, "
                             f"got {policy!r}")
        self.rules = [r if isinstance(r, SloRule)
                      else SloRule(**validate_slo_rule(r)) for r in rules]
        self.policy = policy
        self._tracer = tracer
        self._registry = registry
        self._clock = clock
        self._lock = threading.Lock()
        self._state = [{"breach_since": None, "firing": False,
                        "fired_count": 0, "last_value": None}
                       for _ in self.rules]

    @classmethod
    def from_config(cls, telemetry_config, tracer=None, registry=None,
                    clock=time.monotonic):
        """Build from a :class:`DeepSpeedTelemetryConfig`; None when the
        block declares no rules."""
        if telemetry_config is None or not telemetry_config.slo_rules:
            return None
        return cls(telemetry_config.slo_rules,
                   policy=telemetry_config.slo_policy,
                   tracer=tracer, registry=registry, clock=clock)

    # -- evaluation -----------------------------------------------------
    @staticmethod
    def _lookup(values, rule):
        """Resolve a rule's metric against a value mapping via aliases
        (docstring above). Non-numeric / absent → None (rule is skipped
        and its breach clock resets: missing data is not a breach)."""
        candidates = [rule.metric]
        if rule.metric.startswith("Serving/"):
            candidates.append("Serving/Snapshot/" + rule.metric[len("Serving/"):])
        # router counters export under Fleet/router/* (router.py
        # export_gauges); let rules name them the short way, e.g.
        # "Router/shed_rate" -> Fleet/router/shed_rate
        if rule.metric.startswith("Router/"):
            candidates.append("Fleet/router/" + rule.metric[len("Router/"):])
        worst = "max" if rule.max is not None else "min"
        candidates += [f"Fleet/{c}/{worst}" for c in list(candidates)]
        for c in candidates:
            v = values.get(c)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                return float(v)
        return None

    def evaluate(self, values, now=None):
        """One evaluation pass. Returns the rules that NEWLY fired this
        pass (already-firing rules are not re-reported); raises
        :class:`SloViolationError` for the first of them under
        ``policy="fail"``."""
        if now is None:
            now = self._clock()
        newly = []
        with self._lock:
            for rule, st in zip(self.rules, self._state):
                v = self._lookup(values, rule)
                st["last_value"] = v
                if v is None or not rule.breached(v):
                    st["breach_since"] = None
                    st["firing"] = False
                    continue
                if st["breach_since"] is None:
                    st["breach_since"] = now
                if not st["firing"] and now - st["breach_since"] >= rule.for_s:
                    st["firing"] = True
                    st["fired_count"] += 1
                    newly.append((rule, v))
            firing_now = sum(1 for st in self._state if st["firing"])
        # instants/counters outside the lock: tracer/registry have their own
        for rule, v in newly:
            if self._tracer is not None:
                self._tracer.instant(
                    "slo/alert", cat="slo",
                    args={"metric": rule.metric, "value": v,
                          "min": rule.min, "max": rule.max,
                          "for_s": rule.for_s})
            if self._registry is not None:
                self._registry.counter(
                    "Slo/alerts_total",
                    help="SLO rule firing transitions").inc()
        if self._registry is not None:
            self._registry.gauge(
                "Slo/firing", help="SLO rules currently firing").set(
                float(firing_now))
        if newly and self.policy == "fail":
            rule, v = newly[0]
            kind, bound = (("max", rule.max) if rule.max is not None
                           and v > rule.max else ("min", rule.min))
            raise SloViolationError(rule.metric, v, kind, bound, rule.for_s)
        return [rule for rule, _ in newly]

    # -- exposition -----------------------------------------------------
    def firing(self):
        """Rules currently firing, as dicts."""
        with self._lock:
            return [rule.as_dict()
                    for rule, st in zip(self.rules, self._state)
                    if st["firing"]]

    def alerts_doc(self):
        """``(status, doc)`` for ``/alerts``: 503 while anything fires."""
        now = self._clock()
        rules = []
        firing = 0
        with self._lock:
            for rule, st in zip(self.rules, self._state):
                firing += bool(st["firing"])
                entry = dict(rule.as_dict(), firing=bool(st["firing"]),
                             fired_count=st["fired_count"],
                             last_value=st["last_value"])
                if st["breach_since"] is not None:
                    entry["breach_for_s"] = max(0.0, now - st["breach_since"])
                rules.append(entry)
        doc = {"status": "alerting" if firing else "ok",
               "firing": firing, "policy": self.policy, "rules": rules}
        return (503 if firing else 200), doc

    def attach(self, server):
        """Register ``/alerts`` on a :class:`TelemetryServer`."""
        server.add_json_route("/alerts", self.alerts_doc)
        return server
