"""Shared counter/gauge/histogram registry with Prometheus text rendering.

One naming scheme for train and serve: metrics keep the repo's existing
slash tags (``Train/Samples/train_loss``, ``Serving/tokens_per_sec``) as
their registry keys and are sanitized to Prometheus identifiers only at
exposition time (``Train/Samples/train_loss`` → ``Train_Samples_train_loss``),
so the ``Train/*`` and ``Serving/*`` families stay recognizable on
``/metrics`` and in dashboards.

Two ways metrics arrive:

- **push**: code sets gauges / bumps counters / observes histograms
  directly (``registry.gauge(tag).set(v)``);
- **monitor fan-out**: :class:`MonitorBridge` implements the repo's
  monitor interface (``record``/``flush``/``close``) so the registry rides
  the ONE ``monitor_from_config`` construction path — every existing
  ``monitor.record("Train/..."/"Serving/...")`` call in the engines
  populates the registry with no per-call-site changes. Like the other
  monitor backends it buffers at ``record`` time (values may be device
  arrays; the host transfer is deferred) and converts at ``flush``.
- **pull**: ``gauge_fn(name, fn)`` registers a callback polled at render
  time — used for live values (serving snapshot, pool occupancy,
  supervisor restart counts) that would be stale as pushed gauges.

Stdlib-only on purpose (see ``telemetry/trace.py``).
"""

import re
import threading

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")

# Latency-ish default buckets (seconds): sub-ms to tens of seconds.
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0)


def prom_name(tag):
    """Sanitize a slash tag into a legal Prometheus metric name."""
    name = _PROM_BAD.sub("_", tag)
    if name and name[0].isdigit():
        name = "_" + name
    return name or "_"


class Counter:
    """Monotonically increasing value."""

    kind = "counter"

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self.value = 0.0

    def inc(self, amount=1.0):
        if amount < 0:
            raise ValueError(f"counter '{self.name}' cannot decrease ({amount})")
        self.value += amount


class Gauge:
    """Last-write-wins value."""

    kind = "gauge"

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self.value = 0.0

    def set(self, value):
        self.value = float(value)


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics: each ``le``
    bucket counts observations <= its bound, plus ``+Inf``/sum/count)."""

    kind = "histogram"

    def __init__(self, name, buckets=DEFAULT_BUCKETS, help=""):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError(f"histogram '{name}' needs at least one bucket")
        self.counts = [0] * (len(self.buckets) + 1)   # +1 for +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, value):
        v = float(value)
        self.sum += v
        self.count += 1
        for i, bound in enumerate(self.buckets):
            if v <= bound:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def cumulative(self):
        """[(le, cumulative_count), ...] ending with ('+Inf', count)."""
        out, running = [], 0
        for bound, c in zip(self.buckets, self.counts):
            running += c
            out.append((repr(bound), running))
        out.append(("+Inf", self.count))
        return out


class MetricsRegistry:
    """Name → metric map with get-or-create accessors and renderers."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}
        self._gauge_fns = {}

    def _get_or_create(self, cls, name, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, **kwargs)
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric '{name}' already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name, help=""):
        return self._get_or_create(Counter, name, help=help)

    def gauge(self, name, help=""):
        return self._get_or_create(Gauge, name, help=help)

    def histogram(self, name, buckets=DEFAULT_BUCKETS, help=""):
        return self._get_or_create(Histogram, name, buckets=buckets, help=help)

    def gauge_fn(self, name, fn, help=""):
        """Register a pull gauge: ``fn()`` is called at render time and may
        return a float, a flat {suffix: float} dict (rendered as
        ``name/suffix``), or None to skip."""
        with self._lock:
            self._gauge_fns[name] = (fn, help)

    def unregister(self, name):
        with self._lock:
            self._metrics.pop(name, None)
            self._gauge_fns.pop(name, None)

    def reset(self):
        with self._lock:
            self._metrics.clear()
            self._gauge_fns.clear()

    # -- rendering ------------------------------------------------------
    def _pulled(self):
        """Materialize callback gauges as (name, help, value) rows."""
        with self._lock:
            fns = list(self._gauge_fns.items())
        rows = []
        for name, (fn, help) in fns:
            try:
                v = fn()
            except Exception:
                continue    # a broken callback must not take down /metrics
            if v is None:
                continue
            if isinstance(v, dict):
                for suffix, sub in v.items():
                    if isinstance(sub, (int, float)) and not isinstance(sub, bool):
                        rows.append((f"{name}/{suffix}", help, float(sub)))
            else:
                rows.append((name, help, float(v)))
        return rows

    def render_prometheus(self):
        """Prometheus text exposition format (version 0.0.4)."""
        with self._lock:
            metrics = list(self._metrics.values())
        lines = []
        for m in metrics:
            pname = prom_name(m.name)
            if m.help:
                lines.append(f"# HELP {pname} {m.help}")
            lines.append(f"# TYPE {pname} {m.kind}")
            if m.kind == "histogram":
                for le, c in m.cumulative():
                    lines.append(f'{pname}_bucket{{le="{le}"}} {c}')
                lines.append(f"{pname}_sum {m.sum}")
                lines.append(f"{pname}_count {m.count}")
            else:
                lines.append(f"{pname} {m.value}")
        for name, help, value in self._pulled():
            pname = prom_name(name)
            if help:
                lines.append(f"# HELP {pname} {help}")
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname} {value}")
        return "\n".join(lines) + "\n"

    def as_dict(self, pulled=True):
        """JSON-friendly snapshot of everything (raw slash names).
        ``pulled=False`` skips the callback gauges — for callers that
        evaluate every step and already hold the live values (the serving
        engine's SLO pass)."""
        with self._lock:
            metrics = list(self._metrics.values())
        out = {}
        for m in metrics:
            if m.kind == "histogram":
                out[m.name] = {"sum": m.sum, "count": m.count,
                               "buckets": dict(m.cumulative())}
            else:
                out[m.name] = m.value
        if pulled:
            for name, _help, value in self._pulled():
                out[name] = value
        return out


# Tags routed to histograms (not last-value gauges) when they arrive via
# the monitor fan-out: latency distributions where p95 matters.
HISTOGRAM_TAGS = frozenset({"Serving/ttft_s"})


class MonitorBridge:
    """Monitor-interface adapter feeding a :class:`MetricsRegistry`.

    Appended to the ``monitor_from_config`` fan-out when telemetry is
    enabled. ``record`` buffers (tag, value) — values may be device
    arrays, and converting them would be a host sync on the training hot
    path, so the transfer is deferred exactly like the tensorboard/csv
    backends do. ``flush`` converts and applies. A bounded auto-flush
    keeps the pending buffer (and /metrics staleness) in check for
    callers that record per step but flush rarely.
    """

    def __init__(self, registry, histogram_tags=HISTOGRAM_TAGS,
                 auto_flush_every=512, rank=0):
        self.registry = registry
        self.enabled = rank == 0
        self._histogram_tags = frozenset(histogram_tags)
        self._auto_flush_every = int(auto_flush_every)
        self._pending = []

    def record(self, tag, value, step):
        if not self.enabled:
            return
        self._pending.append((tag, value, step))
        if len(self._pending) >= self._auto_flush_every:
            self.flush()

    def flush(self):
        if not self._pending:
            return
        pending, self._pending = self._pending, []
        for tag, value, step in pending:
            v = float(value)
            if tag in self._histogram_tags:
                self.registry.histogram(tag).observe(v)
            else:
                self.registry.gauge(tag).set(v)
            self.registry.counter(f"{tag}/samples_total").inc()

    def close(self):
        self.flush()
