"""Unified telemetry: trace spans, metrics registry, introspection endpoint.

One process-global :class:`Tracer` and :class:`MetricsRegistry`, so spans
and gauges recorded by the training engines, the serving engine, the
resilience layer and the sentinels all land in the same timeline and the
same ``/metrics`` page. Both start disabled/empty; a ds_config with a
``telemetry`` block arms them via :func:`configure_from_config` (an
absent block leaves the global state alone, so a telemetry-armed process
can construct helper engines without disarming itself).

Hot-path cost when disabled: ``get_tracer().enabled`` is False, ``span()``
returns the shared ``NULL_SPAN`` singleton, ``instant()`` returns before
touching the clock — nothing is recorded and nothing is allocated.

Stdlib-only (no jax/numpy): importable from the launcher supervisor.
"""

import os

from deepspeed_tpu.telemetry.trace import NULL_SPAN, Tracer  # noqa: F401
from deepspeed_tpu.telemetry.registry import (  # noqa: F401
    DEFAULT_BUCKETS,
    HISTOGRAM_TAGS,
    MetricsRegistry,
    MonitorBridge,
    prom_name,
)
from deepspeed_tpu.telemetry.server import TelemetryServer  # noqa: F401
from deepspeed_tpu.telemetry.slo import (  # noqa: F401
    SloEngine,
    SloRule,
    SloViolationError,
    validate_slo_rule,
)
from deepspeed_tpu.telemetry.anomaly import (  # noqa: F401
    STEP_SPAN_NAMES,
    StragglerDetector,
)
from deepspeed_tpu.telemetry.collector import FleetCollector  # noqa: F401
from deepspeed_tpu.telemetry.config import (  # noqa: F401
    DeepSpeedTelemetryConfig,
    TELEMETRY,
    TELEMETRY_PORT_ENV,
    resolve_http_port,
)

_tracer = Tracer(enabled=False)
_registry = MetricsRegistry()


def get_tracer():
    return _tracer


def get_registry():
    return _registry


def span(name, cat="train", args=None):
    """Module-level convenience over the global tracer (cold call sites;
    hot loops cache ``get_tracer()`` and guard on ``.enabled``)."""
    return _tracer.span(name, cat=cat, args=args)


def instant(name, cat="lifecycle", args=None):
    return _tracer.instant(name, cat=cat, args=args)


def configure(enabled, trace_max_events=None):
    """Arm/disarm the global tracer explicitly (tests, scripts)."""
    _tracer.configure(enabled, max_events=trace_max_events)
    return _tracer, _registry


def configure_from_config(telemetry_config, rank=None, role=None):
    """Apply a :class:`DeepSpeedTelemetryConfig`. A config whose
    ``telemetry`` block was absent (``configured=False``) is a no-op —
    only an explicit block changes global state.

    ``rank``/``role`` stamp process identity onto the trace (Chrome ``M``
    metadata -> named Perfetto lanes, and the key the fleet collector
    merges on). Callers that don't know their rank (scripts, serving
    without a launcher) inherit it from the ``RANK`` env var the launcher
    exports."""
    if telemetry_config is None or not telemetry_config.configured:
        return _tracer, _registry
    _tracer.configure(telemetry_config.enabled,
                      max_events=telemetry_config.trace_max_events)
    if telemetry_config.enabled:
        if rank is None:
            env_rank = os.environ.get("RANK", "").strip()
            try:
                rank = int(env_rank) if env_rank else 0
            except ValueError:
                rank = 0
        _tracer.set_process_info(rank=rank, role=role or "worker")
    return _tracer, _registry


__all__ = [
    "Tracer", "NULL_SPAN", "MetricsRegistry", "MonitorBridge",
    "TelemetryServer", "DeepSpeedTelemetryConfig", "DEFAULT_BUCKETS",
    "HISTOGRAM_TAGS", "prom_name", "get_tracer", "get_registry", "span",
    "instant", "configure", "configure_from_config",
    "FleetCollector", "StragglerDetector", "STEP_SPAN_NAMES",
    "SloEngine", "SloRule", "SloViolationError", "validate_slo_rule",
    "TELEMETRY_PORT_ENV", "resolve_http_port",
]
