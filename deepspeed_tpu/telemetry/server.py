"""Live introspection endpoint: a stdlib-only background HTTP server.

Attachable to the :class:`ServingEngine` (decode-loop liveness, serving
snapshot, KV-pool occupancy, prefix-cache stats) and to the launcher's
:class:`WorkerSupervisor` (child liveness, restart counts) — and to
anything else that can hand it a registry/tracer and a few callbacks.

Routes:

``/metrics``
    Prometheus text exposition from the attached :class:`MetricsRegistry`.
``/healthz``
    JSON liveness: ``{"status": "ok"|"unhealthy", ...}`` merged from the
    registered health providers. Any provider reporting falsy health (or
    raising) flips the status and the HTTP code to 503 — so a k8s/GCE
    probe needs no JSON parsing.
``/snapshot``
    JSON merged from the registered snapshot providers (serving metrics
    snapshot, pool occupancy, prefix-cache stats, supervisor restarts).
``/trace``
    Drains the tracer ring buffer as Chrome trace JSON (load the response
    body straight into Perfetto). ``?drain=0`` peeks without draining.
``/registry``
    The registry as raw slash-tag JSON (``MetricsRegistry.as_dict``) —
    what the fleet collector scrapes, since Prometheus-text sanitization
    would destroy the ``Train/*``/``Serving/*`` tag structure.

Custom routes can be added with :meth:`add_json_route` /
:meth:`add_text_route` (the SLO engine's ``/alerts``, the collector's
``/fleet/*`` family).

The server runs on a daemon thread (``ThreadingHTTPServer``), binds
127.0.0.1 by default, and ``port=0`` picks an ephemeral port (tests).
Request handling never touches the hot path: scrapes read the registry
under its lock and render off-thread.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlparse, parse_qs


class TelemetryServer:
    """Background HTTP server over a registry + tracer + provider callbacks."""

    def __init__(self, registry=None, tracer=None, host="127.0.0.1", port=0):
        self.registry = registry
        self.tracer = tracer
        self._host = host
        self._port = int(port)
        self._httpd = None
        self._thread = None
        self._snapshot_providers = {}
        self._health_providers = {}
        self._json_routes = {}
        self._text_routes = {}

    # -- wiring ---------------------------------------------------------
    def add_json_route(self, path, fn):
        """Serve ``fn()`` as JSON at ``path``. ``fn`` may return either a
        document (sent with 200) or a ``(status, document)`` pair — the
        latter gives routes ``/healthz``-style status semantics (the SLO
        engine's ``/alerts`` answers 503 while any rule is firing)."""
        self._json_routes[path.rstrip("/") or "/"] = fn
        return self

    def add_text_route(self, path, fn,
                       content_type="text/plain; charset=utf-8"):
        """Serve ``fn()`` (a string, or ``(status, string)``) at ``path``."""
        self._text_routes[path.rstrip("/") or "/"] = (fn, content_type)
        return self
    def add_snapshot_provider(self, name, fn):
        """``fn()`` → JSON-serializable value, merged into ``/snapshot``
        under ``name``. A raising provider reports its error string."""
        self._snapshot_providers[name] = fn
        return self

    def add_health_provider(self, name, fn):
        """``fn()`` → truthy (healthy) / falsy (unhealthy), or a dict with
        a boolean ``"healthy"`` key plus detail fields for ``/healthz``."""
        self._health_providers[name] = fn
        return self

    # -- lifecycle ------------------------------------------------------
    @property
    def port(self):
        return self._httpd.server_address[1] if self._httpd else self._port

    @property
    def url(self):
        return f"http://{self._host}:{self.port}"

    def start(self):
        if self._httpd is not None:
            return self
        server = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):   # noqa: A003 - silence stderr
                pass

            def do_GET(self):
                server._handle(self)

        self._httpd = ThreadingHTTPServer((self._host, self._port), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="telemetry-http",
            daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._httpd = None
        self._thread = None

    # -- request handling ------------------------------------------------
    def _handle(self, handler):
        parsed = urlparse(handler.path)
        route = parsed.path.rstrip("/") or "/"
        try:
            if route == "/metrics":
                body = (self.registry.render_prometheus()
                        if self.registry is not None else "")
                self._send(handler, 200, body,
                           "text/plain; version=0.0.4; charset=utf-8")
            elif route == "/healthz":
                status, doc = self._health()
                self._send_json(handler, status, doc)
            elif route == "/snapshot":
                self._send_json(handler, 200, self._snapshot())
            elif route == "/trace":
                qs = parse_qs(parsed.query)
                drain = qs.get("drain", ["1"])[0] not in ("0", "false")
                doc = (self.tracer.to_chrome_trace(drain=drain)
                       if self.tracer is not None
                       else {"traceEvents": []})
                self._send_json(handler, 200, doc)
            elif route == "/registry":
                # raw slash-tag JSON view of the registry: what the fleet
                # collector scrapes (parsing Prometheus text would lose
                # the Train/*, Serving/* tag structure to sanitization)
                doc = (self.registry.as_dict()
                       if self.registry is not None else {})
                self._send_json(handler, 200, doc)
            elif route in self._json_routes:
                res = self._json_routes[route]()
                status, doc = res if isinstance(res, tuple) else (200, res)
                self._send_json(handler, status, doc)
            elif route in self._text_routes:
                fn, ctype = self._text_routes[route]
                res = fn()
                status, body = res if isinstance(res, tuple) else (200, res)
                self._send(handler, status, body, ctype)
            else:
                routes = ["/metrics", "/healthz", "/snapshot", "/trace",
                          "/registry"]
                routes += sorted(set(self._json_routes) | set(self._text_routes))
                self._send_json(handler, 404, {"error": f"no route {route}",
                                               "routes": routes})
        except Exception as e:   # a broken provider must not kill the thread
            self._send_json(handler, 500, {"error": repr(e)})

    def _health(self):
        doc, healthy = {}, True
        for name, fn in list(self._health_providers.items()):
            try:
                v = fn()
            except Exception as e:
                doc[name] = {"healthy": False, "error": repr(e)}
                healthy = False
                continue
            if isinstance(v, dict):
                ok = bool(v.get("healthy", True))
                doc[name] = v
            else:
                ok = bool(v)
                doc[name] = {"healthy": ok}
            healthy = healthy and ok
        doc["status"] = "ok" if healthy else "unhealthy"
        return (200 if healthy else 503), doc

    def _snapshot(self):
        doc = {}
        for name, fn in list(self._snapshot_providers.items()):
            try:
                doc[name] = fn()
            except Exception as e:
                doc[name] = {"error": repr(e)}
        return doc

    @staticmethod
    def _send(handler, status, body, content_type):
        data = body.encode("utf-8")
        handler.send_response(status)
        handler.send_header("Content-Type", content_type)
        handler.send_header("Content-Length", str(len(data)))
        handler.end_headers()
        handler.wfile.write(data)

    def _send_json(self, handler, status, doc):
        self._send(handler, status, json.dumps(doc, default=str),
                   "application/json")
