"""GPT-2 expressed as a PipelineModule (layer list) for pipeline parallelism.

Role parity with the reference's Megatron GPT-2 pipeline benchmark subject
(``tests/model/Megatron_GPT2`` with pipeline configs; BASELINE.json's
"GPT-2 1.5B under ZeRO-2+pipe"). The embedding and the LM head share weights
via ``TiedLayerSpec`` — the canonical use of the reference's tied-layer
machinery (pipe/module.py:71).
"""

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import flax.linen as nn

from deepspeed_tpu.models.bert import cross_entropy
from deepspeed_tpu.models.gpt2 import GPT2Config, causal_mask
from deepspeed_tpu.ops.transformer.transformer import DeepSpeedTransformerLayer
from deepspeed_tpu.runtime.pipe.module import LayerSpec, PipelineModule, TiedLayerSpec


class GPT2EmbeddingPipe(nn.Module):
    """First pipeline layer: token + position embeddings. Also the tied-weight
    owner for the LM head."""

    config: GPT2Config

    @nn.compact
    def __call__(self, input_ids):
        cfg = self.config
        init = nn.initializers.normal(stddev=cfg.initializer_range)
        wte = nn.Embed(cfg.vocab_size, cfg.hidden_size, embedding_init=init, name="wte")
        wpe = nn.Embed(cfg.max_position_embeddings, cfg.hidden_size, embedding_init=init, name="wpe")
        S = input_ids.shape[1]
        h = wte(input_ids) + wpe(jnp.arange(S)[None, :])
        return h


class GPT2BlockPipe(nn.Module):
    """One decoder layer; the causal mask is rebuilt from the static seq len."""

    config: GPT2Config

    @nn.compact
    def __call__(self, h, deterministic=None):
        cfg = self.config
        # cfg.layer_config() sets causal=True: masking happens in-kernel.
        return DeepSpeedTransformerLayer(cfg.layer_config())(h, None, deterministic=deterministic)

    @property
    def param_count(self):
        return 12 * self.config.hidden_size ** 2


class GPT2FinalNormPipe(nn.Module):
    config: GPT2Config

    @nn.compact
    def __call__(self, h):
        return nn.LayerNorm(name="ln_f")(h)


def _lm_head_forward(layer, layer_params, h):
    """Tied head: logits via the embedding matrix transpose (weight tying)."""
    wte = layer_params["params"]["wte"]["embedding"]
    return h @ wte.T.astype(h.dtype)


def gpt2_loss_fn(logits, labels):
    """Next-token LM loss (labels are the input ids)."""
    return cross_entropy(logits[:, :-1], labels[:, 1:], ignore_index=-1)


def build_gpt2_pipeline(config, num_stages, partition_method="parameters", **pipe_kwargs):
    """GPT-2 as a layer list: [tied embed, blocks..., ln_f, tied head]."""
    layers = [TiedLayerSpec("embed", GPT2EmbeddingPipe, config)]
    layers += [LayerSpec(GPT2BlockPipe, config) for _ in range(config.num_hidden_layers)]
    layers += [
        LayerSpec(GPT2FinalNormPipe, config),
        TiedLayerSpec("embed", GPT2EmbeddingPipe, config, forward_fn=_lm_head_forward),
    ]
    return PipelineModule(
        layers, num_stages=num_stages, loss_fn=gpt2_loss_fn,
        partition_method=partition_method, **pipe_kwargs,
    )
