"""GPT-2 model family.

Role parity with the reference's Megatron GPT-2 benchmark subject
(``tests/model/Megatron_GPT2``, ZeRO-2 + pipeline configs; BASELINE.json's
"GPT-2 1.5B tokens/sec under ZeRO-2+pipe"). Decoder-only transformer with
causal masking, built on the same scanned/remat encoder machinery as BERT so
the stack shards cleanly across pipe stages and the params stack maps onto
per-stage shardings.
"""

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import flax.linen as nn

from deepspeed_tpu.models.bert import cross_entropy  # noqa: F401 — public surface
from deepspeed_tpu.ops.cross_entropy import chunked_cross_entropy
from deepspeed_tpu.runtime.activation_checkpointing.checkpointing import (
    resolve_remat_policy,
)
from deepspeed_tpu.ops.transformer.transformer import (
    DeepSpeedTransformerConfig,
    DeepSpeedTransformerLayer,
)


@dataclass
class GPT2Config:
    vocab_size: int = 50304  # padded to x128
    hidden_size: int = 1600
    num_hidden_layers: int = 48
    num_attention_heads: int = 25
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    checkpoint_activations: bool = False
    # "nothing" (full recompute) or "dots" (save matmul outputs; recompute
    # only elementwise) — see models/bert.py BertConfig.checkpoint_policy.
    checkpoint_policy: str = "nothing"
    # lax.scan unroll factor for the block stack (see BertConfig.scan_unroll)
    scan_unroll: int = 1

    def __post_init__(self):
        resolve_remat_policy(self.checkpoint_policy)  # validates

    @staticmethod
    def gpt2_xl(**kw):
        """~1.5B params (the reference's Megatron GPT-2 benchmark size)."""
        return GPT2Config(**kw)

    @staticmethod
    def gpt2_small(**kw):
        d = dict(hidden_size=768, num_hidden_layers=12, num_attention_heads=12)
        d.update(kw)
        return GPT2Config(**d)

    @staticmethod
    def gpt2_medium(**kw):
        d = dict(hidden_size=1024, num_hidden_layers=24, num_attention_heads=16)
        d.update(kw)
        return GPT2Config(**d)

    @staticmethod
    def gpt2_large(**kw):
        d = dict(hidden_size=1280, num_hidden_layers=36, num_attention_heads=20)
        d.update(kw)
        return GPT2Config(**d)

    @property
    def intermediate_size(self):
        return 4 * self.hidden_size

    def layer_config(self, training=True):
        return DeepSpeedTransformerConfig(
            hidden_size=self.hidden_size,
            intermediate_size=self.intermediate_size,
            heads=self.num_attention_heads,
            attn_dropout_ratio=self.attention_probs_dropout_prob,
            hidden_dropout_ratio=self.hidden_dropout_prob,
            num_hidden_layers=self.num_hidden_layers,
            initializer_range=self.initializer_range,
            pre_layer_norm=True,
            training=training,
            causal=True,
        )


def causal_mask(seq_len, dtype=jnp.float32):
    """Additive [1,1,S,S] causal mask."""
    mask = jnp.tril(jnp.ones((seq_len, seq_len), bool))
    return jnp.where(mask, 0.0, -1e9).astype(dtype)[None, None, :, :]


class _ScannedDecoderLayer(nn.Module):
    """``deterministic`` is a static field, NOT scan carry (a traced bool there
    would break the Python-level dropout branch in the layer)."""

    layer_cfg: DeepSpeedTransformerConfig
    deterministic: bool = False

    @nn.compact
    def __call__(self, carry, _):
        h, mask = carry
        h = DeepSpeedTransformerLayer(self.layer_cfg)(h, mask, deterministic=self.deterministic)
        return (h, mask), None


class GPT2Model(nn.Module):
    config: GPT2Config
    needs_rng = True

    @nn.compact
    def __call__(self, input_ids, deterministic=False, return_hidden=False):
        cfg = self.config
        init = nn.initializers.normal(stddev=cfg.initializer_range)
        word = nn.Embed(cfg.vocab_size, cfg.hidden_size, embedding_init=init, name="wte")
        pos = nn.Embed(cfg.max_position_embeddings, cfg.hidden_size, embedding_init=init, name="wpe")

        S = input_ids.shape[1]
        h = word(input_ids) + pos(jnp.arange(S)[None, :])
        h = nn.Dropout(rate=cfg.hidden_dropout_prob)(h, deterministic=deterministic)

        # Causality is a layer-config flag (applied in-kernel on the fused
        # path); no materialized S x S mask.
        mask = None
        body = _ScannedDecoderLayer
        if cfg.checkpoint_activations:
            body = nn.remat(body, prevent_cse=False,
                            policy=resolve_remat_policy(cfg.checkpoint_policy))
        ScanStack = nn.scan(
            body,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
            length=cfg.num_hidden_layers,
            unroll=cfg.scan_unroll,
            metadata_params={nn.PARTITION_NAME: "layers"},
        )
        # Explicit stable name: keeps the param key identical whether or not
        # nn.remat wraps the body (see models/bert.py BertEncoder).
        (h, _), _ = ScanStack(cfg.layer_config(), deterministic, name="layers")((h, mask), None)
        h = nn.LayerNorm(name="ln_f")(h)
        if return_hidden:
            # training path: hand (hidden, tied table) to a chunked loss so
            # the [B,S,V] logits never materialize (ops/cross_entropy.py)
            return h, word.embedding
        logits = h @ word.embedding.T.astype(h.dtype)
        return logits


class GPT2LMHeadModel(nn.Module):
    """Language modeling objective: forward(input_ids, labels) -> scalar loss."""

    config: GPT2Config
    needs_rng = True

    @nn.compact
    def __call__(self, input_ids, labels=None, deterministic=False):
        mod = GPT2Model(self.config, name="transformer")
        if labels is None:
            return mod(input_ids, deterministic)
        # next-token prediction through the chunked CE (no [B,S,V] logits)
        h, table = mod(input_ids, deterministic, return_hidden=True)
        return chunked_cross_entropy(
            h[:, :-1], table.T.astype(h.dtype), None, labels[:, 1:],
            ignore_index=-1,
        )


def init_gpt2(config, batch_size=1, seq_len=64, seed=0):
    model = GPT2LMHeadModel(config)
    ids = jnp.zeros((batch_size, seq_len), jnp.int32)
    params = model.init(
        {"params": jax.random.PRNGKey(seed), "dropout": jax.random.PRNGKey(seed + 1)}, ids, ids
    )
    return model, params
