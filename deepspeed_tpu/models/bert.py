"""BERT model family for pretraining/fine-tuning on TPU.

Role parity with the reference's vendored BERT models
(``tests/unit/modeling.py`` post-LN / ``modelingpreln.py`` pre-LN, used as the
kernel ground truth and the BERT-large pretraining benchmark subject,
``docs/_posts/2020-05-28-fastest-bert-training.md``). Built on
``DeepSpeedTransformerLayer`` with a scanned, optionally-rematerialized encoder
stack — the idiomatic XLA shape for a deep uniform transformer (one compiled
layer body, stacked params; plays directly into pipeline stage sharding).
"""

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import flax.linen as nn

from deepspeed_tpu.ops.cross_entropy import chunked_cross_entropy
from deepspeed_tpu.ops.transformer.transformer import (
    DeepSpeedTransformerConfig,
    DeepSpeedTransformerLayer,
)
from deepspeed_tpu.runtime.activation_checkpointing.checkpointing import (
    resolve_remat_policy,
)


@dataclass
class BertConfig:
    vocab_size: int = 30528  # padded to x128 for TPU-friendly embedding matmuls
    hidden_size: int = 1024
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    intermediate_size: int = 4096
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    initializer_range: float = 0.02
    pre_layer_norm: bool = True
    checkpoint_activations: bool = False
    # remat policy when checkpoint_activations is on:
    #   "nothing" — save nothing, recompute the whole layer in backward
    #     (max memory savings, ~1 extra forward of FLOPs);
    #   "dots"    — save matmul outputs, recompute only elementwise ops
    #     (jax.checkpoint_policies.dots_with_no_batch_dims_saveable — the
    #     standard transformer trade: most of the memory win at a fraction
    #     of the recompute cost).
    checkpoint_policy: str = "nothing"
    # lax.scan unroll factor for the layer stack: >1 trades compile time and
    # code size for cross-layer XLA scheduling/fusion freedom (a perf knob;
    # bench sweeps it via BENCH_SCAN_UNROLL)
    scan_unroll: int = 1

    def __post_init__(self):
        resolve_remat_policy(self.checkpoint_policy)  # validates

    @staticmethod
    def bert_large(**kw):
        return BertConfig(**kw)

    @staticmethod
    def bert_base(**kw):
        d = dict(hidden_size=768, num_hidden_layers=12, num_attention_heads=12, intermediate_size=3072)
        d.update(kw)
        return BertConfig(**d)

    def layer_config(self, training=True):
        return DeepSpeedTransformerConfig(
            hidden_size=self.hidden_size,
            intermediate_size=self.intermediate_size,
            heads=self.num_attention_heads,
            attn_dropout_ratio=self.attention_probs_dropout_prob,
            hidden_dropout_ratio=self.hidden_dropout_prob,
            num_hidden_layers=self.num_hidden_layers,
            initializer_range=self.initializer_range,
            pre_layer_norm=self.pre_layer_norm,
            training=training,
        )


class BertEmbeddings(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids, deterministic):
        cfg = self.config
        init = nn.initializers.normal(stddev=cfg.initializer_range)
        word = nn.Embed(cfg.vocab_size, cfg.hidden_size, embedding_init=init, name="word_embeddings")
        pos = nn.Embed(cfg.max_position_embeddings, cfg.hidden_size, embedding_init=init, name="position_embeddings")
        typ = nn.Embed(cfg.type_vocab_size, cfg.hidden_size, embedding_init=init, name="token_type_embeddings")
        seq_len = input_ids.shape[1]
        positions = jnp.arange(seq_len)[None, :]
        h = word(input_ids) + pos(positions) + typ(token_type_ids)
        h = nn.LayerNorm(name="LayerNorm")(h)
        h = nn.Dropout(rate=cfg.hidden_dropout_prob)(h, deterministic=deterministic)
        return h, word.embedding


class _ScannedLayer(nn.Module):
    """Scan body: one transformer layer; params stack along the scan axis.

    ``deterministic`` is a static field (NOT part of the scan carry — a traced
    bool there would break the Python-level dropout branch in the layer).
    ``pld`` enables progressive layer drop: the scanned xs carry
    ``(layer_idx, theta)`` and the layer is stochastically bypassed with the
    PLD paper's depth scaling, keep_prob(l) = 1 - ((l+1)/L)·(1-θ) — deeper
    layers drop first. The coin draws from a dedicated "pld" RNG stream so
    the dropout stream (and thus θ=1 numerics) is untouched.

    Kept layers scale their delta by 1/p (inverted-dropout convention), so
    E[output] equals the full layer and eval (all layers, unscaled) sees the
    distribution training optimized — the reference's example-model PLD
    leaves outputs unscaled and accepts that shift. At p==1 the raw layer
    output is used unmodified, keeping θ=1 bit-identical to PLD off.
    The bypass is a select, not a branch: under a scanned stack XLA
    schedules statically, so the skipped layer's FLOPs are still executed
    (conditional skip inside scan would break flax variable lifting);
    PLD here buys the accuracy-per-sample effect, not step time."""

    layer_cfg: DeepSpeedTransformerConfig
    deterministic: bool = False
    pld: bool = False
    num_layers: int = 0

    @nn.compact
    def __call__(self, carry, xs):
        h, mask = carry
        new_h = DeepSpeedTransformerLayer(self.layer_cfg)(h, mask, deterministic=self.deterministic)
        if self.pld:
            idx, theta = xs
            p_keep = 1.0 - ((idx + 1.0) / float(self.num_layers)) * (1.0 - theta)
            keep = jax.random.bernoulli(self.make_rng("pld"), p_keep)
            inv_p = (1.0 / jnp.maximum(p_keep, 1e-6)).astype(h.dtype)
            scaled = h + (new_h - h) * inv_p
            kept_val = jnp.where(p_keep >= 1.0, new_h, scaled)
            new_h = jnp.where(keep, kept_val, h)
        return (new_h, mask), None


class BertEncoder(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, hidden_states, attention_mask, deterministic, pld_theta=None):
        cfg = self.config
        L = cfg.num_hidden_layers
        body = _ScannedLayer
        if cfg.checkpoint_activations:
            # Activation checkpointing: recompute each layer in backward
            # (reference runtime/activation_checkpointing/checkpointing.py).
            body = nn.remat(body, prevent_cse=False, static_argnums=(),
                            policy=resolve_remat_policy(cfg.checkpoint_policy))
        pld = pld_theta is not None and not deterministic
        ScanStack = nn.scan(
            body,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True, "pld": True},
            length=L,
            unroll=cfg.scan_unroll,
            metadata_params={nn.PARTITION_NAME: "layers"},
        )
        xs = None
        if pld:
            theta = jnp.asarray(pld_theta, jnp.float32)
            xs = (jnp.arange(L, dtype=jnp.float32), jnp.broadcast_to(theta, (L,)))
        # Explicit stable name: nn.remat would otherwise change the generated
        # param key ("ScanCheckpoint_ScannedLayer_0" vs "_ScannedLayer_0"),
        # breaking param trees initialized before the engine flips
        # checkpoint_activations per the ds_config.
        (h, _), _ = ScanStack(cfg.layer_config(), deterministic, pld, L,
                              name="layers")(
            (hidden_states, attention_mask), xs
        )
        return h


class BertModel(nn.Module):
    config: BertConfig
    needs_rng = True

    @nn.compact
    def __call__(self, input_ids, token_type_ids=None, attention_mask=None, deterministic=False,
                 progressive_layer_drop=False, pld_theta=None):
        cfg = self.config
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        if attention_mask is None:
            attention_mask = jnp.ones_like(input_ids)
        # additive mask [B,1,1,S]
        add_mask = (1.0 - attention_mask[:, None, None, :].astype(jnp.float32)) * -10000.0

        h, embed_table = BertEmbeddings(cfg, name="embeddings")(input_ids, token_type_ids, deterministic)
        add_mask = add_mask.astype(h.dtype)
        h = BertEncoder(cfg, name="encoder")(
            h, add_mask, deterministic,
            pld_theta=pld_theta if progressive_layer_drop else None,
        )
        pooled = nn.tanh(nn.Dense(cfg.hidden_size, name="pooler")(h[:, 0]))
        return h, pooled, embed_table


def cross_entropy(logits, labels, ignore_index=-1):
    """Masked CE in fp32; labels==ignore_index contribute 0."""
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    nll = jnp.where(valid, nll, 0.0)
    denom = jnp.maximum(valid.sum(), 1)
    return nll.sum() / denom


class BertForPreTraining(nn.Module):
    """MLM + NSP pretraining head; forward(batch...) returns scalar loss."""

    config: BertConfig
    needs_rng = True

    @nn.compact
    def __call__(self, input_ids, token_type_ids, attention_mask,
                 masked_lm_labels=None, next_sentence_label=None, deterministic=False,
                 progressive_layer_drop=False, pld_theta=None):
        cfg = self.config
        h, pooled, word_table = BertModel(cfg, name="bert")(
            input_ids, token_type_ids, attention_mask, deterministic,
            progressive_layer_drop=progressive_layer_drop, pld_theta=pld_theta,
        )

        # MLM head: transform + tied decoder (weight tying with word embeddings).
        t = nn.Dense(cfg.hidden_size, name="mlm_transform")(h)
        t = nn.gelu(t, approximate=False)
        t = nn.LayerNorm(name="mlm_ln")(t)
        mlm_bias = self.param("mlm_bias", nn.initializers.zeros, (cfg.vocab_size,))

        nsp_logits = nn.Dense(2, name="nsp_head")(pooled)

        if masked_lm_labels is None:
            mlm_logits = t @ word_table.T.astype(t.dtype) + mlm_bias.astype(t.dtype)
            return mlm_logits, nsp_logits

        # Training path: chunked CE never materializes the [B,S,V] logits —
        # the single largest transient of the step (ops/cross_entropy.py).
        mlm_loss = chunked_cross_entropy(
            t, word_table.T.astype(t.dtype), mlm_bias, masked_lm_labels,
            ignore_index=-1,
        )
        if next_sentence_label is not None:
            nsp_loss = cross_entropy(nsp_logits, next_sentence_label, ignore_index=-1)
        else:
            nsp_loss = 0.0
        return mlm_loss + nsp_loss


class BertForQuestionAnswering(nn.Module):
    """Extractive-QA (SQuAD) head: start/end span logits over the sequence.

    Parity with the reference's BingBertSquad fine-tune subject
    (``tests/unit/modeling.py`` BertForQuestionAnswering; driven by
    ``tests/model/BingBertSquad`` and the 1-bit Adam blog's fine-tune runs):
    a Dense(2) over the encoder output split into start/end logits; training
    loss is the mean of the two position cross-entropies with out-of-span
    positions clamped to the sequence length (reference clamps to
    ``ignored_index`` and ignores it in the loss).
    """

    config: BertConfig
    needs_rng = True

    @nn.compact
    def __call__(self, input_ids, token_type_ids, attention_mask,
                 start_positions=None, end_positions=None, deterministic=False,
                 progressive_layer_drop=False, pld_theta=None):
        cfg = self.config
        h, _, _ = BertModel(cfg, name="bert")(
            input_ids, token_type_ids, attention_mask, deterministic,
            progressive_layer_drop=progressive_layer_drop, pld_theta=pld_theta,
        )
        logits = nn.Dense(2, name="qa_outputs")(h)  # [B, S, 2]
        start_logits = logits[..., 0]
        end_logits = logits[..., 1]

        if start_positions is None:
            return start_logits, end_logits

        S = start_logits.shape[1]
        # positions outside [0, S) (answer truncated away) are ignored
        start_positions = jnp.where(
            (start_positions >= 0) & (start_positions < S), start_positions, -1
        )
        end_positions = jnp.where(
            (end_positions >= 0) & (end_positions < S), end_positions, -1
        )
        start_loss = cross_entropy(start_logits, start_positions, ignore_index=-1)
        end_loss = cross_entropy(end_logits, end_positions, ignore_index=-1)
        return (start_loss + end_loss) / 2.0


def init_bert(config, batch_size=2, seq_len=128, seed=0, dtype=jnp.float32):
    model = BertForPreTraining(config)
    ids = jnp.zeros((batch_size, seq_len), jnp.int32)
    labels = jnp.full((batch_size, seq_len), -1, jnp.int32)
    nsl = jnp.zeros((batch_size,), jnp.int32)
    params = model.init(
        {"params": jax.random.PRNGKey(seed), "dropout": jax.random.PRNGKey(seed + 1)},
        ids, ids, jnp.ones((batch_size, seq_len), jnp.int32), labels, nsl,
    )
    return model, params
